"""Prometheus text exposition format v0.0.4.

Pure string rendering over ``MetricsRegistry.collect()`` snapshots — no
sockets here (the admin endpoint serves the result; golden-string tests
cover the format without one). Reference:
https://prometheus.io/docs/instrumenting/exposition_formats/

Rules implemented:
- metric names must match ``[a-zA-Z_:][a-zA-Z0-9_:]*`` — invalid
  characters are replaced with ``_`` and a leading digit is prefixed;
- label names must match ``[a-zA-Z_][a-zA-Z0-9_]*`` (no colons);
- label VALUES may contain any UTF-8 but backslash, double-quote and
  newline must be escaped as ``\\\\``, ``\\"`` and ``\\n``;
- HELP text escapes backslash and newline (quotes are legal there);
- every family gets one ``# HELP`` + ``# TYPE`` block, and the body
  ends with a trailing newline;
- a histogram-bucket sample carrying an exemplar appends the
  OpenMetrics exemplar syntax ``# {trace_id="..."} value timestamp``,
  linking the aggregate bucket to one concrete traced request —
  but ONLY in the OpenMetrics rendering (``render(...,
  openmetrics=True)``; the classic v0.0.4 text parser reads the
  mid-line ``#`` as a malformed timestamp and fails the whole scrape,
  so the plain rendering never carries exemplar tails. The endpoints
  content-negotiate via ``negotiate_render``: scrapers that send
  ``Accept: application/openmetrics-text`` (a real Prometheus server
  does by default) get exemplars + the ``# EOF`` terminator.

The reverse direction lives here too: ``parse_samples`` reads an
exposition body back into (name, labels, value) rows and
``quantile_from_buckets`` reproduces PromQL's ``histogram_quantile``
interpolation — so the regression bench reads its p99 from the SAME
``/metrics`` surface operators scrape, not from bench-local counters.

The FLEET direction stacks on those: ``merge_histograms`` sums
per-replica cumulative ``le`` buckets into one fleet-wide histogram
(quantiles of the union, where quantiles-of-quantiles would lie) and
``merge_expositions`` merges whole per-replica scrape bodies into one
federated exposition — the router's ``/metrics``
(``keystone_tpu/fleet/``) is exactly that merge over its replicas.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from keystone_tpu.observability.registry import MetricFamily

logger = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_METRIC_INVALID = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_INVALID = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    name = _METRIC_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_name(name: str) -> str:
    name = _LABEL_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(value: str) -> str:
    # backslash FIRST or the other escapes' backslashes double-escape
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def escape_help(text: str) -> str:
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def format_le(bound: float) -> str:
    """A histogram bucket bound as its canonical ``le`` label value
    (what promtool emits: ``0.005``, ``1``, ``2.5``, ``+Inf``) so the
    same bound always produces the same series identity."""
    if math.isinf(bound):
        return "+Inf" if bound > 0 else "-Inf"
    if float(bound).is_integer():
        return str(int(bound))
    return repr(float(bound))


def format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def format_exemplar(exemplar) -> str:
    """The OpenMetrics exemplar tail of a bucket line:
    ``# {trace_id="..."} value timestamp``."""
    labelstr = ",".join(
        f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
        for k, v in exemplar.labels.items()
    )
    return (
        f" # {{{labelstr}}} {format_value(exemplar.value)}"
        f" {repr(float(exemplar.timestamp_s))}"
    )


def render_family(family: MetricFamily, exemplars: bool = False) -> str:
    name = sanitize_metric_name(family.name)
    lines = []
    if family.help:
        lines.append(f"# HELP {name} {escape_help(family.help)}")
    lines.append(f"# TYPE {name} {family.mtype}")
    for s in family.samples:
        if s.labels:
            labelstr = "{" + ",".join(
                f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                for k, v in s.labels.items()
            ) + "}"
        else:
            labelstr = ""
        line = f"{name}{s.suffix}{labelstr} {format_value(s.value)}"
        if exemplars and getattr(s, "exemplar", None) is not None:
            line += format_exemplar(s.exemplar)
        lines.append(line)
    return "\n".join(lines) + "\n"


def render(
    families: Iterable[MetricFamily], openmetrics: bool = False
) -> str:
    """Families (from ``MetricsRegistry.collect()``) -> the full
    exposition body. ``openmetrics=True`` switches to the (best-effort)
    OpenMetrics rendering: exemplar tails on histogram buckets plus the
    required ``# EOF`` terminator — never emitted in the classic
    v0.0.4 rendering, whose parsers reject mid-line ``#``."""
    body = "".join(
        render_family(f, exemplars=openmetrics)
        for f in sorted(families, key=lambda f: f.name)
    )
    if openmetrics:
        body += "# EOF\n"
    return body


def negotiate_render(
    families: Iterable[MetricFamily], accept: Optional[str]
) -> Tuple[str, str]:
    """Render for a scraper's ``Accept`` header -> ``(body,
    content_type)``: the OpenMetrics rendering (exemplars) when the
    header asks for ``application/openmetrics-text`` — a real
    Prometheus server does by default — else classic v0.0.4 text."""
    if accept and "application/openmetrics-text" in accept:
        return render(families, openmetrics=True), OPENMETRICS_CONTENT_TYPE
    return render(families), CONTENT_TYPE


# -- reading an exposition back (scrape-side helpers) ----------------------

_SAMPLE_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s#]+)"
)
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(value: str) -> str:
    return (
        value.replace(r"\n", "\n").replace(r"\"", '"').replace("\\\\", "\\")
    )


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    return float(raw)


def parse_samples(
    text: str,
) -> List[Tuple[str, Dict[str, str], float]]:
    """An exposition body -> ``(name, labels, value)`` rows. Comments
    (including exemplar tails — the regex stops at ``#``) are skipped;
    this is the scrape-side half of the format the renderer above
    emits, used by the regression bench to read ``/metrics``."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_LINE.match(line)
        if not m:
            continue
        labels = {
            k: _unescape_label_value(v)
            for k, v in _LABEL_PAIR.findall(m.group("labels") or "")
        }
        out.append((m.group("name"), labels, _parse_value(m.group("value"))))
    return out


def histogram_buckets(
    text: str, name: str, match_labels: Optional[Dict[str, str]] = None
) -> List[Tuple[float, float]]:
    """The cumulative ``(le, count)`` buckets of one histogram family
    in an exposition body, ``le``-ascending (``+Inf`` last), filtered
    to samples whose labels include ``match_labels``."""
    match_labels = match_labels or {}
    buckets = []
    for sample_name, labels, value in parse_samples(text):
        if sample_name != f"{name}_bucket" or "le" not in labels:
            continue
        if any(labels.get(k) != v for k, v in match_labels.items()):
            continue
        buckets.append((_parse_value(labels["le"]), value))
    return sorted(buckets, key=lambda b: b[0])


def merge_histograms(
    bucket_lists: Sequence[Sequence[Tuple[float, float]]],
) -> List[Tuple[float, float]]:
    """Sum per-replica cumulative ``(le, count)`` bucket lists (each
    the ``histogram_buckets`` output of one scrape) into one
    fleet-wide list — the SLO-federation primitive: cumulative ``le``
    buckets are the ONE latency representation that aggregates
    exactly across hosts, so ``quantile_from_buckets`` over the merge
    is the true fleet quantile (a quantile of per-host quantiles is
    not). Duplicate ``le`` entries within one list (several series of
    one family in a single scrape) collapse by summing first. Empty
    lists are skipped; all non-empty lists must agree on the bucket
    layout — summing cumulative counts across MISALIGNED bounds would
    fabricate a distribution, so a conflict raises ``ValueError``
    instead of merging anyway."""
    merged: Dict[float, float] = {}
    layout: Optional[Tuple[float, ...]] = None
    for buckets in bucket_lists:
        if not buckets:
            continue
        collapsed: Dict[float, float] = {}
        for le, count in buckets:
            collapsed[le] = collapsed.get(le, 0.0) + count
        bounds = tuple(sorted(collapsed))
        if layout is None:
            layout = bounds
        elif bounds != layout:
            raise ValueError(
                "conflicting histogram bucket layouts: "
                f"{[format_le(b) for b in layout]} vs "
                f"{[format_le(b) for b in bounds]}"
            )
        for le, count in collapsed.items():
            merged[le] = merged.get(le, 0.0) + count
    return sorted(merged.items(), key=lambda b: b[0])


_HELP_LINE = re.compile(r"^# HELP (\S+) (.*)$")
_TYPE_LINE = re.compile(r"^# TYPE (\S+) (\S+)$")
_SERIES_SUFFIXES = ("_bucket", "_count", "_sum")

# RATIO families: identical-label samples federate by MAX (worst
# case), never by sum — two replicas each at MFU 0.4 are not a fleet
# at MFU 0.8, and two burn rates of 0.9 summing to a fabricated 1.8
# would page on a healthy fleet. Everything else (counters, le
# buckets, additive gauges like queue depth / inflight / build-info
# ones) sums, which IS the fleet truth for those.
MERGE_MAX_FAMILIES = frozenset({
    "keystone_serving_mfu",
    "keystone_serving_padding_efficiency",
    "keystone_slo_burn_rate",
    "keystone_gateway_slo_pressure",
    # drift is a divergence score, not a quantity: the worst replica's
    # drift is the fleet's drift (two replicas each at 0.3 are not a
    # fleet at 0.6)
    "keystone_drift_score",
})


def merge_expositions(
    texts: Sequence[str], on_conflict: str = "raise"
) -> str:
    """Merge N exposition bodies (per-replica ``/metrics`` scrapes)
    into ONE federated body: samples with identical (name, labels)
    SUM across scrapes — exact for counters and cumulative ``le``
    buckets (replicas of one service share label sets, so their
    series line up), and deliberate for additive gauges (the
    fleet-summed queue depth / in-flight / ready count is the
    router's load truth; ``keystone_build_info`` sums to "replicas
    running this build"). RATIO families (``MERGE_MAX_FAMILIES``:
    MFU, padding efficiency, SLO burn/pressure) take the MAX instead
    — worst-case is the honest fleet aggregation for a ratio, a sum
    would fabricate values. Samples whose labels differ —
    distinctly-named gateways, per-lane engines — coexist untouched,
    one series each.

    ``# HELP``/``# TYPE`` metadata is carried from the first scrape
    that declares it; exemplar tails are comment syntax and do not
    survive the parse (the federated body is classic v0.0.4).

    A histogram family whose scrapes disagree on the ``le`` layout
    for one series cannot be summed honestly: with
    ``on_conflict="raise"`` (default) that's a ``ValueError``; with
    ``"drop"`` the whole family is dropped from the output and logged
    — a live router must keep exposing the families that DO merge."""
    if on_conflict not in ("raise", "drop"):
        raise ValueError(
            f"on_conflict must be 'raise' or 'drop', got {on_conflict!r}"
        )
    # (mtype, help) per family, first scrape that declares each wins
    meta: Dict[str, Tuple[Optional[str], Optional[str]]] = {}
    for text in texts:
        for line in text.splitlines():
            m = _HELP_LINE.match(line)
            if m:
                mtype, help_text = meta.get(m.group(1), (None, None))
                if help_text is None:
                    meta[m.group(1)] = (mtype, m.group(2))
                continue
            m = _TYPE_LINE.match(line)
            if m:
                mtype, help_text = meta.get(m.group(1), (None, None))
                if mtype is None:
                    meta[m.group(1)] = (m.group(2), help_text)
    composite = {
        name
        for name, (mtype, _) in meta.items()
        if mtype in ("histogram", "summary")
    }

    def family_of(name: str) -> str:
        for suffix in _SERIES_SUFFIXES:
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in composite:
                return base
        return name

    sums: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    layouts: Dict[Tuple[str, Tuple], Tuple] = {}
    conflicted: set = set()
    for text in texts:
        scrape_layout: Dict[Tuple[str, Tuple], List[float]] = {}
        for name, labels, value in parse_samples(text):
            key = (name, tuple(sorted(labels.items())))
            if name in MERGE_MAX_FAMILIES:
                prev = sums.get(key)
                sums[key] = value if prev is None else max(prev, value)
            else:
                sums[key] = sums.get(key, 0.0) + value
            if name.endswith("_bucket") and "le" in labels:
                base = (
                    family_of(name),
                    tuple(
                        sorted(
                            (k, v) for k, v in labels.items() if k != "le"
                        )
                    ),
                )
                scrape_layout.setdefault(base, []).append(
                    _parse_value(labels["le"])
                )
        for base, les in scrape_layout.items():
            sig = tuple(sorted(les))
            prev = layouts.get(base)
            if prev is None:
                layouts[base] = sig
            elif prev != sig:
                conflicted.add(base[0])
    if conflicted:
        detail = (
            "conflicting histogram bucket layouts across scrapes: "
            + ", ".join(sorted(conflicted))
        )
        if on_conflict == "raise":
            raise ValueError(detail)
        logger.warning("merge_expositions dropped %s", detail)
        sums = {
            key: v
            for key, v in sums.items()
            if family_of(key[0]) not in conflicted
        }

    by_family: Dict[str, List] = {}
    for (name, litems), value in sums.items():
        by_family.setdefault(family_of(name), []).append(
            (name, litems, value)
        )

    def sample_key(entry):
        name, litems, _ = entry
        return (
            name,
            tuple(
                (k, _parse_value(v)) if k == "le" else (k, v)
                for k, v in litems
            ),
        )

    lines: List[str] = []
    for family in sorted(by_family):
        mtype, help_text = meta.get(family, (None, None))
        if help_text is not None:
            lines.append(f"# HELP {family} {escape_help(help_text)}")
        if mtype is not None:
            lines.append(f"# TYPE {family} {mtype}")
        for name, litems, value in sorted(
            by_family[family], key=sample_key
        ):
            if litems:
                labelstr = "{" + ",".join(
                    f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                    for k, v in litems
                ) + "}"
            else:
                labelstr = ""
            lines.append(
                f"{sanitize_metric_name(name)}{labelstr} "
                f"{format_value(value)}"
            )
    return "\n".join(lines) + "\n" if lines else ""


def quantile_from_buckets(
    q: float, buckets: Sequence[Tuple[float, float]]
) -> Optional[float]:
    """PromQL ``histogram_quantile`` over cumulative ``(le, count)``
    buckets: linear interpolation inside the covering bucket, lower
    bound 0 for the first, and the highest finite bound when the
    quantile lands in ``+Inf``. None with no observations."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if math.isinf(le):
                return prev_le  # PromQL clamps to the last finite bound
            if count == prev_count:
                return le
            return prev_le + (le - prev_le) * (
                (rank - prev_count) / (count - prev_count)
            )
        prev_le, prev_count = le, count
    return prev_le
