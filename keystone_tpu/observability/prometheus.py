"""Prometheus text exposition format v0.0.4.

Pure string rendering over ``MetricsRegistry.collect()`` snapshots — no
sockets here (the admin endpoint serves the result; golden-string tests
cover the format without one). Reference:
https://prometheus.io/docs/instrumenting/exposition_formats/

Rules implemented:
- metric names must match ``[a-zA-Z_:][a-zA-Z0-9_:]*`` — invalid
  characters are replaced with ``_`` and a leading digit is prefixed;
- label names must match ``[a-zA-Z_][a-zA-Z0-9_]*`` (no colons);
- label VALUES may contain any UTF-8 but backslash, double-quote and
  newline must be escaped as ``\\\\``, ``\\"`` and ``\\n``;
- HELP text escapes backslash and newline (quotes are legal there);
- every family gets one ``# HELP`` + ``# TYPE`` block, and the body
  ends with a trailing newline;
- a histogram-bucket sample carrying an exemplar appends the
  OpenMetrics exemplar syntax ``# {trace_id="..."} value timestamp``,
  linking the aggregate bucket to one concrete traced request —
  but ONLY in the OpenMetrics rendering (``render(...,
  openmetrics=True)``; the classic v0.0.4 text parser reads the
  mid-line ``#`` as a malformed timestamp and fails the whole scrape,
  so the plain rendering never carries exemplar tails. The endpoints
  content-negotiate via ``negotiate_render``: scrapers that send
  ``Accept: application/openmetrics-text`` (a real Prometheus server
  does by default) get exemplars + the ``# EOF`` terminator.

The reverse direction lives here too: ``parse_samples`` reads an
exposition body back into (name, labels, value) rows and
``quantile_from_buckets`` reproduces PromQL's ``histogram_quantile``
interpolation — so the regression bench reads its p99 from the SAME
``/metrics`` surface operators scrape, not from bench-local counters.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from keystone_tpu.observability.registry import MetricFamily

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_METRIC_INVALID = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_INVALID = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    name = _METRIC_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_name(name: str) -> str:
    name = _LABEL_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(value: str) -> str:
    # backslash FIRST or the other escapes' backslashes double-escape
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def escape_help(text: str) -> str:
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def format_le(bound: float) -> str:
    """A histogram bucket bound as its canonical ``le`` label value
    (what promtool emits: ``0.005``, ``1``, ``2.5``, ``+Inf``) so the
    same bound always produces the same series identity."""
    if math.isinf(bound):
        return "+Inf" if bound > 0 else "-Inf"
    if float(bound).is_integer():
        return str(int(bound))
    return repr(float(bound))


def format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def format_exemplar(exemplar) -> str:
    """The OpenMetrics exemplar tail of a bucket line:
    ``# {trace_id="..."} value timestamp``."""
    labelstr = ",".join(
        f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
        for k, v in exemplar.labels.items()
    )
    return (
        f" # {{{labelstr}}} {format_value(exemplar.value)}"
        f" {repr(float(exemplar.timestamp_s))}"
    )


def render_family(family: MetricFamily, exemplars: bool = False) -> str:
    name = sanitize_metric_name(family.name)
    lines = []
    if family.help:
        lines.append(f"# HELP {name} {escape_help(family.help)}")
    lines.append(f"# TYPE {name} {family.mtype}")
    for s in family.samples:
        if s.labels:
            labelstr = "{" + ",".join(
                f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                for k, v in s.labels.items()
            ) + "}"
        else:
            labelstr = ""
        line = f"{name}{s.suffix}{labelstr} {format_value(s.value)}"
        if exemplars and getattr(s, "exemplar", None) is not None:
            line += format_exemplar(s.exemplar)
        lines.append(line)
    return "\n".join(lines) + "\n"


def render(
    families: Iterable[MetricFamily], openmetrics: bool = False
) -> str:
    """Families (from ``MetricsRegistry.collect()``) -> the full
    exposition body. ``openmetrics=True`` switches to the (best-effort)
    OpenMetrics rendering: exemplar tails on histogram buckets plus the
    required ``# EOF`` terminator — never emitted in the classic
    v0.0.4 rendering, whose parsers reject mid-line ``#``."""
    body = "".join(
        render_family(f, exemplars=openmetrics)
        for f in sorted(families, key=lambda f: f.name)
    )
    if openmetrics:
        body += "# EOF\n"
    return body


def negotiate_render(
    families: Iterable[MetricFamily], accept: Optional[str]
) -> Tuple[str, str]:
    """Render for a scraper's ``Accept`` header -> ``(body,
    content_type)``: the OpenMetrics rendering (exemplars) when the
    header asks for ``application/openmetrics-text`` — a real
    Prometheus server does by default — else classic v0.0.4 text."""
    if accept and "application/openmetrics-text" in accept:
        return render(families, openmetrics=True), OPENMETRICS_CONTENT_TYPE
    return render(families), CONTENT_TYPE


# -- reading an exposition back (scrape-side helpers) ----------------------

_SAMPLE_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s#]+)"
)
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(value: str) -> str:
    return (
        value.replace(r"\n", "\n").replace(r"\"", '"').replace("\\\\", "\\")
    )


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    return float(raw)


def parse_samples(
    text: str,
) -> List[Tuple[str, Dict[str, str], float]]:
    """An exposition body -> ``(name, labels, value)`` rows. Comments
    (including exemplar tails — the regex stops at ``#``) are skipped;
    this is the scrape-side half of the format the renderer above
    emits, used by the regression bench to read ``/metrics``."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_LINE.match(line)
        if not m:
            continue
        labels = {
            k: _unescape_label_value(v)
            for k, v in _LABEL_PAIR.findall(m.group("labels") or "")
        }
        out.append((m.group("name"), labels, _parse_value(m.group("value"))))
    return out


def histogram_buckets(
    text: str, name: str, match_labels: Optional[Dict[str, str]] = None
) -> List[Tuple[float, float]]:
    """The cumulative ``(le, count)`` buckets of one histogram family
    in an exposition body, ``le``-ascending (``+Inf`` last), filtered
    to samples whose labels include ``match_labels``."""
    match_labels = match_labels or {}
    buckets = []
    for sample_name, labels, value in parse_samples(text):
        if sample_name != f"{name}_bucket" or "le" not in labels:
            continue
        if any(labels.get(k) != v for k, v in match_labels.items()):
            continue
        buckets.append((_parse_value(labels["le"]), value))
    return sorted(buckets, key=lambda b: b[0])


def quantile_from_buckets(
    q: float, buckets: Sequence[Tuple[float, float]]
) -> Optional[float]:
    """PromQL ``histogram_quantile`` over cumulative ``(le, count)``
    buckets: linear interpolation inside the covering bucket, lower
    bound 0 for the first, and the highest finite bound when the
    quantile lands in ``+Inf``. None with no observations."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if math.isinf(le):
                return prev_le  # PromQL clamps to the last finite bound
            if count == prev_count:
                return le
            return prev_le + (le - prev_le) * (
                (rank - prev_count) / (count - prev_count)
            )
        prev_le, prev_count = le, count
    return prev_le
