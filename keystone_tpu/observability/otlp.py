"""OTLP/HTTP span export — stdlib only, off by default.

Maps our ``Span`` dataclass onto the OTLP JSON encoding
(``resourceSpans -> scopeSpans -> spans``; see
https://opentelemetry.io/docs/specs/otlp/#otlphttp) and POSTs batches
to a collector's ``/v1/traces`` over ``urllib`` — no SDK, nothing to
install. The exporter is a tracer *sink*: ``install()`` hooks
``Tracer.add_sink``, every finished span lands in a bounded in-memory
queue, and a background daemon thread flushes either when a batch fills
or on a timer. The serving hot path never blocks on the network: a
full queue drops the oldest spans (counted), a dead collector costs one
failed POST per flush interval (counted, logged at debug).

Wiring: ``python -m keystone_tpu --otlp-endpoint http://host:4318 ...``
builds one exporter over the global tracer; libraries construct
``OtlpSpanExporter`` directly. Span identity follows the wire format:
``trace_id`` is already 32 hex chars (see ``tracing.new_trace_id``);
our integer span ids render as 16-hex-char ids.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Any, Deque, Dict, List, Optional, Sequence

from keystone_tpu.loadgen import faults
from keystone_tpu.observability.tracing import Span, Tracer, get_tracer

logger = logging.getLogger(__name__)

TRACES_PATH = "/v1/traces"

# a span with no trace context still needs a valid non-zero trace id on
# the wire; OTLP forbids all-zeros, so orphans get a fixed sentinel
_ORPHAN_TRACE_ID = "f" * 32


def format_span_id(span_id: Optional[int]) -> str:
    """An integer span id as the 8-byte hex the OTLP wire expects."""
    return format((span_id or 0) & ((1 << 64) - 1), "016x")


def _attr_value(value: Any) -> Dict[str, Any]:
    # proto3 JSON mapping: int64 serializes as a STRING
    if isinstance(value, bool):
        return {"boolValue": value}
    if isinstance(value, int):
        return {"intValue": str(value)}
    if isinstance(value, float):
        return {"doubleValue": value}
    return {"stringValue": str(value)}


def _attrs(mapping: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [
        {"key": str(k), "value": _attr_value(v)} for k, v in mapping.items()
    ]


def span_to_otlp(span: Span) -> Dict[str, Any]:
    """One finished ``Span`` as an OTLP JSON span object."""
    start_ns = int(span.start_s * 1e9)
    end_ns = start_ns + int(span.duration_s * 1e9)
    out = {
        "traceId": span.trace_id or _ORPHAN_TRACE_ID,
        "spanId": format_span_id(span.span_id),
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": _attrs(
            {**span.attrs, "thread.id": span.thread_id}
        ),
    }
    if span.parent_id is not None:
        out["parentSpanId"] = format_span_id(span.parent_id)
    return out


def encode_spans(
    spans: Sequence[Span],
    service_name: str = "keystone-tpu",
    resource_attrs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """A batch of spans as the full OTLP/HTTP JSON request body.
    ``resource_attrs`` stamp the RESOURCE (the process), not the
    spans: the fleet's ``service.name`` + ``replica`` identity is
    what lets an external collector lay N processes' halves of one
    trace out as the same stitched topology the router's ``/debugz``
    renders."""
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": _attrs(
                        {
                            "service.name": service_name,
                            **(resource_attrs or {}),
                        }
                    )
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "keystone_tpu.observability"},
                        "spans": [span_to_otlp(s) for s in spans],
                    }
                ],
            }
        ]
    }


class OtlpSpanExporter:
    """Background-batching OTLP/HTTP exporter over one tracer."""

    def __init__(
        self,
        endpoint: str,
        *,
        service_name: str = "keystone-tpu",
        resource_attrs: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        batch_size: int = 256,
        flush_interval_s: float = 2.0,
        queue_capacity: int = 8192,
        timeout_s: float = 5.0,
        registry=None,
    ):
        endpoint = endpoint.rstrip("/")
        if not endpoint.endswith(TRACES_PATH):
            endpoint += TRACES_PATH
        self.endpoint = endpoint
        self.service_name = service_name
        self.resource_attrs = dict(resource_attrs or {})
        self.headers = dict(headers or {})
        self.batch_size = max(1, int(batch_size))
        self.flush_interval_s = float(flush_interval_s)
        self.queue_capacity = max(self.batch_size, int(queue_capacity))
        self.timeout_s = float(timeout_s)
        self._q: Deque[Span] = collections.deque()
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Event()  # set while the queue is empty
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        self._tracer: Optional[Tracer] = None
        if registry is None:
            from keystone_tpu.observability.registry import (
                get_global_registry,
            )

            registry = get_global_registry()
        self._spans = registry.counter(
            "keystone_otlp_spans_total",
            "spans handed to the OTLP exporter, by result",
            ("result",),
        )
        self._posts = registry.counter(
            "keystone_otlp_posts_total",
            "OTLP/HTTP export POSTs, by result",
            ("result",),
        )

    # -- intake (the tracer sink) ------------------------------------------

    def submit(self, span: Span) -> None:
        """Enqueue one finished span (never blocks; oldest spans drop
        when the collector cannot keep up)."""
        with self._lock:
            if len(self._q) >= self.queue_capacity:
                self._q.popleft()
                self._spans.inc(("dropped",))
            self._q.append(span)
            self._idle.clear()
            full = len(self._q) >= self.batch_size
        if full:
            self._kick.set()

    def install(self, tracer: Optional[Tracer] = None) -> "OtlpSpanExporter":
        """Hook the tracer's span sink and start the flush thread."""
        self._tracer = tracer if tracer is not None else get_tracer()
        self._tracer.add_sink(self.submit)
        return self.start()

    # -- flush loop --------------------------------------------------------

    def start(self) -> "OtlpSpanExporter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="keystone-otlp-export", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            self._kick.wait(self.flush_interval_s)
            self._kick.clear()
            self._flush_once()
            if self._stop.is_set():
                self._flush_once()  # final drain
                return

    def _flush_once(self) -> None:
        while True:
            with self._lock:
                batch = [
                    self._q.popleft()
                    for _ in range(min(len(self._q), self.batch_size))
                ]
            if not batch:
                # idle only once every popped batch has been POSTed,
                # so flush() returning means the collector has seen
                # everything submitted before the call
                with self._lock:
                    if not self._q:
                        self._idle.set()
                return
            self._post(batch)

    def _post(self, batch: List[Span]) -> None:
        # chaos point: black-hole the collector. Dropping BEFORE the
        # POST (counted under result="blackhole") proves the serving
        # path's telemetry isolation without paying connect/timeout
        # stalls on the flush thread — the experiment's question is
        # "does a dead collector cost traffic anything", and the
        # answer must be visible on /metrics, not in wall time.
        if faults.armed() and faults.fire(
            "otlp.export.blackhole", {"endpoint": self.endpoint}
        ) is not None:
            self._posts.inc(("blackhole",))
            self._spans.inc(("dropped",), by=len(batch))
            return
        body = json.dumps(
            encode_spans(
                batch, self.service_name,
                resource_attrs=self.resource_attrs,
            )
        ).encode("utf-8")
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json", **self.headers},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self._posts.inc(("ok",))
            self._spans.inc(("exported",), by=len(batch))
        except Exception as e:
            # the collector being down must cost the serving path
            # nothing: count, log quietly, drop the batch
            self._posts.inc(("error",))
            self._spans.inc(("dropped",), by=len(batch))
            logger.debug("OTLP export to %s failed: %s", self.endpoint, e)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the queue has fully drained (tests; shutdown)."""
        self._kick.set()
        return self._idle.wait(timeout_s)

    def shutdown(self, timeout_s: float = 10.0) -> None:
        """Unhook from the tracer, drain what is queued, stop."""
        if self._tracer is not None:
            self._tracer.remove_sink(self.submit)
            self._tracer = None
        if self._thread is not None:
            self._stop.set()
            self._kick.set()
            self._thread.join(timeout_s)
            self._thread = None

    def __enter__(self) -> "OtlpSpanExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


__all__ = [
    "OtlpSpanExporter",
    "encode_spans",
    "format_span_id",
    "span_to_otlp",
]
