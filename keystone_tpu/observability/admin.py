"""Admin HTTP endpoint: ``/metrics``, ``/varz``, ``/healthz``, ``/tracez``.

Built on the shared scaffolding in ``observability/httpd.py`` — a
stdlib ``http.server`` on a background daemon thread, nothing to
install, nothing running unless ``AdminServer.start()`` (or the
``--admin-port`` CLI flag) is called, zero overhead when off. Routes:

- ``GET /healthz``  -> ``ok`` (liveness probe; the gateway's
  ``/readyz`` is the READINESS signal — a draining process is alive
  but not ready)
- ``GET /metrics``  -> Prometheus text exposition v0.0.4 of the global
  (or injected) ``MetricsRegistry`` — scrape target for Prometheus /
  the autoscaler
- ``GET /varz``     -> the same registry as one JSON document
- ``GET /tracez``   -> recent spans from the tracer as JSON
  (``?format=chrome`` returns Chrome trace-event JSON for
  chrome://tracing / Perfetto; ``?n=100`` bounds the span count)

Binding defaults to localhost; ``port=0`` picks an ephemeral port
(``server.port`` reports the real one — tests and the smoke script use
that).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional
from urllib.parse import parse_qs, urlparse

from keystone_tpu.observability import prometheus
from keystone_tpu.observability.httpd import BackgroundServer, JsonHandler
from keystone_tpu.observability.registry import (
    MetricsRegistry,
    get_global_registry,
)
from keystone_tpu.observability.tracing import Tracer, get_tracer

logger = logging.getLogger(__name__)


class _Handler(JsonHandler):
    # routing state injected per-server via the `server` attribute
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        registry: MetricsRegistry = self.server.registry  # type: ignore
        tracer: Tracer = self.server.tracer  # type: ignore
        try:
            if url.path == "/healthz":
                self._send_text(200, "ok\n")
            elif url.path == "/metrics":
                body = prometheus.render(registry.collect())
                self._send(
                    200, body.encode("utf-8"), prometheus.CONTENT_TYPE
                )
            elif url.path == "/varz":
                self._send_json(registry.varz(), indent=1)
            elif url.path == "/tracez":
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "chrome":
                    self._send_json(tracer.to_chrome_trace(), indent=1)
                else:
                    n = int(q["n"][0]) if "n" in q else None
                    self._send_json(
                        {
                            "enabled": tracer.enabled,
                            "spans": [
                                s.to_dict() for s in tracer.recent(n)
                            ],
                        },
                        indent=1,
                    )
            else:
                self._send_text(
                    404,
                    "not found; try /metrics /varz /healthz /tracez\n",
                )
        except Exception as e:  # a broken collector must not kill the
            # serving thread — report it to the scraper instead
            logger.exception("admin endpoint error for %s", self.path)
            self._send_text(500, f"error: {e}\n")


class AdminServer(BackgroundServer):
    """The background admin endpoint. ``start()`` binds and serves on a
    daemon thread; ``stop()`` shuts down cleanly. Usable as a context
    manager."""

    handler_cls = _Handler
    thread_name = "keystone-admin-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
    ):
        super().__init__(port=port, host=host)
        self.registry = registry if registry is not None else get_global_registry()
        self.tracer = tracer if tracer is not None else get_tracer()

    def _configure(self, httpd) -> None:
        httpd.registry = self.registry
        httpd.tracer = self.tracer


_server: Optional[AdminServer] = None
_server_lock = threading.Lock()


def start_admin_server(
    port: int = 0, host: str = "127.0.0.1", **kwargs
) -> AdminServer:
    """Start (or return) the process-global admin endpoint — what the
    ``--admin-port`` CLI flag calls."""
    global _server
    with _server_lock:
        if _server is None:
            _server = AdminServer(port=port, host=host, **kwargs).start()
        return _server


def stop_admin_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
