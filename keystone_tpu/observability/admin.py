"""Admin HTTP endpoint: ``/metrics``, ``/varz``, ``/healthz``, ``/tracez``.

A stdlib ``http.server`` on a background daemon thread — nothing to
install, nothing running unless ``AdminServer.start()`` (or the
``--admin-port`` CLI flag) is called, zero overhead when off. Routes:

- ``GET /healthz``  -> ``ok`` (liveness probe)
- ``GET /metrics``  -> Prometheus text exposition v0.0.4 of the global
  (or injected) ``MetricsRegistry`` — scrape target for Prometheus /
  the autoscaler
- ``GET /varz``     -> the same registry as one JSON document
- ``GET /tracez``   -> recent spans from the tracer as JSON
  (``?format=chrome`` returns Chrome trace-event JSON for
  chrome://tracing / Perfetto; ``?n=100`` bounds the span count)

Binding defaults to localhost; ``port=0`` picks an ephemeral port
(``server.port`` reports the real one — tests and the smoke script use
that).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from keystone_tpu.observability import prometheus
from keystone_tpu.observability.registry import (
    MetricsRegistry,
    get_global_registry,
)
from keystone_tpu.observability.tracing import Tracer, get_tracer

logger = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    # injected per-server via the `server` attribute
    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, code: int = 200) -> None:
        self._send(
            code,
            json.dumps(obj, indent=1, default=str).encode("utf-8"),
            "application/json; charset=utf-8",
        )

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        registry: MetricsRegistry = self.server.registry  # type: ignore
        tracer: Tracer = self.server.tracer  # type: ignore
        try:
            if url.path == "/healthz":
                self._send(200, b"ok\n", "text/plain; charset=utf-8")
            elif url.path == "/metrics":
                body = prometheus.render(registry.collect())
                self._send(
                    200, body.encode("utf-8"), prometheus.CONTENT_TYPE
                )
            elif url.path == "/varz":
                self._send_json(registry.varz())
            elif url.path == "/tracez":
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "chrome":
                    self._send_json(tracer.to_chrome_trace())
                else:
                    n = int(q["n"][0]) if "n" in q else None
                    self._send_json(
                        {
                            "enabled": tracer.enabled,
                            "spans": [
                                s.to_dict() for s in tracer.recent(n)
                            ],
                        }
                    )
            else:
                self._send(
                    404,
                    b"not found; try /metrics /varz /healthz /tracez\n",
                    "text/plain; charset=utf-8",
                )
        except Exception as e:  # a broken collector must not kill the
            # serving thread — report it to the scraper instead
            logger.exception("admin endpoint error for %s", self.path)
            self._send(
                500, f"error: {e}\n".encode("utf-8"),
                "text/plain; charset=utf-8",
            )

    def log_message(self, format, *args):  # quiet: scrapes every few
        logger.debug("admin: " + format, *args)  # seconds otherwise spam


class AdminServer:
    """The background admin endpoint. ``start()`` binds and serves on a
    daemon thread; ``stop()`` shuts down cleanly. Usable as a context
    manager."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
    ):
        self._requested = (host, port)
        self.registry = registry if registry is not None else get_global_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("AdminServer not started")
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._requested[0]

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def start(self) -> "AdminServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer(self._requested, _Handler)
        httpd.daemon_threads = True
        httpd.registry = self.registry  # type: ignore[attr-defined]
        httpd.tracer = self.tracer  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name="keystone-admin-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("admin endpoint serving on %s", self.url())
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "AdminServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_server: Optional[AdminServer] = None
_server_lock = threading.Lock()


def start_admin_server(
    port: int = 0, host: str = "127.0.0.1", **kwargs
) -> AdminServer:
    """Start (or return) the process-global admin endpoint — what the
    ``--admin-port`` CLI flag calls."""
    global _server
    with _server_lock:
        if _server is None:
            _server = AdminServer(port=port, host=host, **kwargs).start()
        return _server


def stop_admin_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
