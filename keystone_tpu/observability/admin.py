"""Admin HTTP endpoint: ``/metrics``, ``/varz``, ``/healthz``,
``/tracez``, ``/slz``, ``/debugz``, ``/profilez``.

Built on the shared scaffolding in ``observability/httpd.py`` — a
stdlib ``http.server`` on a background daemon thread, nothing to
install, nothing running unless ``AdminServer.start()`` (or the
``--admin-port`` CLI flag) is called, zero overhead when off. Routes:

- ``GET /healthz``  -> ``ok`` (liveness probe; the gateway's
  ``/readyz`` is the READINESS signal — a draining process is alive
  but not ready)
- ``GET /metrics``  -> Prometheus text exposition v0.0.4 of the global
  (or injected) ``MetricsRegistry`` — scrape target for Prometheus /
  the autoscaler; histogram buckets may carry OpenMetrics exemplars
- ``GET /varz``     -> the same registry as one JSON document, plus a
  ``build`` block (git SHA, start time/uptime, jax version, device
  kind) so two scrapes of different binaries are distinguishable
- ``GET /tracez``   -> recent spans from the tracer as JSON
  (``?format=chrome`` returns Chrome trace-event JSON for
  chrome://tracing / Perfetto; ``?n=100`` bounds the span count)
- ``GET /slz``      -> every live ``SloMonitor``'s objectives with
  fast/slow-window burn rates and breach verdicts
- ``GET /debugz``   -> the flight recorders' tail-sampled forensic
  records (``?trace_id=`` filters to one request;
  ``&format=chrome`` dumps that request as a Chrome trace)
- ``GET /profilez`` -> arm a ``jax.profiler`` trace around the next
  ``?seconds=N`` of live traffic and list the capture directory
  (Perfetto/XProf); one capture at a time — concurrent requests get
  409 (``observability/profilez.py``)
- ``GET /attributionz`` -> the per-model device-cost ledger document
  rebuilt from this registry's ``keystone_attr_*`` samples
  (``observability/attribution.py``); empty when no ledger publishes
  here

Starting the endpoint also starts the device-truth side of the plane:
the detected device table rides in ``/varz``'s build block and as the
``keystone_device_info`` gauge (cached one-time — no per-scrape
``jax.devices()``), and the endpoint's ``DeviceMemorySampler`` publishes
per-device in-use/peak/limit memory gauges
(``observability/device.py``).

Binding defaults to localhost; ``port=0`` picks an ephemeral port
(``server.port`` reports the real one — tests and the smoke script use
that).
"""

from __future__ import annotations

import logging
import os
import platform
import threading
import time
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from keystone_tpu.observability import (
    device as device_obs,
    flight,
    profilez,
    prometheus,
    slo,
)
from keystone_tpu.observability.httpd import BackgroundServer, JsonHandler
from keystone_tpu.observability.registry import (
    MetricsRegistry,
    get_global_registry,
)
from keystone_tpu.observability.tracing import (
    Tracer,
    get_tracer,
    tracez_document,
)

logger = logging.getLogger(__name__)

_PROCESS_START_S = time.time()
_git_sha_cache: Optional[str] = None
_git_sha_read = False


def _git_sha() -> Optional[str]:
    """Best-effort repo SHA of the running checkout (one subprocess,
    cached; None outside a git checkout or without git)."""
    global _git_sha_cache, _git_sha_read
    if _git_sha_read:
        return _git_sha_cache
    _git_sha_read = True
    try:
        import subprocess

        out = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
        if out.returncode == 0:
            _git_sha_cache = out.stdout.strip() or None
    except Exception:
        _git_sha_cache = None
    return _git_sha_cache


_build_static: Optional[Dict] = None
_build_static_lock = threading.Lock()


def _static_build_info() -> Dict:
    """The immutable part of the identity, computed ONCE: every
    ``/metrics`` scrape and ``/varz`` hit reads ``build_info``, and
    ``jax.devices()`` can trigger full backend initialization — a
    multi-second side effect a monitoring poll must pay at most once,
    not per scrape."""
    global _build_static
    with _build_static_lock:
        if _build_static is None:
            info: Dict = {
                "git_sha": _git_sha(),
                "start_time_unix_s": _PROCESS_START_S,
                "pid": os.getpid(),
                "python_version": platform.python_version(),
                "jax_version": None,
                "device_kind": None,
            }
            try:  # best-effort: jax is a hard dep, but the backend
                import jax  # may fail to init on this host

                info["jax_version"] = jax.__version__
                devices = jax.devices()
                if devices:
                    info["device_kind"] = devices[0].device_kind
                    info["device_count"] = len(devices)
            except Exception:
                pass
            _build_static = info
        return dict(_build_static)


def build_info() -> Dict:
    """Who/what this process is: enough identity that two ``/varz``
    scrapes of different binaries are distinguishable — plus the
    detected device table (kind, count, peaks, HBM limit; cached
    one-time exactly like the rest of the block)."""
    info = _static_build_info()
    info["uptime_s"] = round(time.time() - _PROCESS_START_S, 3)
    info["devices"] = device_obs.device_table()
    try:
        # late import: observability must not import serving at module
        # load (serving imports observability); the block says whether
        # THIS process can cold-start from serialized executables —
        # {"dir": None} when no store is configured
        from keystone_tpu.serving import aot

        info["aot_cache"] = aot.status()
    except Exception:
        pass
    return info


def register_build_metrics(registry: MetricsRegistry) -> None:
    """Export identity onto the scrape surface: the standard
    ``_info``-style constant gauge plus process start time."""
    def info_cells():
        info = build_info()
        key = (
            str(info.get("git_sha") or "unknown"),
            str(info.get("jax_version") or "unknown"),
            str(info.get("device_kind") or "unknown"),
        )
        return {key: 1.0}

    registry.gauge_func(
        "keystone_build_info",
        info_cells,
        "constant 1 labeled with the build/runtime identity",
        ("git_sha", "jax_version", "device_kind"),
    )
    registry.gauge_func(
        "keystone_process_start_time_seconds",
        lambda: _PROCESS_START_S,
        "process start time, unix epoch seconds",
    )
    device_obs.register_device_metrics(registry)


class _Handler(JsonHandler):
    # routing state injected per-server via the `server` attribute
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        registry: MetricsRegistry = self.server.registry  # type: ignore
        tracer: Tracer = self.server.tracer  # type: ignore
        try:
            if url.path == "/healthz":
                self._send_text(200, "ok\n")
            elif url.path == "/metrics":
                body, ctype = prometheus.negotiate_render(
                    registry.collect(), self.headers.get("Accept")
                )
                self._send(200, body.encode("utf-8"), ctype)
            elif url.path == "/varz":
                doc = registry.varz()
                doc["build"] = build_info()
                self._send_json(doc, indent=1)
            elif url.path == "/tracez":
                q = parse_qs(url.query)
                self._send_json(
                    tracez_document(
                        tracer,
                        q.get("format", [""])[0],
                        q["n"][0] if "n" in q else None,
                    ),
                    indent=1,
                )
            elif url.path == "/slz":
                self._send_json(slo.slz_status(), indent=1)
            elif url.path == "/debugz":
                q = parse_qs(url.query)
                code, doc = flight.debugz_document(
                    q.get("trace_id", [None])[0],
                    q.get("format", [""])[0],
                )
                self._send_json(doc, code=code, indent=1)
            elif url.path == "/profilez":
                q = parse_qs(url.query)
                code, doc = profilez.profilez_document(
                    q.get("seconds", [None])[0]
                )
                self._send_json(doc, code=code, indent=1)
            elif url.path == "/attributionz":
                # the admin endpoint holds a registry, not a zoo, so
                # the ledger document is rebuilt from this registry's
                # own keystone_attr_* samples — the same reconstruction
                # the fleet router applies to its federated scrape
                from keystone_tpu.observability.attribution import (
                    attribution_from_samples,
                )

                samples = prometheus.parse_samples(
                    prometheus.render(registry.collect())
                )
                self._send_json(
                    attribution_from_samples(samples), indent=1
                )
            else:
                self._send_text(
                    404,
                    "not found; try /metrics /varz /healthz /tracez "
                    "/slz /debugz /profilez /attributionz\n",
                )
        except Exception as e:  # a broken collector must not kill the
            # serving thread — report it to the scraper instead
            logger.exception("admin endpoint error for %s", self.path)
            self._send_text(500, f"error: {e}\n")


class AdminServer(BackgroundServer, device_obs.MemorySamplerHost):
    """The background admin endpoint. ``start()`` binds and serves on a
    daemon thread; ``stop()`` shuts down cleanly. Usable as a context
    manager."""

    handler_cls = _Handler
    thread_name = "keystone-admin-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
    ):
        super().__init__(port=port, host=host)
        self.registry = registry if registry is not None else get_global_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        register_build_metrics(self.registry)

    def _configure(self, httpd) -> None:
        httpd.registry = self.registry
        httpd.tracer = self.tracer

    def start(self) -> "AdminServer":
        # device memory telemetry rides with the endpoint: the sampler
        # publishes per-device in-use/peak/limit gauges onto the same
        # registry this endpoint scrapes (refcounted — a gateway in the
        # same process shares the thread, not a second one)
        super().start()
        self._start_memory_sampler()
        return self

    def stop(self) -> None:
        self._stop_memory_sampler()
        super().stop()


_server: Optional[AdminServer] = None
_server_lock = threading.Lock()


def start_admin_server(
    port: int = 0, host: str = "127.0.0.1", **kwargs
) -> AdminServer:
    """Start (or return) the process-global admin endpoint — what the
    ``--admin-port`` CLI flag calls."""
    global _server
    with _server_lock:
        if _server is None:
            _server = AdminServer(port=port, host=host, **kwargs).start()
        return _server


def stop_admin_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
