"""Device truth: what the chip is, what it peaks at, what it holds.

Everything else in the observability plane measures *host wall time*;
this module is the device-side half of the cost model the ROADMAP's
"as fast as the hardware allows" needs:

- **Detection** (``device_table``): the local device set — kind,
  platform, count, peak dense FLOP/s and HBM bandwidth from a
  per-device-kind table (overridable via ``KEYSTONE_PEAK_FLOPS`` /
  ``KEYSTONE_PEAK_MEMBW_GBPS`` for hardware the table doesn't know),
  and the HBM byte limit where the runtime reports one. Computed ONCE
  — ``jax.devices()`` can trigger full backend init, a cost no
  ``/metrics`` scrape should ever pay — and exported as the standard
  constant-1 ``keystone_device_info`` gauge.
- **Cost-model extraction** (``compiled_cost_model``): normalize
  ``jax.jit(...).lower().compile().cost_analysis()`` (a dict, a
  list-wrapped dict, or None depending on backend) and
  ``memory_analysis()`` into one flat ``{flops, bytes_accessed,
  temp_bytes, ...}`` dict. Best-effort by contract: a backend that
  reports nothing yields ``{}``, never an exception — the CPU CI
  degrades to *absent* series, not zeros.
- **Memory telemetry** (``device_memory_stats``,
  ``DeviceMemorySampler``): THE one None-guarded ``memory_stats()``
  probe (``ops/learning/weighted_ls.py`` and ``workflow/auto_cache.py``
  route through it instead of hand-rolling their own), plus a sampler
  thread publishing per-device in-use / peak / limit gauges on the
  registry. CPU backends report no device stats; the sampler falls
  back to one host-RAM series (``device="host"``) so a CPU deployment
  still has a memory surface.

``ServingMetrics`` combines the peaks with each engine's per-bucket
compiled cost model into the rolling MFU gauge and the
compute-vs-bandwidth roofline classification (serving/metrics.py).
"""

from __future__ import annotations

import logging
import os
import re
import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# Peak DENSE matmul throughput per chip (bf16/fp16 where the part has
# it, else f32) and peak HBM bandwidth, keyed by a case-insensitive
# word-bounded substring of ``device.device_kind``. First match wins,
# most specific entries first; the word boundary keeps "l4" from
# claiming an L40S (unknown parts stay (None, None) — absent series
# beat fabricated peaks). Vendor datasheet numbers — the MFU
# denominator, same convention as the PaLM MFU reports (model FLOPs
# over peak FLOPs).
PEAK_TABLE: Tuple[Tuple[str, float, float], ...] = (
    # (kind substring, peak FLOP/s, peak HBM bytes/s)
    ("tpu v6e", 918e12, 1640e9),     # Trillium; some runtimes say "v6e"
    ("tpu v6", 918e12, 1640e9),      # ... others "TPU v6 lite"
    ("tpu v5p", 459e12, 2765e9),
    ("tpu v5 lite", 197e12, 819e9),  # v5e reports "TPU v5 lite"
    ("tpu v5e", 197e12, 819e9),
    ("tpu v5", 459e12, 2765e9),
    ("tpu v4", 275e12, 1200e9),
    ("tpu v3", 123e12, 900e9),
    ("tpu v2", 45e12, 700e9),
    ("h200", 989e12, 4800e9),
    ("h100", 989e12, 3350e9),
    ("a100", 312e12, 2039e9),
    ("l4", 121e12, 300e9),
    ("v100", 125e12, 900e9),
    ("t4", 65e12, 320e9),
)

_ENV_PEAK_FLOPS = "KEYSTONE_PEAK_FLOPS"
_ENV_PEAK_MEMBW = "KEYSTONE_PEAK_MEMBW_GBPS"


def peaks_for(device_kind: Optional[str]) -> Tuple[Optional[float], Optional[float]]:
    """``(peak_flops, peak_membw_bytes_per_s)`` for a device kind, from
    the env overrides first, then the table; ``(None, None)`` for
    hardware neither knows (MFU/roofline series stay absent)."""
    flops = membw = None
    env_flops = os.environ.get(_ENV_PEAK_FLOPS)
    if env_flops:
        try:
            flops = float(env_flops)
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r",
                           _ENV_PEAK_FLOPS, env_flops)
    env_membw = os.environ.get(_ENV_PEAK_MEMBW)
    if env_membw:
        try:
            membw = float(env_membw) * 1e9
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r",
                           _ENV_PEAK_MEMBW, env_membw)
    if flops is not None and membw is not None:
        return flops, membw
    kind = (device_kind or "").lower()
    for sub, table_flops, table_membw in PEAK_TABLE:
        if re.search(rf"\b{re.escape(sub)}\b", kind):
            return (flops if flops is not None else table_flops,
                    membw if membw is not None else table_membw)
    return flops, membw


def device_memory_stats(device: Any = None) -> Optional[Dict[str, int]]:
    """THE ``memory_stats()`` probe: one code path, one None-guard.
    Returns the runtime's stats dict (``bytes_in_use`` /
    ``peak_bytes_in_use`` / ``bytes_limit`` where the backend reports
    them) or None — backends without stats (CPU, the axon tunnel) and
    uninitializable backends both land on None, never an exception."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return stats or None


def host_memory_stats() -> Optional[Dict[str, int]]:
    """Host-RAM analogue of ``device_memory_stats`` for backends with
    no device allocator stats: limit = MemTotal, in-use derived from
    MemAvailable, peak = this process's max RSS."""
    stats: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            fields = {}
            for line in f:
                parts = line.split()
                if parts and parts[0].rstrip(":") in (
                    "MemTotal", "MemAvailable"
                ):
                    fields[parts[0].rstrip(":")] = int(parts[1]) * 1024
        if "MemTotal" in fields:
            stats["bytes_limit"] = fields["MemTotal"]
            if "MemAvailable" in fields:
                stats["bytes_in_use"] = (
                    fields["MemTotal"] - fields["MemAvailable"]
                )
    except OSError:
        pass
    try:
        import resource

        # ru_maxrss is kilobytes on Linux but bytes on macOS
        scale = 1 if sys.platform == "darwin" else 1024
        stats["peak_bytes_in_use"] = (
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
        )
    except Exception:
        pass
    return stats or None


# -- the one-time detected device table ------------------------------------

_table: Optional[List[Dict[str, Any]]] = None
_table_lock = threading.Lock()


def device_table() -> List[Dict[str, Any]]:
    """The local device set as one row per device KIND (kind, platform,
    count, peak FLOP/s, peak HBM bandwidth, HBM byte limit). Computed
    once — ``jax.devices()`` may initialize the whole backend, which a
    per-scrape path must never pay — and safe on hosts where the
    backend fails to init (empty table)."""
    global _table
    with _table_lock:
        if _table is not None:
            return [dict(row) for row in _table]
        rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
        try:
            import jax

            for dev in jax.devices():
                key = (dev.device_kind, dev.platform)
                row = rows.get(key)
                if row is None:
                    flops, membw = peaks_for(dev.device_kind)
                    stats = device_memory_stats(dev)
                    row = rows[key] = {
                        "kind": dev.device_kind,
                        "platform": dev.platform,
                        "count": 0,
                        "peak_flops": flops,
                        "peak_membw_bytes_per_s": membw,
                        "hbm_bytes_limit": (
                            stats.get("bytes_limit") if stats else None
                        ),
                    }
                row["count"] += 1
        except Exception:
            logger.exception("device detection failed; empty table")
        _table = list(rows.values())
        return [dict(row) for row in _table]


def reset_device_table() -> None:
    """Drop the cached table (tests monkeypatching the backend)."""
    global _table
    with _table_lock:
        _table = None


_ENV_CHIP_HBM = "KEYSTONE_CHIP_HBM_BYTES"


def chip_hbm_bytes() -> Optional[int]:
    """The per-chip parameter budget the zoo placement optimizer plans
    against: ``$KEYSTONE_CHIP_HBM_BYTES`` when set (CPU CI and hosts
    whose allocator reports no limit), else the smallest
    ``hbm_bytes_limit`` the runtime reports across device kinds (a
    heterogeneous host must plan for its tightest chip). None when
    neither source knows — callers then skip budget-driven decisions
    rather than plan against a fabricated number."""
    env = os.environ.get(_ENV_CHIP_HBM)
    if env:
        try:
            return int(float(env))
        except ValueError:
            logger.warning("ignoring unparseable %s=%r",
                           _ENV_CHIP_HBM, env)
    limits = [
        row["hbm_bytes_limit"] for row in device_table()
        if row.get("hbm_bytes_limit")
    ]
    return min(limits) if limits else None


def register_device_metrics(registry) -> None:
    """Export the detected table as the standard constant-1 info gauge:
    ``keystone_device_info{kind, platform, count, peak_flops}``.
    Table detection is the one-time cost; every scrape reads the
    cache."""
    def cells():
        return {
            (
                row["kind"],
                row["platform"],
                str(row["count"]),
                str(row["peak_flops"] or "unknown"),
            ): 1.0
            for row in device_table()
        }

    registry.gauge_func(
        "keystone_device_info",
        cells,
        "constant 1 labeled with the detected device kind/count/peaks",
        ("kind", "platform", "count", "peak_flops"),
    )


# -- compiled-program cost extraction --------------------------------------

# cost_analysis keys -> our flat names
_COST_KEYS = (("flops", "flops"), ("bytes accessed", "bytes_accessed"),
              ("transcendentals", "transcendentals"))
_MEMORY_ATTRS = (
    ("temp_size_in_bytes", "temp_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
)


def compiled_cost_model(compiled: Any) -> Dict[str, float]:
    """Normalize one XLA program's analyses into a flat ``{flops,
    bytes_accessed, temp_bytes, ...}`` dict. Accepts a
    ``jax.stages.Lowered`` (``cost_analysis`` without paying an XLA
    compile; no ``memory_analysis``) or a ``Compiled`` (both).
    Backends differ: ``cost_analysis()`` is a dict, a list-wrapped
    dict, or None/raising — any shape that carries nothing yields
    ``{}`` (absent series, the graceful-degradation contract), never
    an exception."""
    model: Dict[str, float] = {}
    cost = None
    try:
        cost = compiled.cost_analysis()
    except Exception:
        cost = None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if isinstance(cost, dict):
        for src, dst in _COST_KEYS:
            v = cost.get(src)
            if isinstance(v, (int, float)) and v >= 0:
                model[dst] = float(v)
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for attr, dst in _MEMORY_ATTRS:
            v = getattr(mem, attr, None)
            if isinstance(v, (int, float)) and v >= 0:
                model[dst] = float(v)
    return model


# -- the memory sampler thread ---------------------------------------------

# the memory_stats keys the sampler exports, as their gauge `stat` label
_SAMPLED_STATS = (
    ("bytes_in_use", "in_use"),
    ("peak_bytes_in_use", "peak"),
    ("bytes_limit", "limit"),
)


class DeviceMemorySampler:
    """Background thread publishing ``device.memory_stats()`` as
    ``keystone_device_memory_bytes{device, kind, stat}`` gauges.

    Devices without allocator stats contribute no series (absent, not
    zero); when NO device reports stats and the platform is CPU, one
    host-RAM series set (``device="host"``, ``kind="host-ram"``)
    publishes instead so a CPU deployment still has a memory surface.
    ``sample_once()`` is the unit-testable core; ``start()`` samples
    immediately, then every ``interval_s`` on a daemon thread."""

    def __init__(
        self,
        registry=None,
        interval_s: float = 10.0,
        devices: Optional[Sequence[Any]] = None,
    ):
        from keystone_tpu.observability.registry import get_global_registry

        self.registry = (
            registry if registry is not None else get_global_registry()
        )
        self.interval_s = float(interval_s)
        self._devices = devices
        self._gauge = self.registry.gauge(
            "keystone_device_memory_bytes",
            "device allocator memory (absent on backends without "
            "stats; device=\"host\" rows are host RAM)",
            ("device", "kind", "stat"),
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _device_list(self) -> Sequence[Any]:
        if self._devices is not None:
            return self._devices
        try:
            import jax

            return jax.devices()
        except Exception:
            return ()

    def sample_once(self) -> int:
        """Publish one sample of every device; returns the number of
        device series sets written (0 = no device reported stats)."""
        published = 0
        devices = self._device_list()
        # an EMPTY device list (backend failed to init) must stay an
        # absent family, not masquerade as a healthy CPU host
        all_cpu = bool(devices)
        for i, dev in enumerate(devices):
            if getattr(dev, "platform", None) != "cpu":
                all_cpu = False
            stats = device_memory_stats(dev)
            if not stats:
                continue
            published += 1
            kind = getattr(dev, "device_kind", "unknown")
            for key, stat in _SAMPLED_STATS:
                if key in stats:
                    self._gauge.set(
                        float(stats[key]), (str(i), kind, stat)
                    )
        if not published and all_cpu:
            host = host_memory_stats()
            if host:
                for key, stat in _SAMPLED_STATS:
                    if key in host:
                        self._gauge.set(
                            float(host[key]), ("host", "host-ram", stat)
                        )
        return published

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                logger.exception("device memory sample failed")

    def start(self) -> "DeviceMemorySampler":
        if self._thread is not None:
            return self
        self._stop.clear()  # restartable (server stop/start cycles)
        try:
            self.sample_once()
        except Exception:
            logger.exception("initial device memory sample failed")
        self._thread = threading.Thread(
            target=self._loop, name="keystone-device-memory", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# Refcounted per-registry sampler sharing: an admin endpoint and a
# gateway frontend in one process both want the memory families on the
# (usually shared) global registry — one sampler thread per registry,
# not one per server.
_samplers_lock = threading.Lock()
_samplers: Dict[int, List] = {}  # id(registry) -> [sampler, refcount]


def acquire_memory_sampler(
    registry=None, interval_s: float = 10.0
) -> DeviceMemorySampler:
    """Start (or share) the memory sampler for a registry. Each
    ``acquire`` must be paired with one ``release_memory_sampler`` —
    the underlying thread stops when the last holder releases. When the
    registry already has a sampler, the tightest requested interval
    wins (the loop re-reads ``interval_s`` every wait)."""
    from keystone_tpu.observability.registry import get_global_registry

    registry = registry if registry is not None else get_global_registry()
    with _samplers_lock:
        entry = _samplers.get(id(registry))
        if entry is None:
            entry = _samplers[id(registry)] = [
                DeviceMemorySampler(
                    registry=registry, interval_s=interval_s
                ).start(),
                0,
            ]
        elif interval_s < entry[0].interval_s:
            entry[0].interval_s = float(interval_s)
        entry[1] += 1
        return entry[0]


def release_memory_sampler(sampler: DeviceMemorySampler) -> None:
    with _samplers_lock:
        entry = _samplers.get(id(sampler.registry))
        if entry is None or entry[0] is not sampler:
            sampler.stop()  # not shared (constructed directly)
            return
        entry[1] -= 1
        if entry[1] <= 0:
            del _samplers[id(sampler.registry)]
            sampler.stop()


class MemorySamplerHost:
    """Mixin for endpoint servers with a ``registry``: hold the shared
    per-registry memory sampler between ``_start_memory_sampler()``
    (call after the server comes up) and ``_stop_memory_sampler()``
    (call before it goes down). Both are idempotent."""

    _mem_sampler: Optional[DeviceMemorySampler] = None

    def _start_memory_sampler(self) -> None:
        if self._mem_sampler is None:
            self._mem_sampler = acquire_memory_sampler(
                registry=self.registry
            )

    def _stop_memory_sampler(self) -> None:
        if self._mem_sampler is not None:
            release_memory_sampler(self._mem_sampler)
            self._mem_sampler = None


__all__ = [
    "DeviceMemorySampler",
    "MemorySamplerHost",
    "acquire_memory_sampler",
    "compiled_cost_model",
    "device_memory_stats",
    "device_table",
    "host_memory_stats",
    "peaks_for",
    "register_device_metrics",
    "release_memory_sampler",
    "reset_device_table",
]
