"""Declarative SLOs evaluated as multi-window burn rates.

KeystoneML's optimizer only acts on *measured* profiles; the serving
plane gets the same discipline for its objectives. An ``Slo`` is a
declarative target ("99% of requests under 250 ms", "99.9% of requests
succeed") read off the metric series the gateway already publishes
(``RegistryHistogram`` cumulative ``le`` buckets for latency,
``RegistryCounter`` cells for availability). The ``SloMonitor`` samples
those cumulative series on an interval and evaluates **burn rates**
over two windows (Google SRE multiwindow convention, fast ~1 m / slow
~30 m):

    burn = (bad fraction over window) / (1 - target)

so burn 1.0 consumes the error budget exactly at the sustainable rate,
and burn >> 1 means the budget is being torched *right now*. The fast
window reacts in seconds (the gateway's admission watchdog tightens the
queue on it — shed early, before saturation); the slow window confirms
the burn is sustained, filtering one-window blips.

Everything lands back on the observability plane: burn rates export as
``keystone_slo_burn_rate{slo,window}`` gauges (scrape-alertable), and
every live monitor is browsable at the admin endpoint's ``/slz``.
Nothing runs unless a monitor is constructed and started — zero
overhead for processes that never declare an objective.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import weakref
from collections import deque
from typing import (
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from keystone_tpu.observability.registry import (
    MetricsRegistry,
    RegistryCounter,
    RegistryHistogram,
    get_global_registry,
)

logger = logging.getLogger(__name__)

FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 1800.0

# every live SloMonitor, for /slz (weak: a closed gateway's monitor
# disappears from the listing with it)
_monitors: "weakref.WeakSet[SloMonitor]" = weakref.WeakSet()


def monitors() -> List["SloMonitor"]:
    """Every live monitor in the process (the ``/slz`` source)."""
    return list(_monitors)


def slz_status() -> Dict:
    """The admin ``/slz`` document: every SLO of every live monitor."""
    slos: List[Dict] = []
    for monitor in monitors():
        slos.extend(monitor.status()["slos"])
    return {"slos": sorted(slos, key=lambda s: s["name"])}


class Slo:
    """One objective: a name, a target fraction, and a ``read``
    callable returning the **cumulative** ``(total, bad)`` event counts
    since process start. The monitor turns successive reads into
    windowed deltas; this object stays pure declaration."""

    def __init__(
        self,
        name: str,
        target: float,
        read: Callable[[], Tuple[float, float]],
        *,
        description: str = "",
        threshold_s: Optional[float] = None,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"SLO {name!r} target must be in (0, 1), got {target}"
            )
        self.name = name
        self.target = target
        self.budget = 1.0 - target
        self.read = read
        self.description = description
        self.threshold_s = threshold_s  # latency SLOs: the objective edge

    @classmethod
    def latency(
        cls,
        name: str,
        histogram: RegistryHistogram,
        threshold_s: float,
        target: float,
        labels: Sequence[str] = (),
    ) -> "Slo":
        """"``target`` of requests complete within ``threshold_s``",
        read from a native histogram's cumulative ``le`` buckets. The
        threshold snaps UP to bucket resolution (the smallest bound >=
        ``threshold_s``) — ``effective`` below is what is actually
        enforced, so declare thresholds on bucket edges for exactness.
        """
        labels = tuple(labels)
        idx = histogram.le_index(threshold_s)
        if idx >= len(histogram.bounds):
            # snapping to +Inf would count EVERY observation as good —
            # a dead objective that can never burn; fail loud instead
            raise ValueError(
                f"latency SLO {name!r} threshold {threshold_s}s exceeds "
                f"the histogram's largest bucket "
                f"({histogram.bounds[-1]}s) and would be unobservable"
            )
        effective = histogram.bounds[idx]

        def read() -> Tuple[float, float]:
            total = histogram.get_count(labels)
            good = histogram.cumulative_count(idx, labels)
            return float(total), float(total - good)

        return cls(
            name,
            target,
            read,
            description=(
                f"p{target * 100:g} latency <= {effective * 1e3:g}ms "
                f"(declared {threshold_s * 1e3:g}ms)"
            ),
            threshold_s=effective,
        )

    @classmethod
    def latency_from_buckets(
        cls,
        name: str,
        read_buckets: Callable[[], Sequence[Tuple[float, float]]],
        threshold_s: float,
        target: float,
    ) -> "Slo":
        """"``target`` of requests complete within ``threshold_s``",
        read from cumulative ``(le, count)`` buckets returned by
        ``read_buckets()`` — the FEDERATION path: a fleet router has
        no registry handle on its replicas' latency series, but it
        does have their scraped ``le`` buckets
        (``prometheus.histogram_buckets`` per replica merged by
        ``prometheus.merge_histograms``), and cumulative buckets are
        the same (total, bad) arithmetic as ``Slo.latency`` — so the
        fleet-wide burn rate is computed over exactly the series the
        replicas export, one ``SloMonitor`` above N processes.

        The threshold snaps UP to the smallest FINITE ``le`` bound >=
        ``threshold_s`` present in each read (same rule as
        ``Slo.latency``, applied per sample since the layout arrives
        with the data); an empty read reports ``(0, 0)`` — no fleet
        traffic yet, nothing burned. A threshold past every finite
        bound cannot raise at declaration time the way ``Slo.latency``
        does (the layout isn't known yet), so it clamps DOWN to the
        largest finite bound instead, with a one-time warning:
        snapping to ``+Inf`` would count every observation as good —
        a dead objective that can never burn — while the clamp keeps
        the SLO live (conservatively strict) and the warning points at
        the misdeclared threshold."""
        warned: List[str] = []  # one-time unobservable-threshold flag

        def read() -> Tuple[float, float]:
            buckets = list(read_buckets() or ())
            if not buckets:
                return 0.0, 0.0
            total = float(buckets[-1][1])
            good = None
            for le, count in buckets:
                if math.isinf(le):
                    continue
                if le >= threshold_s:
                    good = float(count)
                    break
            if good is None:
                finite = [
                    (le, c) for le, c in buckets if not math.isinf(le)
                ]
                if not finite:
                    return total, 0.0  # +Inf-only layout: unjudgeable
                if not warned:
                    warned.append(name)
                    logger.warning(
                        "SLO %s: threshold %gs exceeds the largest "
                        "finite bucket bound (%gs); clamping DOWN to "
                        "it — declare thresholds on bucket edges",
                        name, threshold_s, finite[-1][0],
                    )
                good = float(finite[-1][1])
            return total, total - good

        return cls(
            name,
            target,
            read,
            description=(
                f"p{target * 100:g} fleet latency <= "
                f"{threshold_s * 1e3:g}ms (federated le buckets)"
            ),
            threshold_s=threshold_s,
        )

    @classmethod
    def availability(
        cls,
        name: str,
        counter: RegistryCounter,
        target: float,
        *,
        base_labels: Sequence[str] = (),
        status_label_values: Sequence[str] = ("ok", "shed", "error"),
        bad_values: Sequence[str] = ("error",),
    ) -> "Slo":
        """"``target`` of requests end well", read from a labeled
        outcome counter (the gateway's
        ``keystone_gateway_requests_total{gateway,status}``): total is
        the sum across ``status_label_values`` appended to
        ``base_labels``; ``bad_values`` names the failing statuses."""
        base = tuple(base_labels)
        statuses = tuple(status_label_values)
        bad_set = tuple(bad_values)

        def read() -> Tuple[float, float]:
            by_status = {s: counter.get(base + (s,)) for s in statuses}
            return (
                float(sum(by_status.values())),
                float(sum(by_status[s] for s in bad_set)),
            )

        return cls(
            name,
            target,
            read,
            description=(
                f"{target * 100:g}% of requests avoid "
                f"{'/'.join(bad_set)} outcomes"
            ),
        )


class SloMonitor:
    """Samples every registered SLO's cumulative counts on a clock and
    evaluates fast/slow-window burn rates from the deltas.

    ``sample(now=...)`` is callable directly (tests drive synthetic
    clocks through it); ``start()`` runs it on a daemon thread. Each
    sample also publishes ``keystone_slo_burn_rate{slo,window}`` gauges
    and fires listeners — the gateway's admission watchdog is one."""

    def __init__(
        self,
        fast_window_s: float = FAST_WINDOW_S,
        slow_window_s: float = SLOW_WINDOW_S,
        registry: Optional[MetricsRegistry] = None,
    ):
        if not 0 < fast_window_s < slow_window_s:
            raise ValueError(
                f"need 0 < fast ({fast_window_s}) < slow "
                f"({slow_window_s}) window"
            )
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._slos: Dict[str, Slo] = {}
        # per SLO: (t, total, bad) cumulative samples, oldest first
        self._samples: Dict[str, Deque[Tuple[float, float, float]]] = {}
        self._burns: Dict[str, Dict[str, Optional[float]]] = {}
        self._listeners: List[Callable[["SloMonitor"], None]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else get_global_registry()
        self._burn_gauge = reg.gauge(
            "keystone_slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 consumes "
            "the budget exactly at the sustainable rate)",
            ("slo", "window"),
        )
        _monitors.add(self)

    # -- registration ------------------------------------------------------

    def add(self, slo: Slo) -> Slo:
        with self._lock:
            if slo.name in self._slos:
                raise ValueError(f"SLO {slo.name!r} already registered")
            self._slos[slo.name] = slo
            self._samples[slo.name] = deque()
            self._burns[slo.name] = {"fast": None, "slow": None}
        return slo

    def add_listener(self, fn: Callable[["SloMonitor"], None]) -> None:
        """``fn(monitor)`` fires after every sample (watchdogs hook
        admission tightening here)."""
        self._listeners.append(fn)

    @property
    def slos(self) -> List[Slo]:
        with self._lock:
            return list(self._slos.values())

    # -- evaluation --------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> None:
        """Read every SLO's cumulative counts, append to the history,
        recompute burns, publish gauges, fire listeners."""
        now = time.monotonic() if now is None else now
        with self._lock:
            slos = list(self._slos.values())
        for slo in slos:
            try:
                total, bad = slo.read()
            except Exception:
                logger.exception("SLO %s read failed", slo.name)
                continue
            with self._lock:
                series = self._samples[slo.name]
                series.append((now, float(total), float(bad)))
                # keep one sample older than the slow window so the
                # slow delta always has a baseline to subtract from
                horizon = now - self.slow_window_s
                while len(series) > 2 and series[1][0] <= horizon:
                    series.popleft()
                self._burns[slo.name] = {
                    "fast": self._burn_locked(
                        slo, series, now, self.fast_window_s
                    ),
                    "slow": self._burn_locked(
                        slo, series, now, self.slow_window_s
                    ),
                }
                burns = self._burns[slo.name]
            for window, burn in burns.items():
                if burn is not None:
                    self._burn_gauge.set(burn, (slo.name, window))
        for fn in list(self._listeners):
            try:
                fn(self)
            except Exception:
                logger.exception("SLO listener failed")

    @staticmethod
    def _window_base(
        series: Deque[Tuple[float, float, float]],
        now: float,
        window_s: float,
    ) -> Optional[Tuple[float, float, float]]:
        """The newest sample at least ``window_s`` old — the delta
        baseline. Oldest sample when history is shorter than the window
        (a young process burns against what it has measured)."""
        base = None
        for t, total, bad in series:
            if t <= now - window_s:
                base = (t, total, bad)
            else:
                break
        if base is None and series:
            base = series[0]
        return base

    def _burn_locked(
        self,
        slo: Slo,
        series: Deque[Tuple[float, float, float]],
        now: float,
        window_s: float,
    ) -> Optional[float]:
        if len(series) < 2:
            return None
        base = self._window_base(series, now, window_s)
        latest = series[-1]
        if base is None or latest[0] <= base[0]:
            return None
        d_total = latest[1] - base[1]
        if d_total <= 0:
            return 0.0  # no traffic in the window: nothing burned
        d_bad = max(0.0, latest[2] - base[2])
        return (d_bad / d_total) / slo.budget

    def burn_rates(self, name: str) -> Dict[str, Optional[float]]:
        """The latest ``{"fast": ..., "slow": ...}`` burns for one SLO
        (None until two samples exist)."""
        with self._lock:
            return dict(self._burns.get(name) or {"fast": None, "slow": None})

    def breaching(self, name: str, burn_threshold: float = 1.0) -> bool:
        """Multiwindow page condition: BOTH windows burning past the
        threshold — fast says "now", slow says "and it's sustained"."""
        burns = self.burn_rates(name)
        return all(
            b is not None and b >= burn_threshold for b in burns.values()
        )

    def status(self) -> Dict:
        """The ``/slz`` JSON fragment for this monitor."""
        out = []
        with self._lock:
            items = list(self._slos.values())
        for slo in items:
            burns = self.burn_rates(slo.name)
            with self._lock:
                series = self._samples.get(slo.name) or ()
                latest = series[-1] if series else None
            out.append(
                {
                    "name": slo.name,
                    "description": slo.description,
                    "target": slo.target,
                    "threshold_s": slo.threshold_s,
                    "windows_s": {
                        "fast": self.fast_window_s,
                        "slow": self.slow_window_s,
                    },
                    "burn_rate": burns,
                    "breaching": self.breaching(slo.name),
                    "total": latest[1] if latest else 0.0,
                    "bad": latest[2] if latest else 0.0,
                }
            )
        return {"slos": out}

    # -- lifecycle ---------------------------------------------------------

    def start(self, interval_s: float = 5.0) -> "SloMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.sample()
                except Exception:
                    logger.exception("SLO sample failed")

        self._thread = threading.Thread(
            target=loop, name="keystone-slo-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


__all__ = [
    "FAST_WINDOW_S",
    "SLOW_WINDOW_S",
    "Slo",
    "SloMonitor",
    "monitors",
    "slz_status",
]
