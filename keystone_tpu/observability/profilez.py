"""On-demand device profiling: ``GET /profilez?seconds=N``.

Arms the existing ``utils/profiling.trace`` wrapper (``jax.profiler``
XPlane capture) around whatever live traffic flows for the next N
seconds, then answers with the trace directory listing — the capture is
immediately loadable in Perfetto / TensorBoard's XProf plugin. Served
by BOTH the admin endpoint and the gateway frontend through the shared
``profilez_document`` below (the ``debugz_document`` routing pattern),
so a single-port deployment can still grab a device trace.

One capture at a time: ``jax.profiler.start_trace`` is process-global
state, so a second concurrent request gets a typed **409** instead of
corrupting the first capture. The handler thread blocks for the
capture window (the endpoint servers are threading servers — scrapes
keep flowing on other threads). Only the newest
``MAX_RETAINED_CAPTURES`` capture dirs are kept on disk — a probe
hitting the endpoint periodically can't fill the serving host's tmp.
"""

from __future__ import annotations

import itertools
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

# hard ceiling on one capture window: profiling holds buffers and the
# capture lock; an operator typo ("?seconds=3600") must not wedge the
# endpoint for an hour
MAX_CAPTURE_SECONDS = 60.0
DEFAULT_CAPTURE_SECONDS = 1.0
# bounded retention (the flight-recorder ring convention): a probe
# hitting /profilez periodically on a long-lived server must not fill
# the disk — only the newest captures survive
MAX_RETAINED_CAPTURES = 8

# process-global: jax.profiler allows one active trace per process
_capture_lock = threading.Lock()
_capture_ids = itertools.count()


def default_base_dir() -> str:
    return os.path.join(
        tempfile.gettempdir(), f"keystone-profilez-{os.getpid()}"
    )


def _prune_captures(
    base_dir: str, keep: int = MAX_RETAINED_CAPTURES
) -> None:
    """Best-effort delete of all but the ``keep`` newest capture dirs
    (also sweeps the empty dir a failed capture leaves behind)."""
    try:
        dirs = [
            path
            for name in os.listdir(base_dir)
            if name.startswith("trace-")
            and os.path.isdir(path := os.path.join(base_dir, name))
        ]
        dirs.sort(key=os.path.getmtime)
        for stale in dirs[:-keep] if keep > 0 else dirs:
            shutil.rmtree(stale, ignore_errors=True)
    except OSError:
        pass


def _sweep_dead_process_dirs(current_base: str) -> None:
    """Best-effort removal of ``keystone-profilez-<pid>`` trees left
    by dead server processes: per-pid retention alone would let a
    restart-looping host accumulate 8 captures per dead pid forever.
    Dirs whose pid is still alive (or not ours to signal) are kept."""
    parent = os.path.dirname(current_base)
    try:
        names = os.listdir(parent)
    except OSError:
        return
    for name in names:
        path = os.path.join(parent, name)
        if (
            not name.startswith("keystone-profilez-")
            or path == current_base
            or not os.path.isdir(path)
        ):
            continue
        pid_s = name.rsplit("-", 1)[-1]
        if not pid_s.isdigit():
            continue
        try:
            os.kill(int(pid_s), 0)
        except ProcessLookupError:
            shutil.rmtree(path, ignore_errors=True)
        except OSError:
            pass  # alive under another uid (EPERM etc.) — keep


def _listing(trace_dir: str, limit: int = 200) -> Tuple[list, int]:
    """Relative paths of the capture's files (bounded) + total count."""
    files = []
    for root, _dirs, names in os.walk(trace_dir):
        for name in names:
            files.append(
                os.path.relpath(os.path.join(root, name), trace_dir)
            )
    files.sort()
    return files[:limit], len(files)


def profilez_document(
    seconds_raw: Optional[str], base_dir: Optional[str] = None
) -> Tuple[int, Dict]:
    """One ``/profilez`` request -> ``(status_code, json_doc)``.

    400 on a malformed/out-of-range ``seconds``, 409 while another
    capture is running, 500 when the profiler itself fails (e.g. an
    XPlane backend without trace support), else 200 with the trace
    directory + file listing."""
    try:
        seconds = (
            float(seconds_raw) if seconds_raw is not None
            else DEFAULT_CAPTURE_SECONDS
        )
    except (TypeError, ValueError):
        return 400, {
            "error": "bad_request",
            "detail": f"seconds must be a number, got {seconds_raw!r}",
        }
    if not seconds > 0 or seconds > MAX_CAPTURE_SECONDS:
        return 400, {
            "error": "bad_request",
            "detail": f"seconds must be in (0, {MAX_CAPTURE_SECONDS:g}], "
                      f"got {seconds:g}",
        }
    if not _capture_lock.acquire(blocking=False):
        return 409, {
            "error": "capture_in_progress",
            "detail": "another /profilez capture is running; "
                      "jax.profiler supports one trace per process",
        }
    base = base_dir or default_base_dir()
    try:
        from keystone_tpu.utils.profiling import trace

        trace_dir = os.path.join(
            base,
            time.strftime("trace-%Y%m%d-%H%M%S")
            + f"-{next(_capture_ids)}",
        )
        os.makedirs(trace_dir, exist_ok=True)
        t0 = time.perf_counter()
        with trace(trace_dir):
            # live traffic keeps flowing on the serving threads; this
            # handler just holds the capture window open
            time.sleep(seconds)
        captured_s = time.perf_counter() - t0
        files, total = _listing(trace_dir)
        return 200, {
            "trace_dir": trace_dir,
            "seconds": seconds,
            "captured_s": round(captured_s, 3),
            "file_count": total,
            "files": files,
            "view": "load trace_dir in Perfetto or TensorBoard's "
                    "XProf profile plugin",
        }
    except Exception as e:  # profiler failure must answer, not raise
        return 500, {"error": "profiler_failed", "detail": str(e)}
    finally:
        # the dir just written is the newest -> always retained; runs
        # under the capture lock, so pruning never races a capture
        _prune_captures(base)
        if base_dir is None:  # default per-pid layout only
            _sweep_dead_process_dirs(base)
        _capture_lock.release()


__all__ = [
    "DEFAULT_CAPTURE_SECONDS",
    "MAX_CAPTURE_SECONDS",
    "MAX_RETAINED_CAPTURES",
    "profilez_document",
]
