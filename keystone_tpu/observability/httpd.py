"""Shared stdlib-HTTP scaffolding for the background endpoints.

The admin plane (``observability/admin.py``), the gateway frontend
(``gateway/http.py``), and the fleet router (``fleet/router.py``) are
all the same shape: a ``ThreadingHTTPServer`` on a daemon thread, bound
to localhost by default, ``port=0`` for an ephemeral port, JSON/text
responses with explicit Content-Length, and a clean
``start()``/``stop()``/context-manager lifecycle. This module is that
shape, once — a fix to binding, shutdown, or response framing lands in
every endpoint.

``RequestLogWriter`` is the shared ``--request-log`` sink: one JSON
line per request, stdout or a line-buffered JSONL file, concurrent
handler threads kept whole under one lock. The gateway and the router
both write the same schema through it, which is what keeps fleet
recordings replayable by the same ``loadgen/trace.py`` parser.
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)

# per-POST identity for request logs: concurrent handler threads
# interleave their lines, so a replayer can't rely on adjacency —
# lines from one POST share a post_seq instead (next() on
# itertools.count is atomic under the GIL). The random per-process
# prefix keeps ids unique across restarts: request logs open in
# APPEND mode, and a counter restarting at 1 would make a second
# session's posts dedupe away against the first's.
_POST_NONCE = "%08x" % random.getrandbits(32)
_POST_SEQ = itertools.count(1)


def next_post_seq() -> str:
    """A process-unique per-POST id for ``--request-log`` lines."""
    return f"{_POST_NONCE}-{next(_POST_SEQ)}"


class JsonHandler(BaseHTTPRequestHandler):
    """Response helpers + quiet logging shared by the endpoint
    handlers (scrapes/probes hit every few seconds; request logs go to
    DEBUG instead of stderr)."""

    def _send(
        self,
        code: int,
        body: bytes,
        content_type: str,
        headers: Optional[dict] = None,
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(
        self,
        obj,
        code: int = 200,
        indent: Optional[int] = None,
        headers: Optional[dict] = None,
    ) -> None:
        self._send(
            code,
            json.dumps(obj, indent=indent, default=str).encode("utf-8"),
            "application/json; charset=utf-8",
            headers=headers,
        )

    def _send_text(
        self, code: int, text: str, headers: Optional[dict] = None
    ) -> None:
        self._send(
            code,
            text.encode("utf-8"),
            "text/plain; charset=utf-8",
            headers=headers,
        )

    def log_message(self, format, *args):  # noqa: A002 (stdlib API)
        logger.debug("%s: " + format, type(self).__module__, *args)


class _QueueingHTTPServer(ThreadingHTTPServer):
    """``ThreadingHTTPServer`` with a real listen backlog.
    socketserver's default ``request_queue_size`` of 5 drops bursty
    connection attempts with a client-side connection reset the
    moment more arrive in one scheduler quantum than ``accept()``
    drains — which the open-loop load generator at fleet rates (and
    a router fanning out to replicas) does routinely. A reset on an
    otherwise-healthy endpoint would be indistinguishable from a
    LOST request to the invariant checker."""

    request_queue_size = 128


class BackgroundServer:
    """A ``ThreadingHTTPServer`` + daemon serve thread behind
    ``start()``/``stop()``. Subclasses set ``handler_cls`` and
    ``thread_name`` and attach their routing state to the live server
    object in ``_configure()``."""

    handler_cls = JsonHandler
    thread_name = "keystone-http"

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._requested = (host, port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _configure(self, httpd: ThreadingHTTPServer) -> None:
        """Attach handler-visible state (registries, gateways, ...) to
        ``httpd`` before the serve thread starts."""

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError(f"{type(self).__name__} not started")
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._requested[0]

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def start(self) -> "BackgroundServer":
        if self._httpd is not None:
            return self
        httpd = _QueueingHTTPServer(self._requested, self.handler_cls)
        httpd.daemon_threads = True
        self._configure(httpd)
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()
        logger.info("%s serving on %s", type(self).__name__, self.url())
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class RequestLogWriter:
    """The ``--request-log`` sink shared by the gateway frontend and
    the fleet router: falsy = disabled, True = one JSON line per
    request on stdout, a path = append line-buffered JSONL there (the
    loadgen record/replay path — no process-output scraping)."""

    def __init__(self, request_log) -> None:
        self.enabled = bool(request_log)
        # the stop() close race (PR 7 review): a straggler handler
        # thread must re-check this under the lock, never write to a
        # closed file — the guarded-by rule keeps it that way
        self._file = None  # guarded-by: _lock
        self._lock = threading.Lock()
        self._to_file = isinstance(request_log, (str, bytes)) or hasattr(
            request_log, "__fspath__"
        )
        if self._to_file:
            self._file = open(  # noqa: SIM115 (held open for the
                # server's lifetime; close() closes it)
                request_log, "a", buffering=1, encoding="utf-8",
            )

    def write(self, line: dict) -> None:
        """One record to the log (stdout or the file). Handler threads
        are concurrent; the lock keeps lines whole."""
        text = json.dumps(line)
        if not self._to_file:
            with self._lock:
                # one write() call for text+newline, under the lock:
                # print() issues two writes and concurrent handler
                # threads would interleave mid-line, producing merged
                # lines the trace parser drops
                sys.stdout.write(text + "\n")
                sys.stdout.flush()
            return
        with self._lock:
            # re-read under the lock: daemon handler threads are not
            # joined by stop(), so a straggler can race the close —
            # it must drop its line, not write to a closed file
            out = self._file
            if out is not None:
                out.write(text + "\n")

    def close(self) -> None:
        if self._file is not None:
            with self._lock:
                self._file.close()
                self._file = None
