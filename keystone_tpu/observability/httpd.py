"""Shared stdlib-HTTP scaffolding for the background endpoints.

The admin plane (``observability/admin.py``) and the gateway frontend
(``gateway/http.py``) are both the same shape: a ``ThreadingHTTPServer``
on a daemon thread, bound to localhost by default, ``port=0`` for an
ephemeral port, JSON/text responses with explicit Content-Length, and a
clean ``start()``/``stop()``/context-manager lifecycle. This module is
that shape, once — a fix to binding, shutdown, or response framing
lands in both endpoints.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)


class JsonHandler(BaseHTTPRequestHandler):
    """Response helpers + quiet logging shared by the endpoint
    handlers (scrapes/probes hit every few seconds; request logs go to
    DEBUG instead of stderr)."""

    def _send(
        self,
        code: int,
        body: bytes,
        content_type: str,
        headers: Optional[dict] = None,
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(
        self, obj, code: int = 200, indent: Optional[int] = None
    ) -> None:
        self._send(
            code,
            json.dumps(obj, indent=indent, default=str).encode("utf-8"),
            "application/json; charset=utf-8",
        )

    def _send_text(
        self, code: int, text: str, headers: Optional[dict] = None
    ) -> None:
        self._send(
            code,
            text.encode("utf-8"),
            "text/plain; charset=utf-8",
            headers=headers,
        )

    def log_message(self, format, *args):  # noqa: A002 (stdlib API)
        logger.debug("%s: " + format, type(self).__module__, *args)


class BackgroundServer:
    """A ``ThreadingHTTPServer`` + daemon serve thread behind
    ``start()``/``stop()``. Subclasses set ``handler_cls`` and
    ``thread_name`` and attach their routing state to the live server
    object in ``_configure()``."""

    handler_cls = JsonHandler
    thread_name = "keystone-http"

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._requested = (host, port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _configure(self, httpd: ThreadingHTTPServer) -> None:
        """Attach handler-visible state (registries, gateways, ...) to
        ``httpd`` before the serve thread starts."""

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError(f"{type(self).__name__} not started")
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._requested[0]

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def start(self) -> "BackgroundServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer(self._requested, self.handler_cls)
        httpd.daemon_threads = True
        self._configure(httpd)
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()
        logger.info("%s serving on %s", type(self).__name__, self.url())
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
