"""Per-model device-cost attribution: WHO is spending the fleet.

Every serving counter so far answers "what did this engine do"; nothing
answers "which model (tenant) caused it" — the number the placement
planner's re-plan loop and any multi-tenant QoS policy need as
evidence. The ``AttributionLedger`` is that answer: a per-model account
of device seconds, modeled FLOPs, H2D bytes, goodput vs padded rows and
dispatch counts, fed from the same ``record_dispatch`` facts the
engine-level counters read, so the two surfaces can never tell
different stories.

Solo engines charge their one model everything. Shared-prefix engines
(``zoo/cse.py``) need the *fair-split* rule: each dispatched window ran
one shared featurize prefix plus every co-resident model's head, so the
prefix's modeled cost (its own XLA cost model, vs the heads') is
apportioned across the window's models **by row share**, and each
head's cost goes to its own model. The per-window weights are
normalized against the ENGINE's dispatch totals, so per-model charges
sum exactly to the engine totals — the invariant the tests and the
``serving_attribution_drift`` bench row pin at 1e-6 relative. Engines
whose prefix/head cost models are absent (CPU CI) degrade to pure
row-share splitting — still exactly summing, just less informed.

Exported two ways, same numbers:
- ``keystone_attr_*{model}`` Prometheus families (``register()``) —
  absent-not-zero like every degradable series here, and federated
  across the fleet by the existing ``merge_expositions`` sum path
  (identical model labels across replicas add, which IS fleet truth
  for these counters);
- the ``GET /attributionz`` document (``attribution_document``) —
  per-model device-seconds share, a $/FLOP-style normalized cost
  (device seconds per modeled GFLOP), and a top-k spender table. The
  router builds the SAME document from its federated scrape
  (``attribution_from_samples``) so its ``/attributionz`` is
  fleet-truth, not router-local.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# the ledger's additive per-model cells, in export order; every one is
# a lifetime total (monotonic -> Prometheus counters)
CELL_FIELDS = (
    "device_seconds",
    "device_flops",
    "h2d_bytes",
    "goodput_rows",
    "padded_rows",
    "dispatches",
)

_COUNTER_HELP = {
    "device_seconds": "device wall seconds attributed to the model "
    "(completion-timed dispatches, fair-split over shared engines)",
    "device_flops": "modeled device FLOPs attributed to the model "
    "(shared featurize prefixes split by row share)",
    "h2d_bytes": "host-to-device bytes attributed to the model "
    "(padding included, split by row share on shared engines)",
    "goodput_rows": "valid (non-padding) rows served for the model",
    "padded_rows": "padded rows attributed to the model "
    "(its share of bucket waste)",
    "dispatches": "compiled-program dispatches attributed to the model "
    "(fractional on shared engines: the model's weight share of each "
    "window)",
}


class AttributionLedger:
    """Thread-safe per-model cost account. Cells are floats — shared
    windows charge fractional rows/dispatches, which is what makes the
    sum-to-engine-totals invariant exact instead of rounded."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cells: Dict[str, Dict[str, float]] = {}
        # per-model staging/AOT bytes: a gauge (point-in-time), kept
        # out of the additive cells; None never stored (absent = absent)
        self._staging: Dict[str, float] = {}

    def charge(self, model: str, **deltas: float) -> None:
        """Add cost to one model's account. Unknown fields raise —
        a typo'd field silently opening a new column is exactly the
        drift this plane exists to catch."""
        bad = set(deltas) - set(CELL_FIELDS)
        if bad:
            raise ValueError(f"unknown attribution fields: {sorted(bad)}")
        with self._lock:
            cell = self._cells.get(model)
            if cell is None:
                cell = self._cells[model] = {f: 0.0 for f in CELL_FIELDS}
            for field, v in deltas.items():
                cell[field] += float(v)

    def set_staging_bytes(self, model: str, nbytes: Optional[float]) -> None:
        """Point-in-time staging/AOT byte footprint for one model
        (None clears — the series goes absent, never zero-stamped)."""
        with self._lock:
            if nbytes is None:
                self._staging.pop(model, None)
            else:
                self._staging[model] = float(nbytes)

    # -- queries -----------------------------------------------------------

    def per_model(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {m: dict(cell) for m, cell in self._cells.items()}

    def totals(self) -> Dict[str, float]:
        """Cross-model sums — what must equal the engine-side totals."""
        out = {f: 0.0 for f in CELL_FIELDS}
        for cell in self.per_model().values():
            for f in CELL_FIELDS:
                out[f] += cell[f]
        return out

    def staging_bytes(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._staging)

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._cells)

    # -- MetricsRegistry bridge --------------------------------------------

    def register(self, registry=None) -> None:
        """Export the ledger as ``keystone_attr_*{model}`` families.
        Absent-not-zero: a model appears only once it has been charged,
        and the staging gauge only where a footprint was set."""
        from keystone_tpu.observability.registry import (
            MetricFamily,
            Sample,
            get_global_registry,
        )

        reg = registry if registry is not None else get_global_registry()
        import weakref

        ref = weakref.ref(self)

        def collect():
            ledger = ref()
            if ledger is None:
                return None
            cells = ledger.per_model()
            fams = []
            for field in CELL_FIELDS:
                samples = [
                    Sample("", {"model": m}, cell[field])
                    for m, cell in sorted(cells.items())
                    if cell[field]
                ]
                if samples:
                    fams.append(MetricFamily(
                        f"keystone_attr_{field}_total", "counter",
                        _COUNTER_HELP[field], samples,
                    ))
            staging = ledger.staging_bytes()
            if staging:
                fams.append(MetricFamily(
                    "keystone_attr_staging_bytes", "gauge",
                    "per-model staging/AOT byte footprint (host "
                    "staging pools + serialized-executable namespaces)",
                    [
                        Sample("", {"model": m}, v)
                        for m, v in sorted(staging.items())
                    ],
                ))
            return fams

        reg.register_collector(collect)


class RowClaimQueue:
    """FIFO of ``(model, rows)`` claims declaring which model each row
    of upcoming shared-engine traffic belongs to — enqueued at submit
    time, drained per dispatched window. One queue per shared UNIT
    (shared by every lane's engine): the micro-batcher coalesces FIFO,
    so the drain tracks window membership; concurrent lanes can skew an
    individual window's shares, but the attribution binding normalizes
    per window, so per-model totals still sum exactly to engine totals
    whatever the interleaving."""

    def __init__(self):
        self._lock = threading.Lock()
        self._claims: collections.deque = collections.deque()

    def claim(self, model: str, rows: float) -> None:
        if rows > 0:
            with self._lock:
                self._claims.append((model, float(rows)))

    def drain(self, n_valid: float) -> Dict[str, float]:
        """Consume claims covering ``n_valid`` dispatched rows ->
        ``{model: rows}``. A partially-covered claim is split and its
        remainder left queued; an under-claimed window returns what was
        claimed (missing rows are unattributed — the binding
        normalizes)."""
        out: Dict[str, float] = {}
        need = float(n_valid)
        with self._lock:
            while need > 1e-9 and self._claims:
                model, rows = self._claims.popleft()
                take = min(rows, need)
                out[model] = out.get(model, 0.0) + take
                need -= take
                if rows - take > 1e-9:
                    self._claims.appendleft((model, rows - take))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._claims)


class EngineAttribution:
    """The per-engine binding ``ServingMetrics`` calls into from
    ``record_dispatch`` / ``record_dispatch_complete``.

    ``models`` is the engine's resident model set. Solo engines pass
    one model and every weight question collapses to "all of it".
    Shared engines pass ``shares_fn(n_valid) -> {model: rows}`` (the
    CSE claim-queue drain: which model contributed which rows to this
    window) and optionally ``split_cost_fn(bucket) -> (prefix_flops,
    {model: head_flops})`` from the prefix/head split cost models.

    Per-window weight of model m:
        ``w[m] = rowshare[m] * prefix_flops + head_flops[m]``
    normalized to sum 1 — so ``total * w[m]`` sums exactly to the
    engine's total whatever the cost models say. Without a split cost
    model the weights degrade to pure row share.
    """

    def __init__(
        self,
        ledger: AttributionLedger,
        models: Sequence[str],
        *,
        shares_fn: Optional[Callable[[int], Dict[str, float]]] = None,
        split_cost_fn: Optional[
            Callable[[int], Optional[Tuple[float, Dict[str, float]]]]
        ] = None,
    ):
        if not models:
            raise ValueError("an attribution binding needs >= 1 model")
        self.ledger = ledger
        self.models = tuple(models)
        self.shares_fn = shares_fn
        self.split_cost_fn = split_cost_fn
        self._lock = threading.Lock()
        # weight vectors accumulated since the last completion record:
        # record_dispatch_complete covers every dispatch since the
        # caller's previous sync point, so its seconds are split by the
        # SUM of the pending windows' weights, not just the last one
        self._pending: Dict[str, float] = {}

    # -- weight computation ------------------------------------------------

    def _row_shares(self, n_valid: int) -> Dict[str, float]:
        if len(self.models) == 1:
            return {self.models[0]: 1.0}
        rows: Dict[str, float] = {}
        if self.shares_fn is not None:
            try:
                rows = {
                    m: float(r)
                    for m, r in (self.shares_fn(n_valid) or {}).items()
                    if r > 0
                }
            except Exception:
                rows = {}
        total = sum(rows.values())
        if total <= 0:
            # no claims (direct engine.apply, warmup): uniform split
            even = 1.0 / len(self.models)
            return {m: even for m in self.models}
        return {m: r / total for m, r in rows.items()}

    def _weights(self, bucket: int, row_shares: Dict[str, float]):
        split = None
        if self.split_cost_fn is not None:
            try:
                split = self.split_cost_fn(bucket)
            except Exception:
                split = None
        if not split:
            return dict(row_shares)
        prefix_flops, head_flops = split
        weights = {
            m: row_shares.get(m, 0.0) * float(prefix_flops)
            + float(head_flops.get(m, 0.0))
            for m in set(row_shares) | set(head_flops)
        }
        total = sum(weights.values())
        if total <= 0:
            return dict(row_shares)
        return {m: w / total for m, w in weights.items()}

    # -- ServingMetrics hooks ----------------------------------------------

    def on_dispatch(
        self,
        bucket: int,
        n_valid: int,
        padded: int,
        flops: float,
        seconds: Optional[float],
        h2d_bytes: Optional[int],
    ) -> None:
        row_shares = self._row_shares(n_valid)
        weights = self._weights(bucket, row_shares)
        if seconds is None:
            # this window's device seconds arrive later, at the
            # caller's sync point (record_dispatch_complete) — queue
            # its weights; a dispatch that already carried completion
            # seconds is charged right here instead
            with self._lock:
                for m, w in weights.items():
                    self._pending[m] = self._pending.get(m, 0.0) + w
        for m in set(row_shares) | set(weights):
            rs = row_shares.get(m, 0.0)
            w = weights.get(m, 0.0)
            deltas = {
                "goodput_rows": rs * n_valid,
                "padded_rows": rs * padded,
                "dispatches": w,
            }
            if flops:
                deltas["device_flops"] = w * flops
            if h2d_bytes:
                deltas["h2d_bytes"] = rs * h2d_bytes
            if seconds is not None:
                deltas["device_seconds"] = w * seconds
            self.ledger.charge(m, **deltas)

    def on_complete(self, seconds: float) -> None:
        """Completion-timed seconds covering every dispatch since the
        last completion: split by the accumulated pending weights."""
        with self._lock:
            pending, self._pending = self._pending, {}
        total = sum(pending.values())
        if total <= 0:
            even = 1.0 / len(self.models)
            pending = {m: even for m in self.models}
            total = 1.0
        for m, w in pending.items():
            if w:
                self.ledger.charge(
                    m, device_seconds=seconds * (w / total)
                )


# -- /attributionz documents ----------------------------------------------


def _share_doc(
    cells: Dict[str, Dict[str, float]],
    staging: Dict[str, float],
    top_k: int,
) -> Dict:
    total_seconds = sum(c.get("device_seconds", 0.0) for c in cells.values())
    total_flops = sum(c.get("device_flops", 0.0) for c in cells.values())
    models = {}
    for m, cell in sorted(cells.items()):
        flops = cell.get("device_flops", 0.0)
        seconds = cell.get("device_seconds", 0.0)
        entry = {f: cell.get(f, 0.0) for f in CELL_FIELDS}
        entry["device_seconds_share"] = (
            seconds / total_seconds if total_seconds > 0 else None
        )
        entry["device_flops_share"] = (
            flops / total_flops if total_flops > 0 else None
        )
        # the $/FLOP-style normalized unit cost: device seconds per
        # modeled GFLOP — a model burning time without modeled work
        # (host-bound, tiny batches) surfaces as expensive here
        entry["seconds_per_gflop"] = (
            seconds / (flops / 1e9) if flops > 0 else None
        )
        rows = entry["goodput_rows"] + entry["padded_rows"]
        entry["goodput_fraction"] = (
            entry["goodput_rows"] / rows if rows > 0 else None
        )
        if m in staging:
            entry["staging_bytes"] = staging[m]
        models[m] = entry

    def spend(item):
        m, e = item
        return (e["device_seconds"], e["device_flops"], e["goodput_rows"])

    top = [
        {
            "model": m,
            "device_seconds": e["device_seconds"],
            "device_seconds_share": e["device_seconds_share"],
            "device_flops": e["device_flops"],
            "seconds_per_gflop": e["seconds_per_gflop"],
        }
        for m, e in sorted(models.items(), key=spend, reverse=True)[:top_k]
    ]
    return {
        "models": models,
        "top": top,
        "totals": {
            "device_seconds": total_seconds,
            "device_flops": total_flops,
        },
    }


def attribution_document(ledger: AttributionLedger, top_k: int = 10) -> Dict:
    """The ``GET /attributionz`` document off one process's ledger."""
    return _share_doc(ledger.per_model(), ledger.staging_bytes(), top_k)


def attribution_from_samples(
    samples: Iterable[Tuple[str, Dict[str, str], float]], top_k: int = 10
) -> Dict:
    """The same document rebuilt from parsed exposition rows
    (``prometheus.parse_samples``) — the fleet router feeds its
    FEDERATED scrape through here so its ``/attributionz`` totals are
    the fleet's, not its own."""
    cells: Dict[str, Dict[str, float]] = {}
    staging: Dict[str, float] = {}
    prefix = "keystone_attr_"
    for name, labels, value in samples:
        if not name.startswith(prefix):
            continue
        model = labels.get("model")
        if model is None:
            continue
        field = name[len(prefix):]
        if field == "staging_bytes":
            staging[model] = staging.get(model, 0.0) + value
            continue
        if field.endswith("_total"):
            field = field[: -len("_total")]
        if field not in CELL_FIELDS:
            continue
        cell = cells.setdefault(model, {f: 0.0 for f in CELL_FIELDS})
        cell[field] += value
    return _share_doc(cells, staging, top_k)


__all__ = [
    "CELL_FIELDS",
    "AttributionLedger",
    "EngineAttribution",
    "RowClaimQueue",
    "attribution_document",
    "attribution_from_samples",
]
