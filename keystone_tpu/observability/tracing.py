"""Span tracing: start/end spans with parent links, a bounded ring of
recent spans, and Chrome trace-event JSON export.

Dapper-style application-level spans for the interpret layer — the JAX
profiler (``utils.profiling.trace``) already covers the XLA/device
substrate, but nothing records *why* the device was asked to do work:
which executor node, which serving dispatch, which coalesced window,
which lane-pipeline stage. Span names in the serving path:
``gateway.admit`` → ``microbatch.coalesce`` → ``serving.dispatch``
(serial lanes) or → ``pipeline.host_prep`` / ``pipeline.upload`` /
``pipeline.compute`` / ``pipeline.deliver`` (staged lanes, one span
per stage per window, each on its own stage thread).
Spans nest via a thread-local stack, so a ``serving.dispatch`` span
started inside a ``microbatch.dispatch`` span carries its parent's id —
``/tracez`` (observability/admin.py) shows the tree, and
``to_chrome_trace()`` exports the ring as Chrome trace-event JSON
(the ``{"traceEvents": [...]}`` object format) loadable in
chrome://tracing or Perfetto.

Disabled is the default and costs one attribute read per ``span()``
call (a shared no-op context manager is returned; nothing is recorded,
no lock is taken). ``enable_tracing()`` flips the process-global
tracer on.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

DEFAULT_CAPACITY = 2048

# span_id -> trace_id entries kept for cross-thread parent pinning (the
# pinned parent has usually FINISHED by the time its child starts — the
# gateway.admit span ends at submit-return, the micro-batch window
# opens later on the dispatcher thread)
TRACE_MAP_CAPACITY = 8192

_ids = itertools.count(1)


def new_trace_id() -> str:
    """A fresh 128-bit trace id as 32 lowercase hex chars (the OTLP /
    W3C trace-context wire width, and the exemplar label value)."""
    return os.urandom(16).hex()


# -- W3C trace context (the cross-process wire format) ----------------------

# https://www.w3.org/TR/trace-context/: version "00" header is
# `00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>`. The fleet
# router emits it on every forwarded /predict; the gateway adopts the
# trace id so one request is ONE trace across processes.
TRACEPARENT_HEADER = "traceparent"

# the RESPONSE header both serving tiers echo the request's trace id
# on (success AND typed shed): one constant, because the gateway, the
# router, and the loadgen client all speak it — a casing drift in one
# tier would silently turn every client-side trace id into None
TRACE_RESPONSE_HEADER = "X-Keystone-Trace"

_HEX = frozenset("0123456789abcdef")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A parsed ``traceparent``: the remote caller's trace identity.
    ``parent_span_id`` is the REMOTE process's span id (16 hex chars)
    — it never maps onto this process's integer span ids, so adopters
    take the ``trace_id`` and record the remote parent as an attr."""

    trace_id: str
    parent_span_id: str
    flags: str = "01"


def _is_hex(s: str, width: int) -> bool:
    return len(s) == width and all(c in _HEX for c in s)


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """A ``traceparent`` header value -> ``TraceContext``, or None for
    absent/malformed/all-zero input (the W3C spec says a receiver that
    cannot parse the header MUST restart the trace — minting a fresh
    id, never half-adopting garbage)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, parent_id, flags = parts[0], parts[1], parts[2], parts[3]
    if not _is_hex(version, 2) or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        # version 00 defines EXACTLY four fields; trailing data makes
        # the header unparseable and the trace restarts (the spec's
        # rule) — only future versions may append fields
        return None
    if not _is_hex(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _is_hex(parent_id, 16) or parent_id == "0" * 16:
        return None
    if not _is_hex(flags, 2):
        return None
    return TraceContext(trace_id=trace_id, parent_span_id=parent_id, flags=flags)


def format_traceparent(trace_id: str, span_id: Optional[int]) -> str:
    """The outbound header for a span in THIS process: our integer
    span ids render as the 8-byte hex field the wire expects (same
    mapping the OTLP exporter uses), sampled flag always set — the
    downstream process decides its own recording, we only carry
    identity."""
    return "00-{}-{:016x}-01".format(
        trace_id, (span_id or 0) & ((1 << 64) - 1)
    )


@dataclasses.dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    start_s: float  # epoch seconds (time.time clock)
    duration_s: float
    thread_id: int
    attrs: Dict[str, Any]
    trace_id: Optional[str] = None  # shared by every span of one request

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_s": self.start_s,
            "duration_ms": round(self.duration_s * 1e3, 6),
            "thread_id": self.thread_id,
            "attrs": dict(self.attrs),
        }


class _ActiveSpan:
    """A span in flight; exposes ``set_attr`` and is the context object
    ``Tracer.span()`` yields."""

    __slots__ = (
        "name", "span_id", "parent_id", "trace_id", "attrs", "_t0", "_wall",
    )

    def __init__(
        self,
        name: str,
        parent_id: Optional[int],
        attrs: Dict,
        trace_id: Optional[str] = None,
    ):
        self.name = name
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.trace_id = trace_id if trace_id is not None else new_trace_id()
        self.attrs = attrs
        self._t0 = time.perf_counter()
        self._wall = time.time()

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value


class _NullSpan:
    """The shared disabled-path object: every method is a no-op."""

    __slots__ = ()
    span_id = None
    parent_id = None
    trace_id = None

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded in-memory span recorder with thread-local parent links."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.enabled = enabled
        # the ring swap incident (PR 4 review): enable_tracing used to
        # rebuild this deque unguarded and raced concurrent end_span
        # appenders — exactly what the guarded-by rule now checks
        self._ring: Deque[Span] = (
            collections.deque(maxlen=capacity)
        )  # guarded-by: _lock
        self._lock = threading.Lock()
        self._local = threading.local()
        # span_id -> trace_id for recently started spans, so a child
        # pinned to a cross-thread parent_id joins the parent's trace
        # even after the parent finished; bounded FIFO
        self._trace_map: Dict[int, str] = {}  # guarded-by: _lock
        self._trace_order: Deque[int] = (
            collections.deque()
        )  # guarded-by: _lock
        # sinks observe every FINISHED span (the OTLP exporter installs
        # here); empty list = zero per-span overhead beyond the check
        self._sinks: List[Callable[[Span], None]] = []  # guarded-by: _lock

    # -- span lifecycle ----------------------------------------------------

    def _stack(self) -> List[_ActiveSpan]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def start_span(
        self,
        name: str,
        parent_id: Optional[int] = None,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ):
        """Explicit API (use ``span()`` where a ``with`` block fits).
        The new span's parent is this thread's innermost open span,
        unless ``parent_id`` pins it explicitly — the cross-thread case,
        e.g. a micro-batch window on the dispatcher thread parenting
        under the ``gateway.admit`` span of the request that opened it.
        ``trace_id`` ADOPTS a caller-supplied identity (an inbound W3C
        ``traceparent``'s) instead of minting one — the cross-PROCESS
        case; it wins over any inherited/mapped id so a forwarded
        request stays one trace fleet-wide."""
        if not self.enabled:
            return _NULL_SPAN
        stack = self._stack()
        if parent_id is None:
            if stack:
                parent_id = stack[-1].span_id
                if trace_id is None:
                    trace_id = stack[-1].trace_id
        elif trace_id is None:
            # explicit cross-thread parent: join its trace if we still
            # know it (bounded map); else this span roots a new trace
            with self._lock:
                trace_id = self._trace_map.get(parent_id)
        span = _ActiveSpan(name, parent_id, attrs, trace_id=trace_id)
        stack.append(span)
        with self._lock:
            self._trace_map[span.span_id] = span.trace_id
            self._trace_order.append(span.span_id)
            while len(self._trace_order) > TRACE_MAP_CAPACITY:
                self._trace_map.pop(self._trace_order.popleft(), None)
        return span

    def end_span(self, span: _ActiveSpan) -> Optional[Span]:
        if span is _NULL_SPAN:
            return None
        done = Span(
            name=span.name,
            span_id=span.span_id,
            parent_id=span.parent_id,
            start_s=span._wall,
            duration_s=time.perf_counter() - span._t0,
            thread_id=threading.get_ident(),
            attrs=span.attrs,
            trace_id=span.trace_id,
        )
        stack = self._stack()
        if span in stack:  # tolerate out-of-order ends
            stack.remove(span)
        with self._lock:
            self._ring.append(done)
            sinks = list(self._sinks) if self._sinks else None
        if sinks:
            for sink in sinks:
                try:
                    sink(done)
                except Exception:  # a broken exporter must not break
                    pass  # the instrumented hot path
        return done

    # -- sinks (span exporters) --------------------------------------------

    def add_sink(self, fn: Callable[[Span], None]) -> None:
        """``fn`` observes every finished span (called outside the
        instrumented code path's locks; exceptions are swallowed)."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    @contextlib.contextmanager
    def _span_cm(
        self,
        name: str,
        parent_id: Optional[int],
        trace_id: Optional[str],
        attrs: Dict[str, Any],
    ):
        span = self.start_span(
            name, parent_id=parent_id, trace_id=trace_id, **attrs
        )
        try:
            yield span
        finally:
            self.end_span(span)

    def span(
        self,
        name: str,
        parent_id: Optional[int] = None,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ):
        """``with tracer.span("serving.dispatch", bucket=8):`` — records
        nothing when the tracer is disabled. ``parent_id`` pins the
        parent explicitly (cross-thread chains); ``trace_id`` adopts a
        remote trace identity (cross-process chains)."""
        if not self.enabled:
            return _NULL_SPAN
        return self._span_cm(name, parent_id, trace_id, attrs)

    def current_span(self):
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else _NULL_SPAN

    # -- queries / export --------------------------------------------------

    def recent(self, n: Optional[int] = None) -> List[Span]:
        """Most recent finished spans, oldest first."""
        with self._lock:
            spans = list(self._ring)
        return spans if n is None else spans[-n:]

    def spans_for_trace(self, trace_id: str) -> List[Span]:
        """Every finished span of one trace still in the ring, oldest
        first — the flight recorder's span-tree source."""
        if not trace_id:
            return []
        with self._lock:
            return [s for s in self._ring if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The ring as Chrome trace-event JSON (object format): one
        complete ``"ph": "X"`` event per span, microsecond timestamps,
        span/parent ids in ``args`` — loads in chrome://tracing and
        Perfetto."""
        pid = os.getpid()
        events = []
        for s in self.recent():
            events.append(
                {
                    "name": s.name,
                    "ph": "X",
                    "ts": s.start_s * 1e6,
                    "dur": s.duration_s * 1e6,
                    "pid": pid,
                    "tid": s.thread_id,
                    "args": {
                        **s.attrs,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                        "trace_id": s.trace_id,
                    },
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


# -- process-global tracer -------------------------------------------------

_global_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process-global tracer (disabled until ``enable_tracing``)."""
    return _global_tracer


def enable_tracing(capacity: Optional[int] = None) -> Tracer:
    if capacity is not None:
        # the ring replacement must be atomic with concurrent end_span
        # appenders (they append under the same lock) — an unguarded
        # rebuild raced writers into the deque being copied and lost
        # their spans (or tripped RuntimeError on mutation-during-copy)
        with _global_tracer._lock:
            if capacity != _global_tracer._ring.maxlen:
                _global_tracer._ring = collections.deque(
                    _global_tracer._ring, maxlen=capacity
                )
    _global_tracer.enabled = True
    return _global_tracer


def disable_tracing() -> None:
    _global_tracer.enabled = False


def tracez_document(
    tracer: Tracer, fmt: str = "", n_raw: Optional[str] = None
) -> Dict[str, Any]:
    """Build the ``/tracez`` response document — shared by the admin
    endpoint and the gateway frontend (the way ``flight.debugz_document``
    backs both ``/debugz`` routes) so the two handlers cannot drift.
    ``fmt="chrome"`` returns the Chrome trace-event export; otherwise the
    recent-span listing, optionally limited to the last ``n_raw`` spans."""
    if fmt == "chrome":
        return tracer.to_chrome_trace()
    n = int(n_raw) if n_raw is not None else None
    return {
        "enabled": tracer.enabled,
        "spans": [s.to_dict() for s in tracer.recent(n)],
    }


__all__ = [
    "DEFAULT_CAPACITY",
    "Span",
    "TRACEPARENT_HEADER",
    "TRACE_RESPONSE_HEADER",
    "TraceContext",
    "Tracer",
    "disable_tracing",
    "enable_tracing",
    "format_traceparent",
    "get_tracer",
    "new_trace_id",
    "parse_traceparent",
    "tracez_document",
]
