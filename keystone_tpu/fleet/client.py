"""Fleet roster client: the ONE implementation of the ``/registerz``
and ``/deregisterz`` wire calls.

The gateway (``--register`` self-registration + deregister-on-drain)
and the autoscale supervisor (registering in-process replicas,
deregistering retired/dead ones) speak the same two routes with the
same ``{"url": ...}`` body; this module is that call once, so the
payload can never drift between the two sides. Retry POLICY stays at
the call sites — startup registration may wait patiently for a
router that is still binding, a process-exit deregistration must
not — which is why ``post_roster`` raises on failure instead of
swallowing it."""

from __future__ import annotations

import json
import logging
import urllib.request

logger = logging.getLogger(__name__)

REGISTER_ROUTE = "/registerz"
DEREGISTER_ROUTE = "/deregisterz"


def post_roster(
    router_url: str,
    route: str,
    replica_url: str,
    timeout_s: float = 5.0,
    models=None,
) -> None:
    """POST one replica URL to a router roster route (``/registerz``
    or ``/deregisterz``). Raises on any transport/HTTP failure — the
    caller owns the retry policy. ``models`` (an iterable of model
    ids) advertises which zoo models the replica serves: the router
    only forwards ``/predict/<model>`` to replicas advertising that
    id. Omitted entirely when empty, so pre-zoo routers keep parsing
    the same ``{"url": ...}`` body they always did."""
    doc = {"url": replica_url.rstrip("/")}
    if models:
        doc["models"] = sorted(str(m) for m in models)
    body = json.dumps(doc).encode("utf-8")
    req = urllib.request.Request(
        router_url.rstrip("/") + route,
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s):
        pass


def try_deregister(
    router_url: str, replica_url: str, timeout_s: float = 5.0
) -> bool:
    """One best-effort ``/deregisterz`` (idempotent — an unknown URL
    is a no-op success). Returns False on failure instead of raising:
    every caller is mid-retirement or mid-exit and must proceed to
    the drain either way, and a dead router's roster entry dies with
    it anyway."""
    try:
        post_roster(
            router_url, DEREGISTER_ROUTE, replica_url,
            timeout_s=timeout_s,
        )
        logger.info(
            "deregistered %s from router %s", replica_url, router_url
        )
        return True
    except Exception as e:
        logger.warning(
            "could not deregister %s from router %s: %s",
            replica_url, router_url, e,
        )
        return False


__all__ = [
    "DEREGISTER_ROUTE",
    "REGISTER_ROUTE",
    "post_roster",
    "try_deregister",
]
