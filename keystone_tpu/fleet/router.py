"""Fleet router: the cross-host front door over N gateway replicas.

``EnginePool`` lifted one level: the pool's least-loaded / health /
retry topology applied to whole ``serve-gateway`` PROCESSES instead of
in-process lanes — the failover-aware frontend shape production model
servers put in front of predictable replicas (Clockwork, OSDI '20; the
request plane below it is the Orca-style gateway from PRs 3–5). A
stdlib ``http.server`` on a daemon thread, same scaffolding as the
gateway frontend (``observability/httpd.py``). Routes:

- ``POST /predict`` — forwarded VERBATIM (raw bytes, no re-encode) to
  the least-loaded ready+healthy replica
  (``fleet/registry.py ReplicaRegistry.pick``). A transport failure,
  untyped 5xx, or black-holed response is retried ONCE on another
  replica before anything reaches the client, so a single replica
  dying mid-request is invisible; typed ``Overloaded`` responses
  (429/503/504 with the ``overloaded`` body) PROPAGATE verbatim — the
  shed/expired semantics the gateway computed survive the extra hop —
  except 503-``closed`` (a draining replica), which fails over to a
  sibling first and is surfaced only when no replica can answer. An
  untyped 5xx that REPRODUCES across the retry propagates verbatim as
  the error it is (the pool's deterministic-error doctrine — a
  500-ing fleet must look like one, not like a typed shed); only when
  no replica is reachable at all does the router shed typed itself
  (503 ``overloaded``/``closed``).
- ``POST /predict/<model>`` — the model-zoo route: forwarded with the
  PATH PRESERVED to the least-loaded replica ADVERTISING that model
  id (the ``models`` list in its registration), so the replica's own
  zoo resolves the model and its typed ``unknown_model`` 404 reaches
  the client verbatim. When NO replica advertises the id, the router
  answers a typed 503 ``{"error": "no_replica_for_model",
  "model": ...}`` — a routing fact, distinct from overload.
- ``POST /registerz`` — ``{"url": "http://host:port"}``
  self-registration (what ``serve-gateway --register`` POSTs at
  startup); idempotent per URL, so re-registration is a heartbeat —
  one that also REFRESHES the optional ``"models": [...]`` advertised
  zoo model ids (``serve-gateway --zoo --register`` sends its
  registry's ids).
- ``POST /deregisterz`` — ``{"url": "http://host:port"}`` roster
  REMOVAL (idempotent): no new forwards land on the replica from the
  moment this returns, which is the first step of graceful
  retirement — the autoscale supervisor (and a draining
  ``serve-gateway`` itself, on SIGTERM) deregisters, drains
  in-flight work, then exits, instead of lingering in the roster
  until probes fail it.
- ``GET /fleetz`` — the JSON roster: per-replica health state
  (healthy / half-open / unhealthy / unreachable), readiness + the
  burn-state body, load, build info, failure forensics.
- ``GET /metrics`` — **SLO federation**: every replica's scrape plus
  the router's own registry merged into ONE exposition
  (``prometheus.merge_expositions`` — identical-label series sum, so
  N replicas of one service export one fleet-wide family and
  ``quantile_from_buckets`` over the merged ``le`` buckets is the
  TRUE fleet p99, not a quantile of quantiles). Replicas that can't
  answer the on-demand scrape contribute their last probe's cached
  body instead.
- ``GET /attributionz`` — the FLEET-TRUTH per-model device-cost
  ledger: the federated scrape's ``keystone_attr_*{model}`` samples
  (identical model labels across replicas sum) rebuilt into the same
  document each replica serves (``observability/attribution.py``).
- ``GET /driftz`` — fleet drift: every replica's
  ``keystone_drift_score{model}`` off the federated scrape (the gauge
  MAX-merges — the worst replica's drift is the fleet's); re-plan
  recommendations stay on each replica's own ``/driftz``.
- ``GET /slz`` — burn rates of the router's fleet-wide latency SLO
  (``Slo.latency_from_buckets`` over the merged replica buckets) when
  one is declared, alongside any replica-local monitors in-process.
- ``GET /tracez`` — this process's recent spans (one ``router.forward``
  span per forward attempt; retries are sibling spans with a
  ``retry_reason`` attr), same surface as the gateway's.
- ``GET /debugz?trace_id=`` — **stitched cross-process forensics**
  (``observability/stitch.py``): the router's spans for the trace plus
  each involved replica's ``/debugz`` half grafted under the
  router-hop spans, rendered as JSON (with the
  ``router_hop/queue_wait/coalesce/device/deliver`` phase
  decomposition) or one multi-process Chrome trace
  (``format=chrome``). Partial when a replica can't contribute —
  counted, never an error.

- ``GET /readyz`` — 200 while at least one replica is ready+healthy
  (the roster state rides in the body), 503 otherwise: the router is
  a routing signal for the layer above it, same contract as the
  gateway's.
- ``GET|POST /chaosz`` — the fault-injection plane, identical to the
  gateway frontend's: the fleet-level points
  ``router.replica.blackhole`` (drop a matched replica's /predict
  responses — a return-path partition), ``router.replica.partition``
  (sever the forward BEFORE it dials — the request-path partition
  the autoscale drill fires mid-scale-up), and ``router.trace.drop``
  (strip the traceparent off a forward — the partial-stitch drill)
  are armed HERE, in the router process, and fire on the forward
  path.

Distributed tracing rides the hot path: the router mints (or adopts
an inbound) W3C ``traceparent``, sends it on every forward so the
replica's whole admit → coalesce → dispatch chain shares the trace
id, and echoes ``X-Keystone-Trace`` on every /predict response —
success AND typed shed. ``--request-log`` writes the gateway's
replayable JSONL schema plus ``replica``/``attempts`` per routed
POST. Tracing is ON by default (``--no-trace`` opts out); the
``serving_router_trace_overhead`` bench row bounds its cost at
<= 1.05x p99.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlparse

from keystone_tpu.fleet.registry import ReplicaRegistry
from keystone_tpu.loadgen import faults
from keystone_tpu.observability import prometheus
from keystone_tpu.observability import slo as slo_mod
from keystone_tpu.observability.httpd import (
    BackgroundServer,
    JsonHandler,
    RequestLogWriter,
    next_post_seq,
)
from keystone_tpu.observability.registry import get_global_registry
from keystone_tpu.observability.stitch import TraceStitcher
from keystone_tpu.observability.tracing import (
    TRACEPARENT_HEADER,
    TRACE_RESPONSE_HEADER,
    format_traceparent,
    get_tracer,
    new_trace_id,
    parse_traceparent,
    tracez_document,
)

logger = logging.getLogger(__name__)

# per-attempt forward bound: must EXCEED the gateway's own
# RESULT_TIMEOUT_S (60 s — a live replica always answers within it)
# while staying under the loadgen client's lost-declaration bound, so
# a slow-but-alive replica yields a typed answer, not a lost request
FORWARD_TIMEOUT_S = 70.0

# the replica latency family the fleet SLO federates over
FLEET_LATENCY_FAMILY = "keystone_gateway_request_latency_seconds"


class ReplicaUnavailable(RuntimeError):
    """One replica could not produce a response the client should see
    YET — transport failure, untyped 5xx, black-holed response, or a
    draining replica's 503-``closed``. ``charge`` says whether the
    failure is evidence against the replica's health (a drain is
    not). Two kinds of last-resort payload ride along for when NO
    sibling can answer either: ``typed`` (a draining replica's typed
    503, surfaced verbatim) and ``untyped`` (a real error response the
    replica produced — after the retry reproduces the failure it must
    PROPAGATE as the error it is, mirroring the pool's
    deterministic-error doctrine; dressing it up as a typed shed
    would hide a 500-ing fleet from the exact invariant checker built
    to catch it)."""

    def __init__(
        self,
        detail: str,
        charge: bool = True,
        typed: Optional[Tuple[int, bytes]] = None,
        untyped: Optional[Tuple[int, bytes]] = None,
    ):
        super().__init__(detail)
        self.charge = charge
        self.typed = typed
        self.untyped = untyped


class RouterMetrics:
    """The router's own (non-federated) series, merged into
    ``/metrics`` alongside the replica scrapes."""

    def __init__(self, registry=None, router: str = "router"):
        reg = registry if registry is not None else get_global_registry()
        self.registry = reg
        self.router = router
        self._requests = reg.counter(
            "keystone_router_requests_total",
            "terminal request outcomes through the fleet router",
            ("router", "status"),
        )
        self._retries = reg.counter(
            "keystone_router_retries_total",
            "requests retried on another replica after a replica "
            "failure",
            ("router",),
        )
        self._replicas = reg.gauge(
            "keystone_router_replicas",
            "replicas known to the router, by health state",
            ("router", "state"),
        )

    def record_outcome(self, status: str) -> None:
        self._requests.inc((self.router, status))

    def record_retry(self) -> None:
        self._retries.inc((self.router,))

    def set_replica_states(self, counts: Dict[str, int]) -> None:
        for state in ("healthy", "half-open", "unhealthy", "unreachable"):
            self._replicas.set(
                float(counts.get(state, 0)), (self.router, state)
            )

    def retry_count(self) -> float:
        return self._retries.get((self.router,))

    def outcome_count(self, status: str) -> float:
        return self._requests.get((self.router, status))


class _RouterHandler(JsonHandler):
    def _send(self, code, body, content_type, headers=None) -> None:
        # every /predict response — forwarded success, propagated
        # typed shed, router-minted shed — echoes the ONE fleet-wide
        # trace id; even when the replica answered under a different
        # (self-minted) id, the ROUTER's id is the one its /debugz
        # can stitch, partially or fully
        tid = getattr(self, "_trace_id", None)
        if tid:
            headers = {**(headers or {}), TRACE_RESPONSE_HEADER: tid}
        super()._send(code, body, content_type, headers=headers)

    def _send_error_json(self, code: int, error: str, **extra) -> None:
        self._send_json({"error": error, **extra}, code=code)

    @property
    def fleet(self) -> ReplicaRegistry:
        return self.server.fleet  # type: ignore[attr-defined]

    @property
    def metrics(self) -> RouterMetrics:
        return self.server.metrics  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        path = url.path
        self._trace_id = None  # per-request (keep-alive safety)
        try:
            if path == "/readyz":
                counts = self.fleet.counts()
                self.metrics.set_replica_states(counts)
                routable = sum(
                    1
                    for r in self.fleet.replicas()
                    if r.healthy and r.ready
                )
                body = (
                    f"{'ok' if routable else 'no replica ready'} "
                    f"({routable}/{len(self.fleet)} replicas ready; "
                    f"states {json.dumps(counts, sort_keys=True)})\n"
                )
                self._send_text(200 if routable else 503, body)
            elif path == "/healthz":
                self._send_text(200, "ok\n")
            elif path == "/fleetz":
                self._send_json(self.server.fleetz(), indent=1)  # type: ignore[attr-defined]
            elif path == "/metrics":
                body = self.server.federated_metrics()  # type: ignore[attr-defined]
                self._send(
                    200, body.encode("utf-8"), prometheus.CONTENT_TYPE
                )
            elif path == "/attributionz":
                self._send_json(
                    self.server.attributionz(), indent=1  # type: ignore[attr-defined]
                )
            elif path == "/driftz":
                self._send_json(
                    self.server.driftz(), indent=1  # type: ignore[attr-defined]
                )
            elif path == "/slz":
                self._send_json(slo_mod.slz_status(), indent=1)
            elif path == "/tracez":
                q = parse_qs(url.query)
                self._send_json(
                    tracez_document(
                        get_tracer(),
                        q.get("format", [""])[0],
                        q["n"][0] if "n" in q else None,
                    ),
                    indent=1,
                )
            elif path == "/debugz":
                # the stitched cross-process forensics: this router's
                # router.forward spans + every involved replica's
                # /debugz half, grafted into one tree with the phase
                # decomposition (observability/stitch.py)
                q = parse_qs(url.query)
                code, doc = self.server.stitcher.document(  # type: ignore[attr-defined]
                    q.get("trace_id", [None])[0],
                    q.get("format", [""])[0],
                    self.server.resolve_replica_url,  # type: ignore[attr-defined]
                )
                self._send_json(doc, code=code, indent=1)
            elif path == "/chaosz":
                if not self.server.chaos_routes:  # type: ignore[attr-defined]
                    self._send_error_json(
                        404, "chaos_routes_disabled",
                        detail="started with --no-chaosz",
                    )
                else:
                    self._send_json(
                        faults.get_injector().status(), indent=1
                    )
            else:
                self._send_text(
                    404,
                    "not found; try /predict /predict/<model> "
                    "/registerz /deregisterz /fleetz /readyz /healthz "
                    "/metrics /attributionz /driftz /slz /tracez "
                    "/debugz /chaosz\n",
                )
        except Exception as e:
            logger.exception("router GET error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def do_POST(self) -> None:  # noqa: N802 (stdlib handler API)
        path = urlparse(self.path).path
        self._trace_id = None  # _predict adopts/mints; see _send
        try:
            if path == "/predict" or path.startswith("/predict/"):
                model_id = path[len("/predict/"):] if (
                    path.startswith("/predict/")
                ) else None
                self._predict(model_id or None)
            elif path == "/registerz":
                self._registerz()
            elif path == "/deregisterz":
                self._deregisterz()
            elif path == "/chaosz":
                self._chaosz()
            else:
                self._send_text(
                    404, "not found; try /predict /predict/<model> "
                    "/registerz /deregisterz /chaosz\n"
                )
        except Exception as e:
            logger.exception("router POST error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(length) if length else b""

    # -- the fleet hot path -------------------------------------------------

    def _log_request(
        self,
        status: int,
        latency_s: float,
        attempts: int,
        replica_name: Optional[str],
        body: bytes,
        error: Optional[str] = None,
    ) -> None:
        """One structured JSON line per routed POST (``--request-log``)
        — the GATEWAY's schema (``ts/path/status/latency_ms/lane/
        trace_id/n_rows/shape/deadline_ms/post_seq``) plus the fleet
        fields ``replica`` (who served it) and ``attempts``, so a
        fleet recording replays through the same ``loadgen/trace.py``
        parser as a single-gateway one."""
        n_rows = shape = deadline_ms = None
        try:
            doc = json.loads(body or b"{}")
            instances = doc.get("instances")
            if isinstance(instances, list) and instances:
                n_rows = len(instances)
                first, dims = instances[0], []
                while isinstance(first, list):
                    dims.append(len(first))
                    first = first[0] if first else None
                shape = dims
            deadline_ms = doc.get("deadline_ms")
        except (ValueError, TypeError):
            pass  # a malformed body still deserves its outcome line
        line = {
            "ts": round(self._t_wall, 6),
            "path": "/predict",
            "status": status,
            "latency_ms": round(latency_s * 1e3, 3),
            "lane": None,  # schema parity: lanes are a replica detail
            "trace_id": self._trace_id,
            "n_rows": n_rows,
            "shape": shape,
            "deadline_ms": deadline_ms,
            "post_seq": next_post_seq(),
            "replica": replica_name,
            "attempts": attempts,
        }
        if error is not None:
            line["error"] = error
        self.server.write_request_log(line)  # type: ignore[attr-defined]

    def _predict(self, model_id: Optional[str] = None) -> None:
        body = self._read_body()
        t0 = time.perf_counter()
        self._t_wall = time.time()  # arrival clock for the request log
        # one fleet-wide trace id per request: adopt the client's W3C
        # traceparent if it sent one, mint otherwise (tracing on) —
        # every forward attempt below is a SIBLING span under this id
        # and the header the replica receives carries it downstream
        tracer = get_tracer()
        ctx = parse_traceparent(self.headers.get(TRACEPARENT_HEADER))
        if ctx is not None:
            self._trace_id = ctx.trace_id
        elif tracer.enabled:
            self._trace_id = new_trace_id()
        request_log = self.server.request_log  # type: ignore[attr-defined]
        if not body:
            if request_log:
                # one line per routed POST means THIS one too — a
                # replay that silently loses client mistakes can't
                # reproduce the client's offered load
                self._log_request(
                    400, time.perf_counter() - t0, 0, None, body,
                    error="empty /predict body",
                )
            self._send_error_json(
                400, "bad_request", detail="empty /predict body"
            )
            return
        max_retries = self.server.max_retries  # type: ignore[attr-defined]
        tried: List = []
        typed_fallback: Optional[Tuple[int, bytes]] = None
        untyped_fallback: Optional[Tuple[int, bytes]] = None
        retry_reason: Optional[str] = None
        for _attempt in range(max_retries + 1):
            # a named model only routes to replicas ADVERTISING it
            # (registration's "models" list) — the health fallbacks
            # inside pick() never widen past the advertiser set
            replica = self.fleet.pick(exclude=tried, model=model_id)
            if replica is None:
                break
            tried.append(replica)
            if _attempt > 0:
                # counted HERE, when a second attempt actually
                # dispatches — an exhausted pick() is not a retry
                self.metrics.record_retry()
            # one router.forward span per ATTEMPT: retries are sibling
            # spans (same trace, no parent) whose retry_reason attr
            # says why the previous hop failed — the stitched tree
            # shows the failover, not just the attempt that won
            span = tracer.start_span(
                "router.forward",
                trace_id=self._trace_id,
                router=self.server.router_name,  # type: ignore[attr-defined]
                replica=replica.name,
                attempt=_attempt,
            )
            if retry_reason is not None:
                span.set_attr("retry_reason", retry_reason)
            traceparent = None
            if self._trace_id is not None:
                # tracing off but an inbound context present: relay
                # the caller's header verbatim (a formatted one would
                # carry the null span's all-zero parent id, which the
                # replica must reject per the W3C spec)
                traceparent = (
                    format_traceparent(self._trace_id, span.span_id)
                    if span.span_id is not None
                    else self.headers.get(TRACEPARENT_HEADER)
                )
                # chaos point: strip the trace context off this
                # forward (router.trace.drop) — the replica must fall
                # back to a self-minted id and serve normally, and
                # the stitch must degrade to a counted partial tree
                if faults.armed() and faults.fire(
                    "router.trace.drop",
                    {"replica": replica.name, "index": replica.index},
                ) is not None:
                    span.set_attr("traceparent_dropped", True)
                    traceparent = None
            try:
                status, payload, ctype = self._forward(
                    replica, body, traceparent,
                    path=(
                        "/predict" if model_id is None
                        else f"/predict/{model_id}"
                    ),
                )
                span.set_attr("status", status)
            except ReplicaUnavailable as e:
                retry_reason = f"{replica.name}: {e}"
                span.set_attr("error", str(e))
                if e.charge:
                    replica.mark_failed(str(e))
                if e.typed is not None:
                    typed_fallback = e.typed
                if e.untyped is not None:
                    untyped_fallback = e.untyped
                if _attempt < max_retries:
                    logger.warning(
                        "router: replica %s failed a request (%s); "
                        "retrying on another replica",
                        replica.name, e,
                    )
                continue
            except Exception as e:
                # transport-layer surprises urllib does NOT wrap as
                # OSError (http.client.BadStatusLine, IncompleteRead,
                # ...) propagate to do_POST's 500 handler — but the
                # attempt span must still record (or the forensics
                # for exactly the failed request lose its forward
                # hop), and the request log still gets its
                # one-line-per-POST outcome
                span.set_attr("error", f"{type(e).__name__}: {e}")
                if request_log:
                    self._log_request(
                        500, time.perf_counter() - t0, len(tried),
                        replica.name, body,
                        error=f"{type(e).__name__}: {e}",
                    )
                raise
            finally:
                # every exit path — success, retry, raise — ends the
                # span: a leaked _ActiveSpan stays on this handler
                # thread's stack and never reaches the ring/exporter
                tracer.end_span(span)
            replica.mark_ok()
            self.metrics.record_outcome(
                "ok" if status < 400
                else "shed" if status in (429, 503, 504)
                else "error"
            )
            if request_log:
                self._log_request(
                    status, time.perf_counter() - t0, len(tried),
                    replica.name, body,
                )
            self._send(
                status, payload,
                ctype or "application/json; charset=utf-8",
            )
            return
        if untyped_fallback is not None:
            # the failure REPRODUCED (or had no sibling to disprove
            # it): a real error response propagates as the error it
            # is — the pool's deterministic-error doctrine. Masking
            # it as a typed shed would hide a 500-ing fleet from the
            # invariant checker built to catch exactly that.
            status, payload = untyped_fallback
            self.metrics.record_outcome("error")
            if request_log:
                self._log_request(
                    status, time.perf_counter() - t0, len(tried),
                    None, body, error=retry_reason,
                )
            self._send(
                status, payload, "application/json; charset=utf-8"
            )
            return
        if typed_fallback is not None:
            # every live replica is draining: surface THEIR typed
            # answer (503 closed), not a router-invented error
            status, payload = typed_fallback
            self.metrics.record_outcome("shed")
            if request_log:
                self._log_request(
                    status, time.perf_counter() - t0, len(tried),
                    None, body, error="closed",
                )
            self._send(
                status, payload, "application/json; charset=utf-8"
            )
            return
        if model_id is not None and not tried:
            # a roster may exist yet hold NO advertiser for this model
            # — that is a routing fact, not overload, and the typed
            # body says which model the fleet can't place
            self.metrics.record_outcome("shed")
            if request_log:
                self._log_request(
                    503, time.perf_counter() - t0, 0, None, body,
                    error=f"no replica advertises model {model_id}",
                )
            self._send_json(
                {
                    "error": "no_replica_for_model",
                    "model": model_id,
                    "detail": (
                        f"none of {len(self.fleet)} replicas "
                        f"advertises model {model_id!r}"
                    ),
                },
                code=503,
            )
            return
        self.metrics.record_outcome("shed")
        if request_log:
            self._log_request(
                503, time.perf_counter() - t0, len(tried), None, body,
                error=retry_reason or "no replica available",
            )
        self._send_json(
            {
                "error": "overloaded",
                "reason": "closed",
                "detail": (
                    f"no replica available (tried {len(tried)} of "
                    f"{len(self.fleet)})"
                ),
            },
            code=503,
        )

    def _forward(
        self,
        replica,
        body: bytes,
        traceparent: Optional[str] = None,
        path: str = "/predict",
    ) -> Tuple[int, bytes, str]:
        """POST the raw /predict body to one replica (plus the W3C
        ``traceparent`` when the request is traced — the replica
        adopts its trace id). ``path`` is PRESERVED on the forward —
        a ``/predict/<model>`` request reaches the replica under the
        same model id the client named, so the replica's zoo (not the
        router) owns model resolution. Returns ``(status, payload,
        content_type)`` for any response the client should see
        verbatim; raises ``ReplicaUnavailable`` for outcomes worth
        trying another replica for."""
        # chaos point: an armed router.replica.partition severs the
        # router<->replica link BEFORE the forward is even dialed —
        # the request-path half of a network partition (the replica
        # never sees the request, unlike blackhole's return-path
        # drop). The retry + health machinery must absorb it exactly
        # like a connection refusal: fail over to a sibling, charge
        # the replica. Unarmed: one attribute read.
        if faults.armed() and faults.fire(
            "router.replica.partition",
            {"replica": replica.name, "index": replica.index},
        ) is not None:
            raise ReplicaUnavailable(
                "router.replica.partition severed the forward to "
                f"{replica.name}"
            )
        headers = {"Content-Type": "application/json"}
        if traceparent is not None:
            headers[TRACEPARENT_HEADER] = traceparent
        req = urllib.request.Request(
            replica.url + path,
            data=body,
            headers=headers,
            method="POST",
        )
        timeout = self.server.forward_timeout_s  # type: ignore[attr-defined]
        replica.begin_request()
        try:
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    status = resp.status
                    payload = resp.read()
                    ctype = resp.headers.get("Content-Type")
            except urllib.error.HTTPError as e:
                status = e.code
                payload = e.read() or b""
                ctype = e.headers.get("Content-Type")
                try:
                    doc = json.loads(payload or b"{}")
                except ValueError:
                    doc = {}
                typed = (
                    status in (429, 503, 504)
                    and doc.get("error") == "overloaded"
                )
                if not typed and status >= 500:
                    # an untyped 5xx is replica-specific until a
                    # sibling reproduces it — same doctrine as the
                    # pool's retry-to-another-lane. The raw response
                    # rides along: if every sibling fails too, THIS
                    # error surfaces verbatim, never a fake typed shed
                    raise ReplicaUnavailable(
                        f"untyped {status} from {replica.name}",
                        untyped=(status, payload),
                    ) from e
                if typed and doc.get("reason") == "closed":
                    # draining: fail over (a healthy sibling should
                    # answer), keep the typed 503 as the last resort,
                    # and charge nothing — draining is lifecycle, not
                    # failure
                    raise ReplicaUnavailable(
                        f"{replica.name} draining (typed closed)",
                        charge=False,
                        typed=(status, payload),
                    ) from e
                # typed shed (429/504) or a client 4xx: the gateway's
                # verdict about THIS request — propagate verbatim
            except (TimeoutError, OSError) as e:
                # URLError (connection refused/reset) and socket
                # timeouts are both OSError here: the replica process
                # never produced an answer
                raise ReplicaUnavailable(
                    f"{replica.name}: {type(e).__name__}: {e}"
                ) from e
        finally:
            replica.end_request()
        # chaos point: an armed router.replica.blackhole (typically
        # matched to one replica by name or registration index) drops
        # the matched replica's responses AFTER the replica did the
        # work — a return-path partition. The router must treat it
        # exactly like a transport failure: retry elsewhere, charge
        # the replica's health. Unarmed: one attribute read, no ctx
        # dict built.
        if faults.armed() and faults.fire(
            "router.replica.blackhole",
            {"replica": replica.name, "index": replica.index},
        ) is not None:
            raise ReplicaUnavailable(
                "router.replica.blackhole dropped a response from "
                f"{replica.name}"
            )
        return status, payload, ctype

    # -- membership + chaos surfaces ----------------------------------------

    def _registerz(self) -> None:
        try:
            doc = json.loads(self._read_body() or b"{}")
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        url = doc.get("url")
        if not isinstance(url, str):
            self._send_error_json(
                400, "bad_request",
                detail='want {"url": "http://host:port"}',
            )
            return
        models = doc.get("models")
        if models is not None and (
            not isinstance(models, list)
            or not all(isinstance(m, str) for m in models)
        ):
            self._send_error_json(
                400, "bad_request",
                detail='"models" must be a list of model-id strings',
            )
            return
        try:
            replica, created = self.fleet.add(
                url, source="registered", models=models
            )
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        self._send_json(
            {
                "registered": True,
                "created": created,
                "index": replica.index,
                "replicas": len(self.fleet),
                "probe_interval_s": self.fleet.probe_interval_s,
                "models": sorted(replica.models),
            }
        )

    def _deregisterz(self) -> None:
        """Roster removal (idempotent): the graceful-retirement half
        of ``/registerz``. A deregistered replica gets no new
        forwards; in-flight forwards finish normally."""
        try:
            doc = json.loads(self._read_body() or b"{}")
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        url = doc.get("url")
        if not isinstance(url, str):
            self._send_error_json(
                400, "bad_request",
                detail='want {"url": "http://host:port"}',
            )
            return
        try:
            removed = self.fleet.remove(url)
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        self._send_json(
            {"deregistered": removed, "replicas": len(self.fleet)}
        )

    def _chaosz(self) -> None:
        """Arm/disarm fault points in the ROUTER process (the fleet
        hot path's chaos surface; same contract as the gateway
        frontend's)."""
        if not self.server.chaos_routes:  # type: ignore[attr-defined]
            self._send_error_json(
                404, "chaos_routes_disabled",
                detail="started with --no-chaosz",
            )
            return
        injector = faults.get_injector()
        try:
            doc = json.loads(self._read_body() or b"{}")
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        if "arm" in doc:
            spec = doc["arm"]
            if not isinstance(spec, dict) or "point" not in spec:
                self._send_error_json(
                    400, "bad_request",
                    detail='arm wants {"point": ..., [count/delay_ms/'
                           'for_s/match]}',
                )
                return
            spec = dict(spec)
            point = spec.pop("point")
            if point not in faults.FAULT_POINTS:
                self._send_error_json(
                    400, "unknown_fault_point", point=point,
                    known=sorted(faults.FAULT_POINTS),
                )
                return
            try:
                injector.arm(point, **spec)
            except (TypeError, ValueError) as e:
                self._send_error_json(400, "bad_request", detail=str(e))
                return
        elif "disarm" in doc:
            point = doc["disarm"]
            if point == "*":
                injector.disarm_all()
            else:
                injector.disarm(point)
        else:
            self._send_error_json(
                400, "bad_request",
                detail='want {"arm": {...}} or {"disarm": "<point>|*"}',
            )
            return
        self._send_json(injector.status(), indent=1)


class RouterServer(BackgroundServer):
    """The fleet router over one ``ReplicaRegistry``. ``start()``
    binds, serves on a daemon thread, and starts the registry's
    background health probes; ``stop()`` shuts both down."""

    handler_cls = _RouterHandler
    thread_name = "keystone-router-http"

    def __init__(
        self,
        replicas: Sequence[str] = (),
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        name: str = "router",
        registry=None,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 5.0,
        unhealthy_after: Optional[int] = None,
        recovery_after_s: Optional[float] = None,
        forward_timeout_s: float = FORWARD_TIMEOUT_S,
        max_retries: int = 1,
        chaos_routes: bool = True,
        request_log: Any = False,
        stitch_timeout_s: float = 5.0,
        slo_latency_s: Optional[float] = None,
        slo_target: float = 0.99,
        slo_fast_window_s: float = 60.0,
        slo_slow_window_s: float = 1800.0,
        slo_sample_interval_s: float = 5.0,
    ):
        super().__init__(port=port, host=host)
        if max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {max_retries}"
            )
        self.name = name
        self.registry = (
            registry if registry is not None else get_global_registry()
        )
        self.metrics = RouterMetrics(registry=self.registry, router=name)
        # ``--request-log`` parity with the gateway: one JSON line per
        # routed POST in the same replayable schema (plus replica +
        # attempts), through the shared writer
        self._request_log = RequestLogWriter(request_log)
        self.request_log = self._request_log.enabled
        # the cross-process forensics engine behind GET /debugz
        self.stitcher = TraceStitcher(
            name=name,
            registry=self.registry,
            fetch_timeout_s=stitch_timeout_s,
        )
        kwargs: Dict[str, Any] = {}
        if unhealthy_after is not None:
            kwargs["unhealthy_after"] = unhealthy_after
        if recovery_after_s is not None:
            kwargs["recovery_after_s"] = recovery_after_s
        self.fleet = ReplicaRegistry(
            replicas,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            name=name,
            **kwargs,
        )
        self.forward_timeout_s = float(forward_timeout_s)
        self.max_retries = int(max_retries)
        self.chaos_routes = bool(chaos_routes)
        self._started_t = time.time()
        # -- the fleet-wide SLO (federated burn rates at /slz) -------------
        self.slo_monitor: Optional[slo_mod.SloMonitor] = None
        self._slo_sample_interval_s = float(slo_sample_interval_s)
        if slo_latency_s is not None:
            self.slo_monitor = slo_mod.SloMonitor(
                fast_window_s=slo_fast_window_s,
                slow_window_s=slo_slow_window_s,
                registry=self.registry,
            )
            self.slo_monitor.add(
                slo_mod.Slo.latency_from_buckets(
                    f"{name}:fleet_latency",
                    self.federated_latency_buckets,
                    threshold_s=slo_latency_s,
                    target=slo_target,
                )
            )

    # -- federation ---------------------------------------------------------

    def federated_latency_buckets(self) -> List[Tuple[float, float]]:
        """The fleet-wide cumulative latency buckets: every replica's
        cached ``keystone_gateway_request_latency_seconds`` buckets
        merged (label-agnostic — distinctly-named gateways still sum
        into one fleet distribution)."""
        return prometheus.merge_histograms(
            [
                prometheus.histogram_buckets(text, FLEET_LATENCY_FAMILY)
                for text in self.fleet.scrapes()
            ]
        )

    def federated_metrics(self) -> str:
        """The ``/metrics`` body: on-demand replica scrapes (cached
        fallback for unreachable replicas) + the router's own
        registry, merged into one exposition. Conflicting histogram
        layouts drop (logged) rather than failing the whole fleet
        scrape."""
        own = prometheus.render(self.registry.collect())
        return prometheus.merge_expositions(
            [own] + self.fleet.fresh_scrapes(), on_conflict="drop"
        )

    def attributionz(self, top_k: int = 10) -> Dict:
        """The FLEET-TRUTH ``/attributionz``: the per-model cost-ledger
        document rebuilt from the federated scrape, so identical model
        labels across replicas have already SUMMED — the totals are the
        fleet's, not this process's."""
        from keystone_tpu.observability.attribution import (
            attribution_from_samples,
        )

        return attribution_from_samples(
            prometheus.parse_samples(self.federated_metrics()),
            top_k=top_k,
        )

    def driftz(self) -> Dict:
        """The fleet ``/driftz``: every replica's
        ``keystone_drift_score{model}`` off the federated scrape (the
        gauge MAX-merges — the worst replica's drift IS the fleet's).
        Re-plan recommendations stay replica-local (each replica's
        ``/driftz`` owns its zoo's plan); this surface names who is
        drifting fleet-wide."""
        from keystone_tpu.observability.drift import DEFAULT_THRESHOLD

        scores: Dict[str, float] = {}
        for name, labels, value in prometheus.parse_samples(
            self.federated_metrics()
        ):
            if name != "keystone_drift_score":
                continue
            model = labels.get("model")
            if model is not None:
                scores[model] = max(scores.get(model, value), value)
        return {
            "threshold": DEFAULT_THRESHOLD,
            "scores": {m: round(s, 4) for m, s in sorted(scores.items())},
            "drifted": sorted(
                m for m, s in scores.items() if s > DEFAULT_THRESHOLD
            ),
            "note": (
                "federated MAX of keystone_drift_score per model; "
                "re-plan recommendations live on each replica's /driftz"
            ),
        }

    def fleetz(self) -> Dict:
        """The ``/fleetz`` document: router identity + the roster."""
        doc = self.fleet.roster()
        counts = doc["counts"]
        self.metrics.set_replica_states(counts)
        doc["router"] = {
            "name": self.name,
            "uptime_s": round(time.time() - self._started_t, 1),
            "max_retries": self.max_retries,
            "forward_timeout_s": self.forward_timeout_s,
            "slo": (
                [s.name for s in self.slo_monitor.slos]
                if self.slo_monitor is not None
                else []
            ),
        }
        return doc

    # -- lifecycle ----------------------------------------------------------

    def resolve_replica_url(self, name: str) -> Optional[str]:
        """Replica NAME (a ``router.forward`` span's ``replica`` attr)
        -> base URL via the registry — the stitcher only ever dials
        replicas the fleet actually knows, never a URL a span claims."""
        replica = self.fleet.find_by_name(name)
        return replica.url if replica is not None else None

    def write_request_log(self, line: Dict[str, Any]) -> None:
        self._request_log.write(line)

    def _configure(self, httpd) -> None:
        httpd.fleet = self.fleet
        httpd.metrics = self.metrics
        httpd.max_retries = self.max_retries
        httpd.forward_timeout_s = self.forward_timeout_s
        httpd.chaos_routes = self.chaos_routes
        httpd.federated_metrics = self.federated_metrics
        httpd.fleetz = self.fleetz
        httpd.attributionz = self.attributionz
        httpd.driftz = self.driftz
        httpd.router_name = self.name
        httpd.request_log = self.request_log
        httpd.write_request_log = self.write_request_log
        httpd.stitcher = self.stitcher
        httpd.resolve_replica_url = self.resolve_replica_url

    def start(self) -> "RouterServer":
        super().start()
        self.fleet.start()
        if self.slo_monitor is not None:
            self.slo_monitor.start(self._slo_sample_interval_s)
        return self

    def stop(self) -> None:
        if self.slo_monitor is not None:
            self.slo_monitor.stop()
        self.fleet.stop()
        super().stop()
        self._request_log.close()


def main(argv=None) -> int:
    """``python -m keystone_tpu serve-router --replica URL ...`` —
    stand up the fleet tier over running ``serve-gateway`` replicas
    (or an empty roster that fills via ``--register``
    self-registration)."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-router", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--router-port", "--port", dest="port", type=int,
                    default=0, help="bind port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="URL",
                    help="a gateway replica's base URL (repeatable); "
                    "replicas can also self-register via POST "
                    "/registerz (serve-gateway --register)")
    ap.add_argument("--probe-interval", type=float, default=2.0,
                    help="seconds between background health probes")
    ap.add_argument("--probe-timeout", type=float, default=5.0)
    ap.add_argument("--unhealthy-after", type=int, default=None,
                    help="consecutive request failures that bench a "
                    "replica (default 3, mirroring the lane pool)")
    ap.add_argument("--recovery-after", type=float, default=None,
                    help="seconds a benched replica sits out before "
                    "half-open probe traffic (default 5)")
    ap.add_argument("--forward-timeout", type=float,
                    default=FORWARD_TIMEOUT_S)
    ap.add_argument("--max-retries", type=int, default=1,
                    help="retries on ANOTHER replica after a replica "
                    "failure before the error surfaces")
    ap.add_argument("--slo-latency-ms", type=float, default=None,
                    help="declare a FLEET-WIDE latency SLO at this "
                    "threshold: burn rates computed over the "
                    "federated le buckets, served at /slz")
    ap.add_argument("--slo-target", type=float, default=0.99)
    ap.add_argument("--no-chaosz", action="store_true",
                    help="disable the /chaosz fault-injection routes "
                    "on this router")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable distributed tracing: no "
                    "router.forward spans, no W3C traceparent "
                    "propagation to replicas, no X-Keystone-Trace "
                    "echo, no /debugz stitching (default ON — the "
                    "serving_router_trace_overhead bench row bounds "
                    "the cost at <= 1.05x p99)")
    ap.add_argument("--request-log", nargs="?", const=True,
                    default=False, metavar="FILE",
                    help="one structured JSON line per routed "
                    "/predict (the gateway's replayable schema plus "
                    "replica + attempts). Bare flag: stdout; with "
                    "FILE: append line-buffered JSONL there")
    args = ap.parse_args(argv)
    if not args.no_trace:
        # the fleet's forensic chain — traceparent propagation, the
        # stitched /debugz, phase decomposition — keys off spans, so
        # the router traces by default
        from keystone_tpu.observability import enable_tracing

        enable_tracing()
    server = RouterServer(
        args.replica,
        port=args.port,
        host=args.host,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        unhealthy_after=args.unhealthy_after,
        recovery_after_s=args.recovery_after,
        forward_timeout_s=args.forward_timeout,
        max_retries=args.max_retries,
        chaos_routes=not args.no_chaosz,
        request_log=args.request_log,
        slo_latency_s=(
            args.slo_latency_ms / 1e3
            if args.slo_latency_ms is not None else None
        ),
        slo_target=args.slo_target,
    ).start()
    # chaos experiments can pre-arm fleet fault points from the
    # environment (KEYSTONE_FAULTS="router.replica.blackhole=..."),
    # same contract as the serving CLIs
    faults.arm_from_env()
    # the machine-parseable bound-address line FIRST (smoke scripts
    # and drills launch with --port 0 and read this, no port races),
    # then the human summary
    print(
        json.dumps(
            {
                "listening": server.url().rstrip("/"),
                "role": "router",
                "replicas": [r.url for r in server.fleet.replicas()],
            }
        ),
        flush=True,
    )
    print(
        f"router: {server.url()} (POST /predict, POST /registerz, "
        "POST /deregisterz, GET /fleetz, GET /readyz, GET /metrics, "
        "GET /attributionz, GET /driftz, GET /slz, GET /tracez, "
        "GET /debugz?trace_id=, GET|POST /chaosz)",
        flush=True,
    )
    stop = threading.Event()

    def handle(signum, frame):
        logger.info("router: signal %d, stopping", signum)
        stop.set()

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


__all__ = [
    "FORWARD_TIMEOUT_S",
    "ReplicaUnavailable",
    "RouterMetrics",
    "RouterServer",
    "main",
]
