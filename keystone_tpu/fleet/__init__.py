"""Fleet tier: cross-host serving over N gateway processes.

PRs 1–5 built a complete single-host request plane (compiled bucketed
engines behind micro-batchers, shared-nothing ``EnginePool`` lanes,
admission control, one HTTP gateway). This package is the first
multi-process layer above it — the ``EnginePool`` topology lifted to
HTTP distance, where a replica is a whole ``serve-gateway`` process:

- ``ReplicaRegistry`` / ``Replica`` (registry.py): membership (static
  ``--replica`` URLs + ``POST /registerz`` self-registration),
  background ``/readyz`` health probes (burn-state body and the
  ``X-Keystone-Load`` header included), scraped load, and request-path
  health with half-open recovery mirroring ``Lane.healthy``.
- ``RouterServer`` (router.py): least-loaded routing with
  retry-once-on-another-replica, typed ``Overloaded`` propagation
  (429/504/503 semantics survive the extra hop), **SLO federation**
  (``/metrics`` merges every replica's scrape so ``le``-bucket
  quantiles are true fleet quantiles; ``/slz`` burns a fleet-wide
  latency SLO over the merged buckets), the ``/fleetz`` roster, and
  the ``router.replica.blackhole`` chaos point on the forward path.

CLI: ``python -m keystone_tpu serve-router --replica URL ...``;
drill: ``bin/smoke-fleet.sh``; regression row:
``serving_router_failover`` (``serve-bench --fleet-only``).
"""

from keystone_tpu.fleet.registry import Replica, ReplicaRegistry
from keystone_tpu.fleet.router import (
    ReplicaUnavailable,
    RouterMetrics,
    RouterServer,
)

__all__ = [
    "Replica",
    "ReplicaRegistry",
    "ReplicaUnavailable",
    "RouterMetrics",
    "RouterServer",
]
