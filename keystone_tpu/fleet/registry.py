"""Replica registry: the fleet router's membership + health + load map.

``EnginePool`` keeps N shared-nothing lanes behind one ``submit()``;
this module is the same topology one level up, where a "lane" is a
whole ``serve-gateway`` PROCESS reachable over HTTP. A ``Replica``
mirrors ``gateway/pool.py Lane``'s accounting at network distance:

- **load** — the replica's scraped queue-depth + in-flight gauges
  (or the cheaper ``X-Keystone-Load`` header its ``/readyz`` carries)
  plus the router's own in-flight count toward it, so least-loaded
  routing stays honest between probe ticks;
- **health, two-layer** — *probe liveness* (did the last background
  ``/readyz`` probe reach the process at all) AND *request health*
  (consecutive request-path failures with half-open recovery,
  mirroring ``Lane.healthy``: ``unhealthy_after`` consecutive
  failures bench the replica until ``recovery_after_s`` elapses, then
  it gets probe traffic again and ONE successful request fully
  restores it). The layers are deliberately separate: a replica whose
  ``/readyz`` answers but whose ``/predict`` responses are being
  black-holed (``router.replica.blackhole``, a return-path partition)
  must stay benched on request evidence — a passing probe may not
  overrule failing traffic;
- **readiness** — the replica's own routing signal (``/readyz`` 200
  vs 503-draining), carried verbatim including the burn-state body so
  ``/fleetz`` shows WHY a replica is backing traffic off.

``ReplicaRegistry`` owns the set (static ``--replica`` URLs plus
``POST /registerz`` self-registration, deduped by URL), the
least-loaded pick with the pool's availability-over-purity fallback,
and the background probe loop. Lock discipline: the registry lock
guards ONLY the membership dict — probes run on their own daemon
thread and every HTTP call happens outside any lock (the
blocking-under-lock rule holds at fleet scale too).
"""

from __future__ import annotations

import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from keystone_tpu.observability import prometheus

logger = logging.getLogger(__name__)

# request-path health thresholds, mirroring gateway/pool.py Lane:
# consecutive failures that bench a replica, and how long it sits out
# before the router half-opens it again
UNHEALTHY_AFTER = 3
RECOVERY_AFTER_S = 5.0

# the load gauges a replica's scrape contributes to its routing load
_LOAD_FAMILIES = (
    "keystone_gateway_queue_depth",
    "keystone_gateway_inflight",
)


def _validate_replica_url(url: str) -> str:
    parsed = urlparse(url)
    if parsed.scheme not in ("http", "https") or not parsed.netloc:
        raise ValueError(
            f"replica url must be http(s)://host:port, got {url!r}"
        )
    return url.rstrip("/")


class Replica:
    """One gateway process behind the router (see module docstring)."""

    def __init__(
        self,
        url: str,
        index: int,
        source: str = "static",
        unhealthy_after: int = UNHEALTHY_AFTER,
        recovery_after_s: float = RECOVERY_AFTER_S,
    ):
        self.url = _validate_replica_url(url)
        self.name = urlparse(self.url).netloc
        self.index = index
        self.source = source
        self.unhealthy_after = int(unhealthy_after)
        self.recovery_after_s = float(recovery_after_s)
        self.registered_t = time.time()
        self._lock = threading.Lock()
        # request-path health (mirrors Lane; ONLY the request path
        # writes these — a passing probe must not overrule failing
        # traffic, see module docstring)
        self._consecutive_failures = 0  # guarded-by: _lock
        self._last_failure_t = 0.0  # guarded-by: _lock
        self._last_failure_detail = None  # guarded-by: _lock
        # probe liveness + readiness (the background probe writes these)
        self._probe_alive = True  # guarded-by: _lock
        self._ready = False  # guarded-by: _lock
        self._ready_detail = "never probed"  # guarded-by: _lock
        self._last_probe_t = None  # guarded-by: _lock
        # routing load: replica-reported + router-local in-flight
        self._scraped_load = 0.0  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        # federation inputs cached from the last probe scrape
        self._last_scrape = None  # guarded-by: _lock
        self._build: Dict[str, str] = {}  # guarded-by: _lock
        # zoo model ids this replica advertises (registration +
        # heartbeat refreshes); empty = pre-zoo replica, which only
        # receives bare-/predict traffic
        self._models: frozenset = frozenset()  # guarded-by: _lock

    # -- routing signals ----------------------------------------------------

    @property
    def healthy(self) -> bool:
        with self._lock:
            if not self._probe_alive:
                return False
            if self._consecutive_failures < self.unhealthy_after:
                return True
            # half-open: after the cool-down the replica gets probe
            # traffic again; one request success fully restores it
            return (
                time.perf_counter() - self._last_failure_t
                > self.recovery_after_s
            )

    @property
    def state(self) -> str:
        """``/fleetz``'s one-word verdict: ``unreachable`` (probe
        can't reach the process), ``unhealthy`` (benched on request
        failures), ``half-open`` (cool-down elapsed, next request is
        the probe), or ``healthy``."""
        with self._lock:
            if not self._probe_alive:
                return "unreachable"
            if self._consecutive_failures < self.unhealthy_after:
                return "healthy"
            if (
                time.perf_counter() - self._last_failure_t
                > self.recovery_after_s
            ):
                return "half-open"
            return "unhealthy"

    @property
    def ready(self) -> bool:
        with self._lock:
            return self._ready

    @property
    def load(self) -> float:
        """Routing load: the replica's last-reported queue depth +
        in-flight, plus requests THIS router currently has open
        against it (covers the gap between probe ticks)."""
        with self._lock:
            return self._scraped_load + self._inflight

    @property
    def cached_scrape(self) -> Optional[str]:
        with self._lock:
            return self._last_scrape

    @property
    def models(self) -> frozenset:
        with self._lock:
            return self._models

    def set_models(self, models) -> None:
        with self._lock:
            self._models = frozenset(str(m) for m in models)

    def advertises(self, model: str) -> bool:
        with self._lock:
            return model in self._models

    # -- request-path accounting (the router's forward path) ----------------

    def begin_request(self) -> None:
        with self._lock:
            self._inflight += 1

    def end_request(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def mark_ok(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._last_failure_detail = None

    def mark_failed(self, detail: Optional[str] = None) -> None:
        with self._lock:
            self._consecutive_failures += 1
            self._last_failure_t = time.perf_counter()
            if detail is not None:
                self._last_failure_detail = detail

    # -- probe results (the registry's probe thread) ------------------------

    def record_probe(
        self,
        alive: bool,
        ready: bool = False,
        detail: str = "",
        load: Optional[float] = None,
        scrape: Optional[str] = None,
        build: Optional[Dict[str, str]] = None,
    ) -> None:
        with self._lock:
            self._probe_alive = alive
            self._ready = ready
            self._ready_detail = detail
            self._last_probe_t = time.time()
            if load is not None:
                self._scraped_load = float(load)
            if scrape is not None:
                self._last_scrape = scrape
            if build:
                self._build = dict(build)

    def record_scrape(self, scrape: str) -> None:
        """Refresh only the cached federation input (an on-demand
        ``/metrics`` pull must not overwrite the probe's readiness
        verdict or its burn-state detail)."""
        with self._lock:
            self._last_scrape = scrape

    # -- introspection ------------------------------------------------------

    def status(self) -> Dict:
        """One ``/fleetz`` roster row."""
        with self._lock:
            consecutive = self._consecutive_failures
            row = {
                "url": self.url,
                "name": self.name,
                "index": self.index,
                "source": self.source,
                "ready": self._ready,
                "ready_detail": self._ready_detail,
                "load": self._scraped_load + self._inflight,
                "router_inflight": self._inflight,
                "consecutive_failures": consecutive,
                "last_failure": self._last_failure_detail,
                "last_probe_age_s": (
                    round(time.time() - self._last_probe_t, 2)
                    if self._last_probe_t is not None
                    else None
                ),
                "build": dict(self._build),
                "models": sorted(self._models),
            }
        # state/healthy re-take the lock; cheap, and keeps one
        # source of truth for the half-open arithmetic
        row["state"] = self.state
        row["healthy"] = self.healthy
        return row


class ReplicaRegistry:
    """The router's replica set + background health probes."""

    def __init__(
        self,
        urls: Sequence[str] = (),
        *,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 5.0,
        unhealthy_after: int = UNHEALTHY_AFTER,
        recovery_after_s: float = RECOVERY_AFTER_S,
        name: str = "router",
    ):
        if probe_interval_s <= 0:
            raise ValueError(
                f"probe_interval_s must be > 0, got {probe_interval_s}"
            )
        self.name = name
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.unhealthy_after = int(unhealthy_after)
        self.recovery_after_s = float(recovery_after_s)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}  # guarded-by: _lock
        self._next_index = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for url in urls:
            self.add(url, source="static")

    # -- membership ---------------------------------------------------------

    def add(
        self, url: str, source: str = "registered", models=None
    ) -> Tuple[Replica, bool]:
        """Add one replica (idempotent by URL). Returns ``(replica,
        created)`` — a re-registration of a known URL is a heartbeat,
        not a new member (but it DOES refresh the advertised model
        set: a replica whose zoo spec changed re-registers with the
        new ids)."""
        url = _validate_replica_url(url)
        with self._lock:
            existing = self._replicas.get(url)
            if existing is not None:
                if models is not None:
                    existing.set_models(models)
                return existing, False
            replica = Replica(
                url,
                index=self._next_index,
                source=source,
                unhealthy_after=self.unhealthy_after,
                recovery_after_s=self.recovery_after_s,
            )
            self._next_index += 1
            self._replicas[url] = replica
        if models:
            replica.set_models(models)
        logger.info(
            "fleet %s: replica %s added (%s, index %d)",
            self.name, replica.name, source, replica.index,
        )
        return replica, True

    def remove(self, url: str) -> bool:
        """Drop one replica from the roster (idempotent by URL) — the
        ``POST /deregisterz`` half of graceful retirement: once
        removed, ``pick()`` can never hand the replica new forwards,
        so it can drain its in-flight work and exit without lingering
        in the roster until probes fail it. Returns whether the URL
        was a member."""
        url = _validate_replica_url(url)
        with self._lock:
            replica = self._replicas.pop(url, None)
        if replica is not None:
            logger.info(
                "fleet %s: replica %s deregistered (index %d)",
                self.name, replica.name, replica.index,
            )
        return replica is not None

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def find_by_name(self, name: str) -> Optional[Replica]:
        """Replica by roster name (``host:port``) — how the trace
        stitcher resolves a ``router.forward`` span's ``replica`` attr
        back to a URL it is allowed to dial (the registry is the
        authority on fleet membership, not span attrs)."""
        with self._lock:
            for replica in self._replicas.values():
                if replica.name == name:
                    return replica
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    # -- routing ------------------------------------------------------------

    def pick(
        self,
        exclude: Sequence[Replica] = (),
        model: Optional[str] = None,
    ) -> Optional[Replica]:
        """The least-loaded ready+healthy replica outside ``exclude``
        — with the pool's availability-over-purity fallbacks: a
        healthy-but-draining replica beats nothing, and an unhealthy
        replica beats shedding when it is all that's left (which is
        also how a half-open replica earns its probe traffic).
        ``model`` restricts every tier to replicas ADVERTISING that
        zoo model id — the fallbacks relax health, never routing a
        model to a replica that doesn't serve it (None here means
        'no replica for model', the router's typed 503)."""
        # ONE membership snapshot for all three tiers: the hot path
        # takes the registry lock once, and the fallbacks filter the
        # same roster the first tier saw
        available = [r for r in self.replicas() if r not in exclude]
        if model is not None:
            available = [r for r in available if r.advertises(model)]
        candidates = [r for r in available if r.healthy and r.ready]
        if not candidates:
            candidates = [r for r in available if r.healthy]
        if not candidates:
            candidates = available
        if not candidates:
            return None
        return min(candidates, key=lambda r: r.load)

    # -- probes (own daemon thread; HTTP strictly outside the lock) ---------

    def probe_once(self) -> None:
        """One probe sweep over a membership snapshot: ``/readyz``
        (liveness + readiness + burn-state body + the
        ``X-Keystone-Load`` header) and a ``/metrics`` scrape (load
        fallback, build info, the cached federation input). Replicas
        are probed CONCURRENTLY — a serial sweep would stretch the
        probe period by the sum of per-replica timeouts the moment
        one host answers slowly, delaying unreachable-detection for
        whoever happens to be probed last."""
        self._fan_out(self._probe, self.replicas())

    @staticmethod
    def _fan_out(fn, replicas: Sequence[Replica]) -> None:
        if not replicas:
            return
        if len(replicas) == 1:
            fn(replicas[0])
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(8, len(replicas)),
            thread_name_prefix="keystone-fleet-probe",
        ) as pool:
            for _ in pool.map(fn, replicas):
                pass

    def _probe(self, replica: Replica) -> None:
        try:
            with urllib.request.urlopen(
                replica.url + "/readyz", timeout=self.probe_timeout_s
            ) as resp:
                ready = resp.status == 200
                detail = resp.read().decode("utf-8", "replace").strip()
                load_header = resp.headers.get("X-Keystone-Load")
        except urllib.error.HTTPError as e:
            # 503-draining: the PROCESS answered — alive, not ready
            ready = False
            detail = (e.read() or b"").decode("utf-8", "replace").strip()
            load_header = e.headers.get("X-Keystone-Load")
        except Exception as e:
            replica.record_probe(
                alive=False, ready=False,
                detail=f"probe failed: {type(e).__name__}: {e}",
            )
            return
        scrape = build = None
        scraped_load = None
        try:
            with urllib.request.urlopen(
                replica.url + "/metrics", timeout=self.probe_timeout_s
            ) as resp:
                scrape = resp.read().decode("utf-8", "replace")
            build, scraped_load = self._parse_scrape(scrape)
        except Exception:
            logger.debug(
                "fleet %s: /metrics scrape of %s failed",
                self.name, replica.name, exc_info=True,
            )
        load = None
        if load_header is not None:
            try:
                load = float(load_header)
            except ValueError:
                load = None
        if load is None:
            load = scraped_load
        replica.record_probe(
            alive=True, ready=ready, detail=detail,
            load=load, scrape=scrape, build=build,
        )

    @staticmethod
    def _parse_scrape(
        text: str,
    ) -> Tuple[Dict[str, str], Optional[float]]:
        """Build-info labels + summed load gauges from one scrape."""
        build: Dict[str, str] = {}
        load = None
        for name, labels, value in prometheus.parse_samples(text):
            if name == "keystone_build_info":
                build = dict(labels)
            elif name in _LOAD_FAMILIES:
                load = (load or 0.0) + value
        return build, load

    def start(self) -> "ReplicaRegistry":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.probe_interval_s):
                try:
                    self.probe_once()
                except Exception:
                    logger.exception(
                        "fleet %s: probe sweep failed", self.name
                    )

        self._thread = threading.Thread(
            target=loop,
            name=f"keystone-{self.name}-probes",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- federation + introspection ----------------------------------------

    def scrapes(self) -> List[str]:
        """The cached per-replica exposition bodies (last probe's) —
        the cheap federation input the SLO monitor burns against."""
        return [
            text
            for text in (r.cached_scrape for r in self.replicas())
            if text
        ]

    def fresh_scrapes(
        self, timeout_s: Optional[float] = None
    ) -> List[str]:
        """Scrape every reachable replica NOW (the router's
        ``/metrics`` path — a scrape should reflect the present, not
        the last probe tick); a replica that can't answer contributes
        its cached body instead, so one dead host degrades the
        federation to slightly-stale rather than absent. Replicas are
        scraped concurrently for the same reason probes are: the
        router's scrape latency must track the slowest replica, not
        the fleet-size-weighted sum of slow ones."""
        timeout = timeout_s if timeout_s is not None else self.probe_timeout_s

        def scrape_one(replica: Replica) -> None:
            if not replica.healthy:
                return
            try:
                with urllib.request.urlopen(
                    replica.url + "/metrics", timeout=timeout
                ) as resp:
                    text = resp.read().decode("utf-8", "replace")
                replica.record_scrape(text)
            except Exception:
                pass  # the cached body stands in below

        replicas = self.replicas()
        self._fan_out(scrape_one, replicas)
        return [
            text
            for text in (r.cached_scrape for r in replicas)
            if text
        ]

    def counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for replica in self.replicas():
            state = replica.state
            counts[state] = counts.get(state, 0) + 1
        return counts

    def roster(self) -> Dict:
        """The ``/fleetz`` replica listing."""
        rows = [r.status() for r in self.replicas()]
        return {
            "replicas": sorted(rows, key=lambda r: r["index"]),
            "counts": self.counts(),
            "probe_interval_s": self.probe_interval_s,
        }


__all__ = [
    "RECOVERY_AFTER_S",
    "Replica",
    "ReplicaRegistry",
    "UNHEALTHY_AFTER",
]
