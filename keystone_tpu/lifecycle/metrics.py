"""Lifecycle instrumentation: one handle bundle per MODEL over the
global registry (the ``GatewayMetrics`` shape, ``model``-labeled so
every zoo model's lifecycle stays distinguishable on one scrape).

Families:

- ``keystone_lifecycle_state{model,state}`` — one-hot stage gauge
  (``idle``/``candidate``/``shadow``/``canary``/``promoted``/
  ``rolled_back``): the ``/lifecyclez`` state, scrapeable.
- ``keystone_lifecycle_version{model}`` — newest solved candidate
  version (0 until the first solve).
- ``keystone_lifecycle_refit_samples_total{model}`` /
  ``_refit_chunks_total{model}`` — labeled feedback folded into the
  normal-equations state.
- ``keystone_lifecycle_shadow_pairs_total{model}`` — mirrored
  requests whose primary+shadow outputs were both observed and
  diffed.
- ``keystone_lifecycle_shadow_diff{model,stat}`` — rolling output
  diff between incumbent and candidate (``mean_abs`` / ``max_abs``).
- ``keystone_lifecycle_canary_requests_total{model,outcome}`` —
  live requests routed to the candidate (``ok`` / ``error``; errors
  fall back to the incumbent lanes, so the caller never sees them).
- ``keystone_lifecycle_promotions_total{model}`` /
  ``_rollbacks_total{model,reason}`` — terminal transitions; the
  rollback reason is the policy's gate name (``accuracy`` /
  ``shadow_diff`` / ``canary_errors`` / ``slo_burn`` / ``manual``).
"""

from __future__ import annotations

from typing import Optional

from keystone_tpu.observability.registry import (
    MetricsRegistry,
    get_global_registry,
)

from keystone_tpu.lifecycle.policy import STAGES


class LifecycleMetrics:
    """Pre-resolved metric handles for one model's lifecycle."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        model: str = "default",
    ):
        reg = registry if registry is not None else get_global_registry()
        self.registry = reg
        self.model = model
        self._state = reg.gauge(
            "keystone_lifecycle_state",
            "one-hot lifecycle stage per model",
            ("model", "state"),
        )
        self._version = reg.gauge(
            "keystone_lifecycle_version",
            "newest solved candidate version per model",
            ("model",),
        )
        self._refit_samples = reg.counter(
            "keystone_lifecycle_refit_samples_total",
            "labeled feedback rows folded into the refit state",
            ("model",),
        )
        self._refit_chunks = reg.counter(
            "keystone_lifecycle_refit_chunks_total",
            "feedback chunks accumulated into the normal equations",
            ("model",),
        )
        self._shadow_pairs = reg.counter(
            "keystone_lifecycle_shadow_pairs_total",
            "mirrored requests with both outputs observed and diffed",
            ("model",),
        )
        self._shadow_diff = reg.gauge(
            "keystone_lifecycle_shadow_diff",
            "rolling incumbent-vs-candidate output diff",
            ("model", "stat"),
        )
        self._canary = reg.counter(
            "keystone_lifecycle_canary_requests_total",
            "live requests routed to the candidate engine",
            ("model", "outcome"),
        )
        self._promotions = reg.counter(
            "keystone_lifecycle_promotions_total",
            "candidates promoted to serve all traffic",
            ("model",),
        )
        self._rollbacks = reg.counter(
            "keystone_lifecycle_rollbacks_total",
            "candidates rolled back, by policy gate",
            ("model", "reason"),
        )
        self.set_stage("idle")
        self.set_version(0)

    # -- thin label-bound helpers ------------------------------------------

    def set_stage(self, stage: str) -> None:
        for s in STAGES:
            self._state.set(1.0 if s == stage else 0.0, (self.model, s))

    def set_version(self, version: int) -> None:
        self._version.set(float(version), (self.model,))

    def record_refit_chunk(self, n_samples: int) -> None:
        self._refit_chunks.inc((self.model,))
        self._refit_samples.inc((self.model,), n_samples)

    def record_shadow_pair(
        self, mean_abs: float, max_abs: float
    ) -> None:
        self._shadow_pairs.inc((self.model,))
        self._shadow_diff.set(mean_abs, (self.model, "mean_abs"))
        self._shadow_diff.set(max_abs, (self.model, "max_abs"))

    def record_canary(self, outcome: str) -> None:
        self._canary.inc((self.model, outcome))

    def record_promotion(self) -> None:
        self._promotions.inc((self.model,))

    def record_rollback(self, reason: str) -> None:
        self._rollbacks.inc((self.model, reason))

    # -- test/debug conveniences -------------------------------------------

    def shadow_pair_count(self) -> float:
        return self._shadow_pairs.get((self.model,))

    def canary_count(self, outcome: str) -> float:
        return self._canary.get((self.model, outcome))

    def promotion_count(self) -> float:
        return self._promotions.get((self.model,))

    def rollback_count(self, reason: str) -> float:
        return self._rollbacks.get((self.model, reason))


__all__ = ["LifecycleMetrics"]
