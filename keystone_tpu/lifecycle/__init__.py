"""Online model lifecycle: streaming refit → shadow → canary → swap
with auto-rollback (see README "Online model lifecycle").

Only the dependency-light modules are eager (``policy`` is pure
dataclasses, ``manager`` is a dict behind a lock) — the controller
stack pulls in jax/serving and is imported by the processes that
actually run a lifecycle, not by everyone who routes to one."""

from keystone_tpu.lifecycle.manager import LifecycleManager
from keystone_tpu.lifecycle.policy import (
    GateInputs,
    PolicyState,
    PromotionConfig,
    tick,
)

__all__ = [
    "GateInputs",
    "LifecycleManager",
    "PolicyState",
    "PromotionConfig",
    "tick",
]
