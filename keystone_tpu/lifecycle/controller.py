"""LifecycleController: one model's refit → shadow → canary → swap
loop, wired to a live Gateway.

The controller owns the SIDE EFFECTS around the pure policy
(``policy.tick``): it drains the feedback buffer into the
``RefitAccumulator``, solves candidates, builds their engines
(``Gateway.build_model_batcher`` — same serving config as the lanes,
per-version AOT namespace), arms/clears the pool's shadow mirror and
canary router, and drives ``Gateway.swap_model`` on promotion and
rollback. One ``tick()`` = one policy decision plus its effects;
ticks run manually (``POST /lifecyclez {"tick": true}``, tests,
benches) or on the background interval thread (``interval_s``).

Versioned snapshots: candidate v's engines build against
``namespaced_store("<namespace>/v<version>")`` when the process has
an AOT store configured, so every promoted version's executables land
in their own namespace — rolling back (or paging the version back in)
never recompiles and never collides with another version's slots.

Rollback restores THREE things: the pool hooks (cleared), the refit
state (``restore`` to the last-good snapshot, so a poisoned
accumulation window can't leak into the next candidate), and — for a
post-promotion rollback — the serving engines themselves
(``swap_model`` back to the retained incumbent, which rebuilds from
the identical fitted pipeline: bitwise-identical outputs).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Optional

import numpy as np

from keystone_tpu.lifecycle.metrics import LifecycleMetrics
from keystone_tpu.lifecycle.policy import (
    GateInputs,
    PolicyState,
    PromotionConfig,
    tick as policy_tick,
)
from keystone_tpu.lifecycle.refit import RefitAccumulator
from keystone_tpu.lifecycle.routes import CanaryRouter, ShadowMirror
from keystone_tpu.observability.tracing import get_tracer

logger = logging.getLogger(__name__)


class LifecycleController:
    """Drive one model's online lifecycle over its serving gateway."""

    def __init__(
        self,
        gateway,
        *,
        base,
        head_builder: Callable[[Any, Any], Any],
        feature_dim: int,
        out_dim: int,
        name: str = "default",
        config: PromotionConfig = PromotionConfig(),
        canary_fraction: float = 0.25,
        min_refit_samples: int = 64,
        interval_s: Optional[float] = None,
        registry=None,
        aot_namespace: Optional[str] = None,
        refit_lam: float = 1e-3,
        refit_chunk: int = 64,
        holdout_every: int = 8,
        holdout_cap: int = 512,
    ):
        self._gateway = gateway
        self._base = base
        self._head_builder = head_builder
        self.name = name
        self._config = config
        self._canary_fraction = float(canary_fraction)
        self._min_refit_samples = int(min_refit_samples)
        self._aot_namespace = aot_namespace or name
        self._metrics = LifecycleMetrics(registry=registry, model=name)
        self._refit = RefitAccumulator(
            base,
            feature_dim,
            out_dim,
            name=name,
            lam=refit_lam,
            chunk=refit_chunk,
            holdout_every=holdout_every,
            holdout_cap=holdout_cap,
            metrics=self._metrics,
        )
        # ticks serialize here; everything below it is tick-owned
        # state, mutated only with the lock held
        self._tick_lock = threading.RLock()
        self._state = PolicyState("idle")  # guarded-by: _tick_lock
        self._version = 0  # guarded-by: _tick_lock
        self._incumbent = gateway.fitted  # guarded-by: _tick_lock
        self._previous = None  # guarded-by: _tick_lock
        self._previous_store = None  # guarded-by: _tick_lock
        self._candidate = None  # guarded-by: _tick_lock
        self._candidate_batcher = None  # guarded-by: _tick_lock
        self._candidate_store = None  # guarded-by: _tick_lock
        self._mirror: Optional[ShadowMirror] = None  # guarded-by: _tick_lock
        self._canary: Optional[CanaryRouter] = None  # guarded-by: _tick_lock
        self._last_reason = "idle"  # guarded-by: _tick_lock
        self._last_inputs = GateInputs()  # guarded-by: _tick_lock
        self._solved_at_n = 0  # guarded-by: _tick_lock
        self._last_good = self._refit.snapshot()  # guarded-by: _tick_lock
        # feedback lands here (HTTP handler threads) and drains into
        # the accumulator at tick time
        self._fb_lock = threading.Lock()
        self._fb: list = []  # guarded-by: _fb_lock
        self._closed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if interval_s:
            self._thread = threading.Thread(
                target=self._loop,
                args=(float(interval_s),),
                name=f"keystone-lifecycle-{name}",
                daemon=True,
            )
            self._thread.start()

    # -- feedback intake ---------------------------------------------------

    def add_feedback(self, instances: Any, labels: Any) -> int:
        """Queue one labeled batch (``POST /feedback`` lands here).
        Validation is shape-only and cheap — the accumulation happens
        at tick time, off the request path."""
        X = np.asarray(instances, np.float32)
        Y = np.asarray(labels, np.float32)
        if X.ndim != 2 or Y.ndim != 2 or X.shape[0] != Y.shape[0]:
            raise ValueError(
                f"need matching 2-D instances/labels, got "
                f"{X.shape} vs {Y.shape}"
            )
        with self._fb_lock:
            if self._closed:
                raise RuntimeError("lifecycle controller is closed")
            self._fb.append((X, Y))
        return int(X.shape[0])

    def _drain_feedback(self) -> int:
        with self._fb_lock:
            batches, self._fb = self._fb, []
        folded = 0
        if batches:
            with get_tracer().span(
                "lifecycle.refit", model=self.name,
                batches=len(batches),
            ):
                for X, Y in batches:
                    folded += self._refit.add(X, Y)
        return folded

    # -- the tick ----------------------------------------------------------

    def tick(self) -> Dict:
        """Drain feedback, maybe solve a new candidate, take one
        policy decision, apply its side effects. Returns ``status()``."""
        with self._tick_lock:
            if self._closed:
                return self.status()
            with get_tracer().span("lifecycle.tick", model=self.name):
                self._drain_feedback()
                if self._state.stage in ("idle", "promoted",
                                         "rolled_back"):
                    fresh = (self._refit.n_accumulated
                             - self._solved_at_n)
                    if fresh >= self._min_refit_samples:
                        self._start_candidate_locked()
                    else:
                        return self.status()
                inputs = self._gate_inputs()
                new_state, reason = policy_tick(
                    self._state, inputs, self._config
                )
                if new_state.stage != self._state.stage:
                    self._apply_transition_locked(new_state.stage, reason)
                self._state = new_state
                self._last_reason = reason
                self._last_inputs = inputs
                self._metrics.set_stage(new_state.stage)
            return self.status()

    def _start_candidate_locked(self) -> None:
        from keystone_tpu.serving.aot import namespaced_store

        W, b = self._refit.solve()
        self._version += 1
        self._candidate = self._base.and_then(self._head_builder(W, b))
        self._candidate_store = namespaced_store(
            f"{self._aot_namespace}/v{self._version}"
        )
        self._candidate_batcher = self._gateway.build_model_batcher(
            self._candidate,
            name=f"{self.name}-cand-v{self._version}",
            aot_store=self._candidate_store,
        )
        self._solved_at_n = self._refit.n_accumulated
        self._state = PolicyState("candidate")
        self._metrics.set_version(self._version)
        logger.info(
            "lifecycle %s: candidate v%d solved from %d samples",
            self.name, self._version, self._solved_at_n,
        )

    def _gate_inputs(self) -> GateInputs:
        shadow = self._mirror.stats() if self._mirror else {}
        canary = self._canary.stats() if self._canary else {}
        slo = self._gateway.slo_status()
        cand_err = inc_err = None
        if self._candidate is not None:
            cand_err, inc_err = self._refit.holdout_errors(
                self._candidate, self._incumbent
            )
        return GateInputs(
            shadow_pairs=shadow.get("pairs", 0),
            shadow_max_abs=shadow.get("max_abs", 0.0),
            canary_requests=canary.get("requests", 0),
            canary_errors=canary.get("errors", 0),
            slo_breaching=bool(slo and slo.get("breaching")),
            candidate_err=cand_err,
            incumbent_err=inc_err,
        )

    def _apply_transition_locked(self, stage: str, reason: str) -> None:
        pool = self._gateway.pool
        if stage == "shadow":
            self._mirror = ShadowMirror(
                self._candidate_batcher,
                model=self.name,
                metrics=self._metrics,
            )
            pool.set_mirror(self._mirror)
        elif stage == "canary":
            pool.set_mirror(None)
            self._canary = CanaryRouter(
                self._candidate_batcher,
                self._canary_fraction,
                model=self.name,
                metrics=self._metrics,
            )
            pool.set_canary(self._canary)
        elif stage == "promoted":
            pool.set_canary(None)
            pool.set_mirror(None)
            prev_store = getattr(self._gateway, "_aot_store", None)
            ok = self._gateway.swap_model(
                self._candidate, aot_store=self._candidate_store
            )
            if not ok:  # close() won the race; nothing rotated
                self._close_candidate_locked()
                return
            self._previous = self._incumbent
            self._previous_store = prev_store
            self._incumbent = self._candidate
            self._last_good = self._refit.snapshot()
            self._metrics.record_promotion()
            self._close_candidate_locked()
            logger.info(
                "lifecycle %s: v%d PROMOTED", self.name, self._version
            )
        elif stage == "rolled_back":
            self._rollback_effects_locked(reason)

    def _rollback_effects_locked(self, reason: str) -> None:
        pool = self._gateway.pool
        pool.set_canary(None)
        pool.set_mirror(None)
        # discard the tainted accumulation window: everything since
        # the last KNOWN-GOOD state (initial, or the last promotion)
        # — a poisoned chunk must not leak into the next candidate
        self._refit.restore(self._last_good)
        self._solved_at_n = self._refit.n_accumulated
        self._close_candidate_locked()
        self._metrics.record_rollback(reason)
        logger.warning(
            "lifecycle %s: v%d ROLLED BACK (%s)",
            self.name, self._version, reason,
        )

    def force_rollback(self, reason: str = "manual") -> Dict:
        """Operator rollback. Mid-cycle it kills the candidate (same
        path as a policy rollback); after a promotion — with no new
        cycle active — it swaps the serving engines back to the
        retained pre-promotion incumbent."""
        with self._tick_lock:
            stage = self._state.stage
            if stage in ("candidate", "shadow", "canary"):
                self._rollback_effects_locked(reason)
                self._state = PolicyState("rolled_back")
            elif self._previous is not None:
                ok = self._gateway.swap_model(
                    self._previous, aot_store=self._previous_store
                )
                if ok:
                    self._incumbent = self._previous
                    self._previous = None
                    self._state = PolicyState("rolled_back")
                    self._metrics.record_rollback(reason)
                    logger.warning(
                        "lifecycle %s: promotion v%d un-promoted (%s)",
                        self.name, self._version, reason,
                    )
            self._last_reason = reason
            self._metrics.set_stage(self._state.stage)
            return self.status()

    def _close_candidate_locked(self) -> None:
        batcher, self._candidate_batcher = self._candidate_batcher, None
        if batcher is not None:
            try:
                batcher.close(timeout=5.0)
            except Exception:
                logger.exception(
                    "lifecycle %s: candidate batcher close failed",
                    self.name,
                )

    # -- inspection / plumbing ---------------------------------------------

    def status(self) -> Dict:
        """The ``/lifecyclez`` document for this model."""
        with self._fb_lock:
            pending = sum(x.shape[0] for x, _ in self._fb)
        inputs = self._last_inputs
        return {
            "model": self.name,
            "state": self._state.stage,
            "version": self._version,
            "last_reason": self._last_reason,
            "refit": {
                "accumulated": self._refit.n_accumulated,
                "holdout": self._refit.n_holdout,
                "pending": pending,
                "min_refit_samples": self._min_refit_samples,
            },
            "shadow": self._mirror.stats() if self._mirror else None,
            "canary": self._canary.stats() if self._canary else None,
            "errors": {
                "candidate": inputs.candidate_err,
                "incumbent": inputs.incumbent_err,
            },
            "promotions": int(self._metrics.promotion_count()),
        }

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception(
                    "lifecycle %s: tick failed", self.name
                )

    def close(self, timeout: float = 10.0) -> None:
        with self._fb_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._tick_lock:
            pool = self._gateway.pool
            pool.set_canary(None)
            pool.set_mirror(None)
            self._close_candidate_locked()

    def __enter__(self) -> "LifecycleController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["LifecycleController"]
