"""Streaming refit: labeled feedback → incremental normal equations →
a re-solved head.

The served demo model ends in a ``tanh(x @ W + b)`` head over a frozen
feature base (``serving/bench.build_split_pipeline``). Because the
normal-equations state is ADDITIVE — the same property that makes the
ELL one-pass accumulator in ``ops/learning/sparse_ell.py``
chunk-size-independent — "refit" is never a full refit: each labeled
chunk folds into ``(G, AY, n)`` once and a candidate head is one
regularized PSD solve over the running state (the identical
``_psd_solve_device`` kernel the ELL solver jits).

Math: serving outputs are ``y = tanh(z)`` with ``z = h @ W + b`` over
base features ``h``, so labels are mapped to pre-activation targets
``z = arctanh(clip(y))`` and the head is the ridge solution of the
AUGMENTED system ``[h, 1] @ W_aug = z`` — the ones column carries the
bias, and a 0/1 validity mask zeroes padded rows so every chunk runs
through ONE fixed-shape jitted update (one XLA compile total).

Held-out labels: every ``holdout_every``-th feedback row is diverted
to a bounded holdout buffer and NEVER accumulated — the accuracy gate
compares candidate vs incumbent on data neither was solved from. The
``lifecycle.refit.poison`` chaos point corrupts an accumulated chunk's
targets (the holdout stays clean), which is exactly how the rollback
drill proves the accuracy gate fires.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.loadgen import faults
# the ELL accumulator's solve kernel (sparse_ell jits the same fn):
# refit state is (G, AY, n) exactly like its one-pass scan, so the
# candidate head comes out of the identical factor-and-refine solve
from keystone_tpu.ops.learning.block_ls import _psd_solve_device

_jit_psd_solve = jax.jit(_psd_solve_device)

# labels are tanh outputs in (-1, 1); clip before arctanh so a label
# AT the rail maps to a large-but-finite pre-activation target
_CLIP = 1.0 - 1e-5


@jax.jit
def _accum_update(G, AY, H, Z, mask):
    Ha = jnp.concatenate([H * mask[:, None], mask[:, None]], axis=1)
    return G + Ha.T @ Ha, AY + Ha.T @ (Z * mask[:, None])


class RefitAccumulator:
    """Incremental ``(G, AY, n)`` over a frozen feature base, plus the
    clean holdout buffer the accuracy gate reads."""

    def __init__(
        self,
        base,
        feature_dim: int,
        out_dim: int,
        *,
        name: str = "default",
        lam: float = 1e-3,
        chunk: int = 64,
        holdout_every: int = 8,
        holdout_cap: int = 512,
        metrics=None,  # LifecycleMetrics; duck-typed
    ):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self._base = base
        self.name = name
        self.lam = float(lam)
        self.chunk = int(chunk)
        self.out_dim = int(out_dim)
        self._holdout_every = max(0, int(holdout_every))
        self._holdout_cap = int(holdout_cap)
        self._metrics = metrics
        self._lock = threading.Lock()
        d = int(feature_dim) + 1  # augmented with the bias column
        self._G = jnp.zeros((d, d), jnp.float32)  # guarded-by: _lock
        self._AY = jnp.zeros((d, out_dim), jnp.float32)  # guarded-by: _lock
        self._n = 0  # guarded-by: _lock
        self._seen = 0  # guarded-by: _lock
        self._hold_x: list = []  # guarded-by: _lock
        self._hold_y: list = []  # guarded-by: _lock

    # -- accumulation ------------------------------------------------------

    @property
    def n_accumulated(self) -> int:
        with self._lock:
            return self._n

    @property
    def n_holdout(self) -> int:
        with self._lock:
            return len(self._hold_x)

    def add(self, instances: Any, labels: Any) -> int:
        """Fold one labeled batch in. Returns the rows ACCUMULATED
        (holdout-diverted rows don't count). Chunk-size independent:
        any split of the same rows lands on the same ``(G, AY, n)``."""
        X = np.asarray(instances, np.float32)
        Y = np.asarray(labels, np.float32)
        if X.ndim != 2 or Y.ndim != 2 or X.shape[0] != Y.shape[0]:
            raise ValueError(
                f"need matching 2-D instances/labels, got {X.shape} "
                f"vs {Y.shape}"
            )
        if Y.shape[1] != self.out_dim:
            raise ValueError(
                f"labels are {Y.shape[1]}-dim, model serves "
                f"{self.out_dim}"
            )
        with self._lock:
            # split the holdout rows out FIRST (a global every-k-th
            # row counter), so the accuracy gate's data never touches
            # the normal equations — poisoned or not
            idx = np.arange(X.shape[0]) + self._seen
            self._seen += X.shape[0]
            if self._holdout_every > 0:
                hold = (idx % self._holdout_every) == 0
            else:
                hold = np.zeros(X.shape[0], bool)
            # cap the buffer; hold-pattern rows past the cap fold
            # into the normal equations like any other row (labels
            # are scarce — none get dropped)
            room = max(0, self._holdout_cap - len(self._hold_x))
            kept = np.where(hold)[0][:room]
            for xi, yi in zip(X[kept], Y[kept]):
                self._hold_x.append(xi)
                self._hold_y.append(yi)
            keep = np.ones(X.shape[0], bool)
            keep[kept] = False
            X, Y = X[keep], Y[keep]
            accumulated = int(X.shape[0])
            for start in range(0, X.shape[0], self.chunk):
                self._accumulate_chunk_locked(
                    X[start:start + self.chunk],
                    Y[start:start + self.chunk],
                )
        return accumulated

    def _accumulate_chunk_locked(
        self, xs: np.ndarray, ys: np.ndarray
    ) -> None:
        n = xs.shape[0]
        if n == 0:
            return
        # chaos point: an armed lifecycle.refit.poison corrupts THIS
        # chunk's targets before they fold into (G, AY) — the model
        # the next solve produces is garbage while the holdout buffer
        # (split off above) stays clean, so the accuracy gate must
        # catch it and the controller must roll back. Unarmed: one
        # attribute read, the ctx dict is never built.
        poisoned = faults.armed() and faults.fire(
            "lifecycle.refit.poison", {"model": self.name}
        ) is not None
        pad = self.chunk - n
        if pad:
            xs = np.concatenate(
                [xs, np.zeros((pad, xs.shape[1]), np.float32)]
            )
            ys = np.concatenate(
                [ys, np.zeros((pad, ys.shape[1]), np.float32)]
            )
        mask = np.zeros(self.chunk, np.float32)
        mask[:n] = 1.0
        z = np.arctanh(np.clip(ys, -_CLIP, _CLIP))
        if poisoned:
            z = -40.0 * z
        H = np.asarray(self._base._batch_run(jnp.asarray(xs)))[
            : self.chunk
        ]
        self._G, self._AY = _accum_update(
            self._G, self._AY, jnp.asarray(H), jnp.asarray(z),
            jnp.asarray(mask),
        )
        self._n += n
        if self._metrics is not None:
            self._metrics.record_refit_chunk(n)

    # -- solve / holdout ---------------------------------------------------

    def solve(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One ridge solve over the running state -> ``(W, b)`` for a
        candidate head. Raises if nothing was accumulated yet."""
        with self._lock:
            if self._n == 0:
                raise RuntimeError("no feedback accumulated yet")
            W_aug = _jit_psd_solve(
                self._G, self._AY, jnp.float32(self.lam * self._n)
            )
        W_aug.block_until_ready()
        return W_aug[:-1], W_aug[-1]

    def holdout_errors(
        self, candidate, incumbent
    ) -> Tuple[Optional[float], Optional[float]]:
        """Held-out MSE of two full fitted pipelines (raw instances
        in, served outputs out). ``(None, None)`` until the holdout
        buffer has samples."""
        with self._lock:
            if not self._hold_x:
                return None, None
            X = np.stack(self._hold_x)
            Y = np.stack(self._hold_y)
        out = []
        for fitted in (candidate, incumbent):
            pred = np.asarray(fitted._batch_run(jnp.asarray(X)))[
                : X.shape[0]
            ]
            out.append(float(np.mean((pred - Y) ** 2)))
        return out[0], out[1]

    # -- rollback support --------------------------------------------------

    def snapshot(self) -> tuple:
        """The accumulated state at solve time — ``restore`` discards
        everything folded in since (a poisoned cycle must not leak
        into the NEXT candidate)."""
        with self._lock:
            return (self._G, self._AY, self._n, self._seen)

    def restore(self, snap: tuple) -> None:
        with self._lock:
            self._G, self._AY, self._n, self._seen = snap


__all__ = ["RefitAccumulator"]
