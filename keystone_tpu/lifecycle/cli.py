"""``serve-lifecycle``: operator controls for a live gateway's
lifecycle plane over HTTP.

    python -m keystone_tpu serve-lifecycle status   --url http://host:port
    python -m keystone_tpu serve-lifecycle tick     --url ... [--model m]
    python -m keystone_tpu serve-lifecycle rollback --url ... [--model m]

``status`` GETs ``/lifecyclez``; ``tick`` forces one policy tick on
every controller (what the background interval does on its own);
``rollback`` forces a rollback — mid-cycle it kills the candidate,
after a promotion it swaps the engines back to the retained
incumbent. All three print the server's JSON verbatim (exit 1 on a
transport/HTTP error), so they compose with jq the way the other
``/…z`` surfaces do."""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-lifecycle", description=__doc__
    )
    ap.add_argument(
        "action", choices=("status", "tick", "rollback"),
        help="status: GET /lifecyclez; tick: force one policy tick; "
             "rollback: force a rollback (candidate killed, or a "
             "promotion un-promoted)",
    )
    ap.add_argument("--url", required=True, metavar="BASE",
                    help="gateway base URL, e.g. http://127.0.0.1:8300")
    ap.add_argument("--model", default=None,
                    help="target one model (rollback only; default: "
                    "the server's default lifecycle model)")
    ap.add_argument("--timeout", type=float, default=30.0)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    base = args.url.rstrip("/")
    try:
        if args.action == "status":
            req = urllib.request.Request(base + "/lifecyclez")
        else:
            body = {"tick": True} if args.action == "tick" else \
                {"rollback": True}
            if args.model:
                body["model"] = args.model
            req = urllib.request.Request(
                base + "/lifecyclez",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            doc = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            detail = e.read().decode()
        except Exception:
            detail = ""
        print(f"HTTP {e.code}: {detail}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"request failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(doc, indent=2))
    return 0


__all__ = ["build_parser", "main"]
