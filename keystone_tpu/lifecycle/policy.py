"""PromotionPolicy: the pure candidate → promoted/rolled_back state
machine.

Nothing in here touches engines, threads, or metrics — ``tick`` maps
``(PolicyState, GateInputs) -> (PolicyState, reason)`` and is exactly
as testable as that sounds. The controller (``controller.py``) owns
the side effects (arming the shadow mirror, setting the canary
fraction, swapping engines); this module owns only the DECISIONS:

- ``candidate → shadow``: unconditional — a freshly solved candidate
  always earns mirrored traffic first, never live traffic.
- ``shadow → canary``: enough shadow pairs observed AND the held-out
  accuracy gate says the candidate is at least as good as the
  incumbent.
- ``canary → promoted``: ``promote_after_healthy_ticks`` CONSECUTIVE
  healthy canary ticks (enough canary requests, error rate under the
  ceiling, no SLO burn, accuracy still good). Any marginal tick —
  not bad enough to roll back, not clean enough to count — resets the
  streak but does NOT roll back: that band is the hysteresis that
  stops a candidate from flapping between canary and rollback on
  noisy windows.
- ``→ rolled_back`` (from shadow or canary, immediately): the hard
  gates. Held-out accuracy worse than ``rollback_err_ratio`` × the
  incumbent's (the poisoned-refit drill trips exactly this), shadow
  diff over threshold with enough evidence and NO proven-good
  held-out accuracy (a proven-good candidate is allowed to differ —
  correcting drift is the point of a refit), canary error rate over
  the ceiling with enough evidence, or the serving SLO burning while
  the canary takes live traffic.

``promoted`` and ``rolled_back`` are terminal PER CANDIDATE — the
controller starts a fresh ``PolicyState`` for the next solved version.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

STAGES = ("idle", "candidate", "shadow", "canary", "promoted",
          "rolled_back")


@dataclass(frozen=True)
class GateInputs:
    """One tick's evidence, all pre-aggregated by the controller."""

    shadow_pairs: int = 0
    shadow_max_abs: float = 0.0
    canary_requests: int = 0
    canary_errors: int = 0
    slo_breaching: bool = False
    # held-out MSEs; None until the holdout buffer has samples
    candidate_err: Optional[float] = None
    incumbent_err: Optional[float] = None


@dataclass(frozen=True)
class PromotionConfig:
    # shadow gate
    min_shadow_pairs: int = 32
    max_shadow_diff: float = 0.25
    # canary gate
    min_canary_requests: int = 32
    max_canary_error_rate: float = 0.02
    promote_after_healthy_ticks: int = 2
    # accuracy gates (ratios vs the incumbent's held-out error):
    # <= promote_err_ratio is required to advance/promote;
    # > rollback_err_ratio rolls back immediately; the band between
    # is the hysteresis zone (hold position, reset the streak)
    promote_err_ratio: float = 1.0
    rollback_err_ratio: float = 1.5

    def __post_init__(self):
        if not (0.0 < self.promote_err_ratio
                <= self.rollback_err_ratio):
            raise ValueError(
                "need 0 < promote_err_ratio <= rollback_err_ratio, "
                f"got {self.promote_err_ratio} / "
                f"{self.rollback_err_ratio}"
            )


@dataclass(frozen=True)
class PolicyState:
    stage: str = "candidate"
    healthy_streak: int = 0

    @property
    def terminal(self) -> bool:
        return self.stage in ("promoted", "rolled_back")


def _accuracy(inputs: GateInputs, cfg: PromotionConfig) -> str:
    """'good' | 'bad' | 'marginal' | 'unknown' — the three-way
    accuracy verdict both stages share. 'unknown' (no held-out
    evidence yet) blocks promotion but never rolls back."""
    if inputs.candidate_err is None or inputs.incumbent_err is None:
        return "unknown"
    if inputs.candidate_err > inputs.incumbent_err * \
            max(1e-12, float(cfg.rollback_err_ratio)):
        return "bad"
    if inputs.candidate_err <= inputs.incumbent_err * \
            float(cfg.promote_err_ratio):
        return "good"
    return "marginal"


def tick(
    state: PolicyState,
    inputs: GateInputs,
    cfg: PromotionConfig = PromotionConfig(),
) -> Tuple[PolicyState, str]:
    """One policy decision. Pure: same (state, inputs, cfg) -> same
    (state', reason), no clocks, no side effects."""
    if state.terminal or state.stage == "idle":
        return state, "terminal" if state.terminal else "idle"

    if state.stage == "candidate":
        return PolicyState("shadow"), "shadow_start"

    accuracy = _accuracy(inputs, cfg)

    if state.stage == "shadow":
        if accuracy == "bad":
            return PolicyState("rolled_back"), "accuracy"
        # the shadow-diff gate is the BACKSTOP for candidates without
        # held-out proof: a candidate whose outputs diverge wildly
        # from the incumbent's AND which can't demonstrate good
        # held-out accuracy is suspect. Proven-good candidates are
        # allowed to differ — correcting a stale incumbent's drift is
        # exactly why a refit happens, so output parity with the model
        # being replaced cannot be a hard requirement.
        if (inputs.shadow_pairs >= cfg.min_shadow_pairs
                and inputs.shadow_max_abs > cfg.max_shadow_diff
                and accuracy != "good"):
            return PolicyState("rolled_back"), "shadow_diff"
        if (inputs.shadow_pairs >= cfg.min_shadow_pairs
                and accuracy == "good"):
            return PolicyState("canary"), "canary_start"
        return state, "shadow_wait"

    # canary
    if accuracy == "bad":
        return PolicyState("rolled_back"), "accuracy"
    if inputs.slo_breaching:
        return PolicyState("rolled_back"), "slo_burn"
    if inputs.canary_requests >= cfg.min_canary_requests:
        err_rate = inputs.canary_errors / max(1, inputs.canary_requests)
        if err_rate > cfg.max_canary_error_rate:
            return PolicyState("rolled_back"), "canary_errors"
        if accuracy == "good":
            streak = state.healthy_streak + 1
            if streak >= cfg.promote_after_healthy_ticks:
                return PolicyState("promoted"), "promoted"
            return replace(state, healthy_streak=streak), "canary_healthy"
    # marginal / insufficient evidence: hold position, reset the
    # streak — the hysteresis band (never a rollback)
    return replace(state, healthy_streak=0), "canary_wait"


__all__ = [
    "STAGES",
    "GateInputs",
    "PromotionConfig",
    "PolicyState",
    "tick",
]
