"""Synthetic ground truth for labeled-load drills — numpy only.

``teacher_labels`` reproduces the demo pipeline's forward math
(``serving/bench.build_pipeline``: ``tanh(x @ W + b)`` per layer, the
identical ``default_rng`` draw order) without importing jax or the
serving stack, so ``serve-loadgen`` can synthesize labeled feedback
traffic against a live gateway from nothing but the model's shape
spec. ``head_seed`` redraws the FINAL layer from its own rng stream:
the served incumbent (head from ``seed``'s stream) is then a STALE
model of this teacher, which is exactly the drill setup — streaming
refit learns the teacher's head from feedback, and the candidate
must beat the incumbent on held-out teacher labels.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def teacher_weights(
    d: int, hidden: int, depth: int, seed: int = 0,
    head_seed: Optional[int] = None,
):
    """The demo chain's per-layer ``(W, b)`` list; with ``head_seed``
    the last layer is redrawn from ``default_rng(head_seed)``."""
    rng = np.random.default_rng(seed)
    dims = [d] + [hidden] * (depth - 1) + [d]
    layers = []
    for i in range(depth):
        w = rng.standard_normal((dims[i], dims[i + 1])).astype(
            np.float32
        ) / np.sqrt(dims[i])
        layers.append((w, np.zeros(dims[i + 1], np.float32)))
    if head_seed is not None:
        hrng = np.random.default_rng(head_seed)
        w = hrng.standard_normal((dims[depth - 1], dims[depth])).astype(
            np.float32
        ) / np.sqrt(dims[depth - 1])
        layers[-1] = (w, np.zeros(dims[depth], np.float32))
    return layers


def teacher_labels(
    X,
    d: int,
    hidden: int,
    depth: int,
    seed: int = 0,
    head_seed: Optional[int] = None,
) -> np.ndarray:
    """Ground-truth outputs for instances ``X`` under the (optionally
    head-redrawn) demo model — float32, same tanh chain as serving."""
    h = np.asarray(X, np.float32)
    if h.ndim != 2 or h.shape[1] != d:
        raise ValueError(f"want (n, {d}) instances, got {h.shape}")
    for w, b in teacher_weights(d, hidden, depth, seed, head_seed):
        h = np.tanh(h @ w + b).astype(np.float32)
    return h


__all__ = ["teacher_weights", "teacher_labels"]
