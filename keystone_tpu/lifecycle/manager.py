"""LifecycleManager: the model-id → controller map both serving modes
share.

Single-model gateways hold one controller under the model's name (the
bare ``/feedback`` and ``/lifecyclez`` routes resolve to it); the zoo
attaches the same manager (``ModelZoo.attach_lifecycle``) so
``/feedback/<model>`` and the per-model ``/lifecyclez`` document work
identically with many resident models. Deliberately tiny and
dependency-light — the HTTP layer imports this module, not the
controller stack."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class LifecycleManager:
    """Thread-safe registry of per-model lifecycle controllers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._controllers: Dict[str, object] = {}  # guarded-by: _lock
        self._default: Optional[str] = None  # guarded-by: _lock

    def add(self, controller, default: bool = False) -> None:
        with self._lock:
            name = controller.name
            if name in self._controllers:
                raise ValueError(f"duplicate lifecycle model {name!r}")
            self._controllers[name] = controller
            if default or self._default is None:
                self._default = name

    def get(self, model_id: Optional[str] = None):
        """The controller for ``model_id`` (None -> the default), or
        None when nothing matches."""
        with self._lock:
            if model_id is None:
                model_id = self._default
            return self._controllers.get(model_id)

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._controllers)

    def status(self) -> Dict:
        """The ``/lifecyclez`` document: every model's controller
        status keyed by model id."""
        with self._lock:
            controllers = list(self._controllers.values())
            default = self._default
        return {
            "default_model": default,
            "models": {c.name: c.status() for c in controllers},
        }

    def tick_all(self) -> Dict:
        with self._lock:
            controllers = list(self._controllers.values())
        return {c.name: c.tick() for c in controllers}

    def close(self) -> None:
        with self._lock:
            controllers = list(self._controllers.values())
            self._controllers.clear()
        for c in controllers:
            c.close()


__all__ = ["LifecycleManager"]
