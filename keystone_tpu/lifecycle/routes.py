"""Shadow mirror + canary router: how a candidate engine meets live
traffic.

Both objects wrap the candidate's OWN ``MicroBatcher`` (built by
``Gateway.build_model_batcher`` — same buckets/featurize/sharding
config as the serving lanes, its own engine) and plug into the
``EnginePool`` hooks (``pool.set_mirror`` / ``pool.set_canary``):

- ``ShadowMirror.observe(example, primary_future)`` — called once per
  pool submit, OFF the response path: the example is copied to the
  candidate batcher and the (primary, shadow) outputs are diffed in
  completion callbacks. The primary future is never touched beyond a
  read; a candidate that errors, stalls, or is saturated costs served
  traffic nothing (bounded in-flight, drop-newest).
- ``CanaryRouter`` — ``takes()`` is the DETERMINISTIC per-request
  fraction (``pool.canary_takes`` over a process-local sequence:
  exactly ``floor(n·f)`` of every ``n`` requests, no RNG), and
  ``route`` submits the taken request to the candidate ON the
  response path — but a candidate failure falls back to the incumbent
  lanes through the pool's normal submit path, so a broken candidate
  feeds the policy's error-rate gate without ever failing a caller.
"""

from __future__ import annotations

import itertools
import logging
import threading
from concurrent.futures import Future
from typing import Any, Callable, Optional

import numpy as np

from keystone_tpu.gateway.pool import canary_takes

logger = logging.getLogger(__name__)


class ShadowMirror:
    """Mirror live traffic onto a candidate batcher and keep rolling
    output-diff stats."""

    def __init__(
        self,
        batcher,
        *,
        model: str = "default",
        metrics=None,  # LifecycleMetrics; duck-typed
        max_inflight: int = 64,
    ):
        self._batcher = batcher
        self.model = model
        self._metrics = metrics
        self._max_inflight = int(max_inflight)
        self._lock = threading.Lock()
        self._inflight = 0  # guarded-by: _lock
        self._pairs = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._errors = 0  # guarded-by: _lock
        self._mean_abs = 0.0  # guarded-by: _lock
        self._max_abs = 0.0  # guarded-by: _lock

    def observe(self, example: Any, primary: Future) -> None:
        """Fire-and-forget mirror of one live request. Never raises —
        the pool calls this on its submit path."""
        try:
            with self._lock:
                if self._inflight >= self._max_inflight:
                    self._dropped += 1
                    return
                self._inflight += 1
            shadow = self._batcher.submit(example)
        except Exception:
            with self._lock:
                self._inflight -= 1
                self._errors += 1
            return
        shadow.add_done_callback(
            lambda f: self._pair(primary, f)
        )

    def _pair(self, primary: Future, shadow: Future) -> None:
        # runs on the candidate batcher's delivery thread, after the
        # primary usually already resolved; a still-pending primary
        # chains one more callback instead of blocking this thread
        with self._lock:
            self._inflight -= 1
        if shadow.exception() is not None:
            with self._lock:
                self._errors += 1
            return
        if not primary.done():
            primary.add_done_callback(
                lambda f: self._diff(f, shadow)
            )
            return
        self._diff(primary, shadow)

    def _diff(self, primary: Future, shadow: Future) -> None:
        try:
            if primary.exception() is not None:
                return
            diff = np.abs(
                np.asarray(primary.result(), np.float32)
                - np.asarray(shadow.result(), np.float32)
            )
            mean_abs, max_abs = float(diff.mean()), float(diff.max())
        except Exception:
            with self._lock:
                self._errors += 1
            return
        with self._lock:
            self._pairs += 1
            # rolling mean of means; max is a running max
            self._mean_abs += (mean_abs - self._mean_abs) / self._pairs
            self._max_abs = max(self._max_abs, max_abs)
            stats = (self._mean_abs, self._max_abs)
        if self._metrics is not None:
            self._metrics.record_shadow_pair(*stats)

    def stats(self) -> dict:
        with self._lock:
            return {
                "pairs": self._pairs,
                "mean_abs": round(self._mean_abs, 6),
                "max_abs": round(self._max_abs, 6),
                "errors": self._errors,
                "dropped": self._dropped,
            }


class CanaryRouter:
    """Route a deterministic fraction of live traffic to the
    candidate, with incumbent fallback on any candidate failure."""

    def __init__(
        self,
        batcher,
        fraction: float,
        *,
        model: str = "default",
        metrics=None,  # LifecycleMetrics; duck-typed
    ):
        if not (0.0 <= fraction <= 1.0):
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        self._batcher = batcher
        self.fraction = float(fraction)
        self.model = model
        self._metrics = metrics
        self._seq = itertools.count()  # CPython-atomic next()
        self._lock = threading.Lock()
        self._requests = 0  # guarded-by: _lock
        self._errors = 0  # guarded-by: _lock

    def takes(self) -> bool:
        """The per-request canary decision — deterministic, not
        sampled: over any window of n requests exactly
        ``floor(n·fraction)`` (±1) land on the candidate."""
        return canary_takes(next(self._seq), self.fraction)

    def route(
        self,
        example: Any,
        parent_span_id,
        out: Future,
        fallback: Callable[[], None],
    ) -> None:
        """Serve one taken request from the candidate; any failure
        (submit-time or dispatch) re-routes through ``fallback`` (the
        pool's normal incumbent path) so the caller never sees a
        candidate error — the policy's error-rate gate does."""
        with self._lock:
            self._requests += 1
        try:
            fut = self._batcher.submit(example, parent_span_id=parent_span_id)
        except Exception:
            self._record_error()
            fallback()
            return

        def done(f: Future) -> None:
            if f.exception() is not None:
                self._record_error()
                fallback()
                return
            if self._metrics is not None:
                self._metrics.record_canary("ok")
            out.canary = True
            try:
                out.set_result(f.result())
            except Exception:
                pass  # caller cancelled concurrently

        fut.add_done_callback(done)

    def _record_error(self) -> None:
        with self._lock:
            self._errors += 1
        if self._metrics is not None:
            self._metrics.record_canary("error")

    def stats(self) -> dict:
        with self._lock:
            return {
                "fraction": self.fraction,
                "requests": self._requests,
                "errors": self._errors,
            }


__all__ = ["ShadowMirror", "CanaryRouter"]
