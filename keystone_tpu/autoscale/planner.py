"""Capacity planning: replay the recorded peak, fit the curve, derive
the policy.

``serve-capacity-plan`` answers the question the reactive loop can't:
*how many replicas does a given offered load actually need?* It
replays a workload (a recorded ``--request-log`` trace or a synthetic
spec) through a real router at ×1..×N speed against 1..K supervised
replicas — the same open-loop discipline as ``serve-loadgen``, so
overload actually overloads — and records, per (replicas, speed)
cell: offered rate, achieved p99, shed rate, and whether the SLO
held. From the grid it derives:

- ``capacity(k)`` — the highest offered rate at which ``k`` replicas
  held the SLO (p99 under threshold, sheds under the tolerance);
- a least-squares-through-origin fit ``capacity(k) ≈ per_replica_rps
  × k`` — the replicas-vs-offered-load curve;
- the policy block a ``PolicyConfig.from_plan`` consumes
  (``per_replica_rps``, ``target_utilization``, the SLO) — so the
  autoscaler's thresholds are measured, not guessed.

The artifact is one JSON file (``--out``); the control loop loads it
with ``serve-autoscale --plan plan.json``.

Replicas come from the same ``Supervisor`` the autoscaler uses:
``--mode subprocess`` spawns real ``serve-gateway`` processes (share
an AOT store to keep the K legs warm); the default ``--mode inproc``
builds them as in-process threads over the bench pipeline — what CI
and the tests run, same measurement harness, no per-replica JAX
import.
"""

from __future__ import annotations

import argparse
import json
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from keystone_tpu.autoscale.supervisor import Supervisor

logger = logging.getLogger(__name__)

# shed tolerance for an "SLO held" cell: a capacity point where the
# gateway is already shedding isn't capacity, it's the cliff edge
DEFAULT_MAX_SHED_RATE = 0.01


def fit_capacity(
    capacity_by_replicas: Dict[int, float],
) -> Optional[float]:
    """Least-squares slope THROUGH THE ORIGIN of (k, capacity(k)) —
    zero replicas serve zero rps, so the intercept is not a free
    parameter. Only cells with measured capacity > 0 contribute;
    None when nothing held the SLO anywhere (the plan then carries
    the grid but derives no rate)."""
    pts = [
        (k, c) for k, c in capacity_by_replicas.items() if c > 0
    ]
    if not pts:
        return None
    num = sum(k * c for k, c in pts)
    den = sum(k * k for k, c in pts)
    return num / den if den else None


def derive_policy(
    per_replica_rps: Optional[float],
    slo_latency_s: float,
    target_utilization: float = 0.7,
) -> Dict[str, Any]:
    """The ``policy`` block of the artifact — exactly the fields
    ``PolicyConfig.from_plan`` understands."""
    policy: Dict[str, Any] = {
        "slo_latency_s": slo_latency_s,
        "target_utilization": target_utilization,
    }
    if per_replica_rps is not None:
        policy["per_replica_rps"] = round(per_replica_rps, 3)
    return policy


def run_grid(
    supervisor: Supervisor,
    target_url: str,
    events,
    *,
    replica_counts: Sequence[int],
    speeds: Sequence[float],
    slo_latency_s: float,
    max_shed_rate: float = DEFAULT_MAX_SHED_RATE,
    max_outstanding: int = 64,
    default_shape: Sequence[int] = (8,),
    wait_ready,
    emit=None,
) -> List[Dict[str, Any]]:
    """The measurement grid: for each replica count (ascending — the
    supervisor scales up between legs, reusing warm replicas), replay
    ``events`` at each speed through ``target_url`` and record the
    cell. ``wait_ready(k)`` blocks until the fleet reports ``k``
    ready replicas (the caller owns the router handle)."""
    from keystone_tpu.loadgen.runner import HttpTarget, LoadGenerator

    if not events:
        raise ValueError("capacity plan needs a non-empty workload")
    base_duration = max(e.ts for e in events) or 1.0
    rows: List[Dict[str, Any]] = []
    for k in sorted(set(int(k) for k in replica_counts)):
        supervisor.scale_to(k)
        wait_ready(k)
        for speed in speeds:
            gen = LoadGenerator(
                HttpTarget(target_url, default_shape=default_shape),
                max_outstanding=max_outstanding,
            )
            report = gen.run(
                events, speed=float(speed), recovery_probe_s=0.0
            )
            stats = report.by_status()
            total = len(report.records)
            shed = stats.get("shed", 0)
            lost = stats.get("lost", 0)
            errors = stats.get("error", 0)
            p99 = report.p99()
            offered_rps = len(events) / (base_duration / float(speed))
            ok = (
                lost == 0
                and errors == 0
                and p99 is not None
                and p99 <= slo_latency_s
                and (shed / total if total else 1.0) <= max_shed_rate
            )
            row = {
                "replicas": k,
                "speed": float(speed),
                "offered_rps": round(offered_rps, 2),
                "p99_ms": (
                    round(p99 * 1e3, 3) if p99 is not None else None
                ),
                "shed_rate": round(shed / total, 4) if total else None,
                "lost": lost,
                "errors": errors,
                "slo_held": ok,
            }
            rows.append(row)
            if emit is not None:
                emit({"cell": row})
    return rows


def build_artifact(
    rows: List[Dict[str, Any]],
    slo_latency_s: float,
    slo_target: float,
    target_utilization: float = 0.7,
) -> Dict[str, Any]:
    """Grid rows -> the plan artifact (capacity curve + fit + derived
    policy)."""
    capacity: Dict[int, float] = {}
    for row in rows:
        k = row["replicas"]
        capacity.setdefault(k, 0.0)
        if row["slo_held"]:
            capacity[k] = max(capacity[k], row["offered_rps"])
    per_replica = fit_capacity(capacity)
    return {
        "kind": "keystone-capacity-plan",
        "slo": {"latency_s": slo_latency_s, "target": slo_target},
        "rows": rows,
        "capacity_rps_by_replicas": {
            str(k): round(c, 2) for k, c in sorted(capacity.items())
        },
        "fit": {
            "per_replica_rps": (
                round(per_replica, 3) if per_replica is not None else None
            ),
            "model": "capacity(k) = per_replica_rps * k "
                     "(least squares through origin)",
        },
        "policy": derive_policy(
            per_replica, slo_latency_s, target_utilization
        ),
    }


def _parse_list(spec: str, cast) -> List:
    return [cast(part) for part in spec.split(",") if part.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m keystone_tpu serve-capacity-plan`` — see module
    docstring."""
    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-capacity-plan",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    wl = ap.add_argument_group("workload")
    wl.add_argument("--trace", default=None, metavar="FILE",
                    help="replay this --request-log JSONL recording "
                    "(the recorded peak)")
    wl.add_argument("--synthetic", type=int, default=None, metavar="N",
                    help="synthesize N requests instead of --trace")
    wl.add_argument("--ramp", default=None, metavar="RATE:DUR,...",
                    help="synthesize a STEP/RAMP staircase instead of "
                    "--trace/--synthetic (same grammar as "
                    "serve-loadgen --ramp) — note each grid cell "
                    "replays the whole staircase at its speed")
    wl.add_argument("--arrivals", default="poisson")
    wl.add_argument("--rate", type=float, default=20.0,
                    help="mean synthetic arrival rate at speed x1")
    wl.add_argument("--size-mix", default="1:1.0")
    wl.add_argument("--deadline-ms", type=float, default=None)
    wl.add_argument("--seed", type=int, default=0)

    grid = ap.add_argument_group("grid")
    grid.add_argument("--replicas", default="1,2", metavar="K,...",
                      help="replica counts to measure (ascending)")
    grid.add_argument("--speeds", default="1,2,4", metavar="X,...",
                      help="replay speed multipliers per replica count")
    grid.add_argument("--slo-latency-ms", type=float, required=True,
                      help="the latency objective a cell must hold")
    grid.add_argument("--slo-target", type=float, default=0.99)
    grid.add_argument("--max-shed-rate", type=float,
                      default=DEFAULT_MAX_SHED_RATE)
    grid.add_argument("--target-utilization", type=float, default=0.7,
                      help="fraction of fitted capacity the derived "
                      "policy plans replicas for")
    grid.add_argument("--max-outstanding", type=int, default=64)

    fleet = ap.add_argument_group("fleet under test")
    fleet.add_argument("--mode", choices=("inproc", "subprocess"),
                       default="inproc",
                       help="inproc: replicas as in-process threads "
                       "over the bench pipeline (CI-friendly); "
                       "subprocess: real serve-gateway processes "
                       "(share --aot-cache for warm legs)")
    fleet.add_argument("--d", type=int, default=64)
    fleet.add_argument("--hidden", type=int, default=64)
    fleet.add_argument("--depth", type=int, default=2)
    fleet.add_argument("--buckets", default="4,16")
    fleet.add_argument("--lanes", type=int, default=1)
    fleet.add_argument("--aot-cache", default=None, metavar="DIR",
                       help="shared AOT store for subprocess replicas")
    fleet.add_argument("--startup-timeout", type=float, default=180.0)

    out = ap.add_argument_group("output")
    out.add_argument("--out", default=None, metavar="FILE",
                     help="write the JSON plan artifact here "
                     "(default: stdout only)")
    args = ap.parse_args(argv)

    # the ONE workload builder serve-loadgen uses too — a capacity
    # plan must measure exactly the workload a drill would replay
    from keystone_tpu.loadgen.cli import build_workload

    events = build_workload(args)
    replica_counts = _parse_list(args.replicas, int)
    speeds = _parse_list(args.speeds, float)
    slo_latency_s = args.slo_latency_ms / 1e3

    def emit(doc):
        print(json.dumps(doc), flush=True)

    from keystone_tpu.fleet import RouterServer
    from keystone_tpu.observability.registry import MetricsRegistry

    router = RouterServer(
        [], port=0, name="capacity-plan",
        registry=MetricsRegistry(), probe_interval_s=0.5,
    ).start()
    supervisor = _build_supervisor(args, router.url())
    try:

        def wait_ready(k: int) -> None:
            deadline = time.perf_counter() + args.startup_timeout
            while time.perf_counter() < deadline:
                ready = sum(
                    1
                    for r in router.fleet.replicas()
                    if r.healthy and r.ready
                )
                if ready >= k:
                    return
                router.fleet.probe_once()
                time.sleep(0.25)
            raise SystemExit(
                f"fleet never reached {k} ready replicas within "
                f"{args.startup_timeout:.0f}s"
            )

        rows = run_grid(
            supervisor,
            router.url(),
            events,
            replica_counts=replica_counts,
            speeds=speeds,
            slo_latency_s=slo_latency_s,
            max_shed_rate=args.max_shed_rate,
            max_outstanding=args.max_outstanding,
            default_shape=(args.d,),
            wait_ready=wait_ready,
            emit=emit,
        )
    finally:
        supervisor.stop()
        router.stop()
    artifact = build_artifact(
        rows, slo_latency_s, args.slo_target,
        target_utilization=args.target_utilization,
    )
    doc = json.dumps(artifact, indent=1)
    print(doc, flush=True)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(doc + "\n")
        print(json.dumps({"plan_written": args.out}), flush=True)
    # a plan with no fitted rate is a failed measurement, not a plan
    return 0 if artifact["fit"]["per_replica_rps"] is not None else 1


def _build_supervisor(args, router_url: str) -> Supervisor:
    from keystone_tpu.autoscale.supervisor import (
        InprocLauncher,
        SubprocessLauncher,
    )

    if args.mode == "subprocess":
        gw_args = [
            "--d", str(args.d), "--hidden", str(args.hidden),
            "--depth", str(args.depth), "--buckets", args.buckets,
            "--lanes", str(args.lanes),
        ]
        if args.aot_cache:
            gw_args += ["--aot-cache", args.aot_cache]
        return Supervisor(
            SubprocessLauncher(router_url, gw_args),
            router_url,
            startup_timeout_s=args.startup_timeout,
        )

    # inproc: replicas over the bench pipeline, private registries
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.observability.registry import MetricsRegistry
    from keystone_tpu.serving.bench import build_pipeline

    fitted = build_pipeline(d=args.d, hidden=args.hidden, depth=args.depth)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    def factory(index: int):
        reg = MetricsRegistry()
        gw = Gateway(
            fitted,
            buckets=buckets,
            n_lanes=args.lanes,
            warmup_example=jnp.zeros((args.d,), jnp.float32),
            name=f"plan-r{index}",
            registry=reg,
        )
        srv = GatewayServer(gw, port=0, registry=reg).start()
        return gw, srv

    return Supervisor(
        InprocLauncher(factory),
        router_url,
        startup_timeout_s=args.startup_timeout,
    )


__all__ = [
    "DEFAULT_MAX_SHED_RATE",
    "build_artifact",
    "derive_policy",
    "fit_capacity",
    "main",
    "run_grid",
]
