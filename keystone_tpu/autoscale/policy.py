"""The autoscale policy: fleet observations in, scaling decisions out.

The control loop's brain, deliberately PURE — no HTTP, no threads, no
clocks of its own. ``PolicyEngine.decide(n, obs)`` consumes one
``FleetObservation`` (what the controller scraped off the router's
``/metrics`` + ``/slz`` + ``/fleetz`` this tick) and the current
replica target, and returns a ``Decision``. All the judgement calls
live here where they are unit-testable with synthetic observations:

- **pressure signals** — a tick is *hot* when the fleet p99 breaches
  the SLO threshold or the fast-window burn rate says the error
  budget is being torched (``up_burn``); *cold* when the burn is back
  under ``down_burn`` AND the p99 sits inside the headroom band
  (``down_p99_headroom`` × threshold). Between the two is the
  hysteresis dead band: neither streak advances, so load flapping at
  the threshold can never oscillate the fleet.
- **phase attribution** — scale-out only helps when requests are
  waiting for CAPACITY. The per-request phase decomposition
  (``keystone_request_phase_seconds``, PR 11) says where latency
  goes: a ``queue_wait``-dominated fleet gets more replicas; a
  ``device``-dominated one does not (the same requests would just
  queue on more devices' hosts) — the decision is vetoed with reason
  ``device_bound`` instead of burning money on replicas that can't
  help. Absent phase data (tracing off, no traffic) degrades to
  permitting the burn-driven decision, counted as such.
- **hysteresis + cooldowns** — ``up_consecutive`` / ``down_consecutive``
  hot/cold ticks in a row before acting, plus per-direction cooldowns
  after any action. Scale-down is additionally BANNED while any
  replica is half-open or benched unhealthy: a degraded fleet that
  looks over-provisioned is mid-recovery, not idle.
- **measured capacity** (optional) — a ``serve-capacity-plan``
  artifact carries the fitted per-replica request rate; when present
  the scale-up target jumps straight to
  ``ceil(offered_rps / (target_utilization × per_replica_rps))``
  instead of creeping one replica per cooldown window through a big
  step — the policy is measured, not guessed.

Every decision carries its reason and the observation that produced
it, so the controller can log/export/trace it verbatim.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, Optional

# the phase names whose dominance means "more replicas help": time
# spent waiting for admission/coalescing capacity, not device compute
QUEUE_PHASES = ("queue_wait", "coalesce")

# phase whose dominance means "more replicas will NOT help"
DEVICE_PHASE = "device"


@dataclasses.dataclass
class FleetObservation:
    """One control-loop tick's view of the fleet, as scraped off the
    router (``controller.RouterScraper``). Every field is Optional or
    defaulted because a real scrape degrades: a dead replica, an
    empty fleet, tracing off — the policy must decide on partial
    evidence without inventing values."""

    t: float  # monotonic observation clock (the engine's cooldowns)
    replicas_total: int = 0
    replicas_ready: int = 0
    replicas_half_open: int = 0
    replicas_unhealthy: int = 0
    replicas_unreachable: int = 0
    fleet_p99_s: Optional[float] = None
    burn_fast: Optional[float] = None
    burn_slow: Optional[float] = None
    # did the /metrics scrape SUCCEED this tick? An idle fleet (scrape
    # fine, no traffic) and a blind one (scrape failed) both show
    # p99=None — only the former may ever read as cold
    metrics_ok: bool = False
    offered_rps: Optional[float] = None
    load_total: Optional[float] = None
    requests_total: Optional[float] = None  # cumulative router counter
    # the cumulative federated latency buckets this tick ({le: count};
    # the scraper windows successive snapshots into fleet_p99_s)
    latency_buckets: Dict[float, float] = dataclasses.field(
        default_factory=dict
    )
    # phase -> fraction of decomposed request time spent there, from
    # the stitched traces sampled this tick ({} = no phase evidence)
    phase_shares: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )

    @property
    def dominant_phase(self) -> Optional[str]:
        if not self.phase_shares:
            return None
        return max(self.phase_shares, key=self.phase_shares.get)

    def as_dict(self) -> Dict[str, Any]:
        doc = dataclasses.asdict(self)
        # the raw bucket snapshot is scrape plumbing, not something a
        # decision event should drag along
        doc.pop("latency_buckets", None)
        doc["dominant_phase"] = self.dominant_phase
        return doc


@dataclasses.dataclass
class Decision:
    """One tick's verdict: ``action`` is ``scale_up`` / ``scale_down``
    / ``hold``; ``target`` is the replica count the supervisor should
    converge to (unchanged on hold). ``reason`` explains the action
    OR the veto that blocked one — ``hold`` with reason
    ``device_bound`` is as informative as an action."""

    action: str
    target: int
    reason: str
    hot_streak: int = 0
    cold_streak: int = 0
    observation: Optional[FleetObservation] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "target": self.target,
            "reason": self.reason,
            "hot_streak": self.hot_streak,
            "cold_streak": self.cold_streak,
            "observation": (
                self.observation.as_dict()
                if self.observation is not None
                else None
            ),
        }


@dataclasses.dataclass
class PolicyConfig:
    """The policy's knobs. Defaults are production-flavored (tens of
    seconds); the bench/smoke paths shrink them to single seconds —
    the ARITHMETIC is what's under test, not the wall clock."""

    min_replicas: int = 1
    max_replicas: int = 4
    # the latency objective the policy holds (None = burn-rate only)
    slo_latency_s: Optional[float] = None
    up_burn: float = 1.5
    down_burn: float = 0.5
    up_consecutive: int = 2
    down_consecutive: int = 4
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 60.0
    # scale-down needs the p99 comfortably inside the objective, not
    # just under it — the other half of the hysteresis band
    down_p99_headroom: float = 0.5
    # veto scale-up when the device phase outweighs the queue phases
    # in the decomposition (more replicas can't shorten device time)
    phase_veto: bool = True
    step_up: int = 1
    # measured capacity (serve-capacity-plan artifact); None = react
    # one step at a time
    per_replica_rps: Optional[float] = None
    target_utilization: float = 0.7

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.down_burn >= self.up_burn:
            raise ValueError(
                f"need down_burn ({self.down_burn}) < up_burn "
                f"({self.up_burn}) — the gap IS the hysteresis band"
            )
        if self.up_consecutive < 1 or self.down_consecutive < 1:
            raise ValueError("consecutive tick counts must be >= 1")
        if self.step_up < 1:
            raise ValueError(f"step_up must be >= 1, got {self.step_up}")

    @classmethod
    def from_plan(cls, plan, **overrides) -> "PolicyConfig":
        """Build a config from a ``serve-capacity-plan`` artifact (a
        path or the loaded dict) — the measured-not-guessed path: the
        artifact's fitted ``per_replica_rps`` and derived thresholds
        seed the config, and explicit ``overrides`` win over both."""
        if isinstance(plan, (str, bytes)) or hasattr(plan, "__fspath__"):
            with open(plan, "r", encoding="utf-8") as f:
                plan = json.load(f)
        if not isinstance(plan, dict):
            raise ValueError(
                f"capacity plan must be a dict artifact, got "
                f"{type(plan).__name__}"
            )
        derived = dict(plan.get("policy") or {})
        fit = plan.get("fit") or {}
        if "per_replica_rps" not in derived and fit.get("per_replica_rps"):
            derived["per_replica_rps"] = fit["per_replica_rps"]
        slo = plan.get("slo") or {}
        if "slo_latency_s" not in derived and slo.get("latency_s"):
            derived["slo_latency_s"] = slo["latency_s"]
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(derived) - known
        if unknown:
            raise ValueError(
                f"capacity plan derives unknown policy fields "
                f"{sorted(unknown)} (have {sorted(known)})"
            )
        derived.update(overrides)
        return cls(**derived)


class PolicyEngine:
    """The stateful hysteresis machine over ``PolicyConfig``. One
    instance per control loop; ``decide`` is called once per tick
    from that single loop thread (no internal locking — the
    controller owns the cadence)."""

    def __init__(self, config: Optional[PolicyConfig] = None):
        self.config = config if config is not None else PolicyConfig()
        self._hot_streak = 0
        self._cold_streak = 0
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None

    # -- signal classification ---------------------------------------------

    def _is_hot(self, obs: FleetObservation) -> bool:
        cfg = self.config
        if (
            cfg.slo_latency_s is not None
            and obs.fleet_p99_s is not None
            and obs.fleet_p99_s > cfg.slo_latency_s
        ):
            return True
        return obs.burn_fast is not None and obs.burn_fast >= cfg.up_burn

    def _is_cold(self, obs: FleetObservation) -> bool:
        cfg = self.config
        if not obs.metrics_ok:
            # a failed scrape is blindness, not idleness: absent
            # evidence must never accumulate into shrinking a fleet
            # that may be under live load
            return False
        if obs.burn_fast is not None and obs.burn_fast > cfg.down_burn:
            return False
        if (
            cfg.slo_latency_s is not None
            and obs.fleet_p99_s is not None
            and obs.fleet_p99_s > cfg.slo_latency_s * cfg.down_p99_headroom
        ):
            return False
        return True

    def _device_bound(self, obs: FleetObservation) -> bool:
        """True when the phase decomposition says device compute, not
        capacity starvation, owns the latency — the scale-up veto. No
        phase evidence = not vetoed (burn/latency evidence stands
        alone, counted by the ``phase`` field of the decision's
        observation)."""
        if not self.config.phase_veto or not obs.phase_shares:
            return False
        device = obs.phase_shares.get(DEVICE_PHASE, 0.0)
        queued = sum(
            obs.phase_shares.get(p, 0.0) for p in QUEUE_PHASES
        )
        return device > queued

    def _desired_for_load(self, obs: FleetObservation, n: int) -> int:
        """The capacity-plan feed-forward: replicas the MEASURED
        per-replica rate says this offered load needs. Falls back to
        one step when the plan or the rate observation is absent."""
        cfg = self.config
        if (
            cfg.per_replica_rps
            and cfg.per_replica_rps > 0
            and obs.offered_rps is not None
        ):
            desired = math.ceil(
                obs.offered_rps
                / (cfg.target_utilization * cfg.per_replica_rps)
            )
            if desired > n + cfg.step_up:
                return desired
        return n + cfg.step_up

    # -- the decision ------------------------------------------------------

    def decide(self, n: int, obs: FleetObservation) -> Decision:
        """One tick: classify the observation, advance the streaks,
        apply vetoes, return the verdict. ``n`` is the CURRENT target
        the supervisor converges to (not the momentary process count
        — a replica mid-startup still counts toward the target)."""
        cfg = self.config
        hot, cold = self._is_hot(obs), self._is_cold(obs)

        def hold(reason: str) -> Decision:
            return Decision(
                "hold", n, reason,
                hot_streak=self._hot_streak,
                cold_streak=self._cold_streak,
                observation=obs,
            )

        if hot:
            self._cold_streak = 0
            self._hot_streak += 1
            if self._hot_streak < cfg.up_consecutive:
                return hold("hot_streak_building")
            if n >= cfg.max_replicas:
                return hold("at_max_replicas")
            if (
                self._last_up_t is not None
                and obs.t - self._last_up_t < cfg.up_cooldown_s
            ):
                return hold("up_cooldown")
            if self._device_bound(obs):
                # more replicas cannot shorten the device phase —
                # the one scale-out veto that outranks a burning SLO
                return hold("device_bound")
            target = min(cfg.max_replicas, self._desired_for_load(obs, n))
            self._last_up_t = obs.t
            self._hot_streak = 0
            return Decision(
                "scale_up", target,
                "slo_pressure" if (
                    cfg.slo_latency_s is not None
                    and obs.fleet_p99_s is not None
                    and obs.fleet_p99_s > cfg.slo_latency_s
                ) else "burn_rate",
                observation=obs,
            )

        self._hot_streak = 0
        if not cold:
            # the dead band between hot and cold: BOTH streaks reset,
            # which is what makes threshold flapping oscillation-proof
            self._cold_streak = 0
            return hold("in_band")

        self._cold_streak += 1
        if self._cold_streak < cfg.down_consecutive:
            return hold("cold_streak_building")
        if n <= cfg.min_replicas:
            return hold("at_min_replicas")
        if (
            self._last_down_t is not None
            and obs.t - self._last_down_t < cfg.down_cooldown_s
        ):
            return hold("down_cooldown")
        if obs.replicas_half_open > 0 or obs.replicas_unhealthy > 0:
            # mid-recovery fleets look idle precisely because a
            # replica is benched; shrinking now would be shooting the
            # survivor — the ISSUE's explicit scale-down ban
            return hold("replica_recovering")
        self._last_down_t = obs.t
        self._cold_streak = 0
        return Decision(
            "scale_down", max(cfg.min_replicas, n - 1), "idle",
            observation=obs,
        )


def phase_shares(phase_ms_samples: List[Dict[str, float]]) -> Dict[str, float]:
    """Aggregate per-trace ``phases_ms`` maps (the router's ``/debugz``
    decomposition) into one share-of-total-time map — the policy's
    phase evidence. Empty in, empty out (absent, never zeros)."""
    sums: Dict[str, float] = {}
    for sample in phase_ms_samples:
        for phase, ms in (sample or {}).items():
            if ms is None:
                continue
            sums[phase] = sums.get(phase, 0.0) + float(ms)
    total = sum(sums.values())
    if total <= 0:
        return {}
    return {phase: ms / total for phase, ms in sums.items()}


__all__ = [
    "DEVICE_PHASE",
    "Decision",
    "FleetObservation",
    "PolicyConfig",
    "PolicyEngine",
    "QUEUE_PHASES",
    "phase_shares",
]
