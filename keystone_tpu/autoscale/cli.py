"""``python -m keystone_tpu serve-autoscale`` — the elastic fleet in
one command.

Stands up the whole closed loop:

1. a ``RouterServer`` in this process (the fleet front door:
   ``/predict`` routing, federated ``/metrics``, the fleet latency
   SLO at ``/slz`` — clients and the load generator point HERE);
2. a ``Supervisor`` spawning ``serve-gateway`` replicas as
   subprocesses (``--gateway-port 0`` + the ``{"listening": ...}``
   handshake, ``--register`` self-registration, a shared
   ``--aot-cache`` so scale-out replicas start warm);
3. an ``Autoscaler`` control loop: scrape the router, decide from
   fleet p99 / SLO burn / per-replica load / the phase
   decomposition, and converge the fleet — scale-out under real
   pressure, drain-based scale-down when idle, kill -9'd replicas
   replaced on the next tick.

Every decision prints as a structured JSON event line (the smoke
script parses these), exports ``keystone_autoscale_*`` series on the
router's ``/metrics``, and traces as ``autoscale.*`` spans.

With ``--plan plan.json`` (a ``serve-capacity-plan`` artifact) the
policy's per-replica capacity is MEASURED: scale-up jumps straight to
the replica count the fitted curve says the offered load needs.

The first stdout line is the machine-parseable
``{"listening": <router url>, "role": "autoscaler"}`` handshake,
same contract as serve-gateway/serve-router.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)


def build_parser():
    import argparse

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-autoscale",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--router-port", "--port", dest="port", type=int,
                    default=0, help="router bind port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")

    pol = ap.add_argument_group("policy")
    pol.add_argument("--min-replicas", type=int, default=1)
    pol.add_argument("--max-replicas", type=int, default=4)
    pol.add_argument("--slo-latency-ms", type=float, required=True,
                     help="the fleet latency objective the loop "
                     "holds (declared on the router's /slz too)")
    pol.add_argument("--slo-target", type=float, default=0.99)
    pol.add_argument("--plan", default=None, metavar="FILE",
                     help="a serve-capacity-plan artifact: fitted "
                     "per-replica capacity seeds the policy (explicit "
                     "flags here still win)")
    pol.add_argument("--interval", type=float, default=2.0,
                     help="control-loop tick seconds")
    pol.add_argument("--up-burn", type=float, default=1.5)
    pol.add_argument("--down-burn", type=float, default=0.5)
    pol.add_argument("--up-consecutive", type=int, default=2)
    pol.add_argument("--down-consecutive", type=int, default=4)
    pol.add_argument("--up-cooldown", type=float, default=15.0)
    pol.add_argument("--down-cooldown", type=float, default=30.0)
    pol.add_argument("--slo-fast-window", type=float, default=30.0,
                     help="fast burn window seconds (short for "
                     "drills, minutes in production)")
    pol.add_argument("--slo-sample-interval", type=float, default=1.0)

    gw = ap.add_argument_group("replicas")
    gw.add_argument("--d", type=int, default=64)
    gw.add_argument("--hidden", type=int, default=64)
    gw.add_argument("--depth", type=int, default=2)
    gw.add_argument("--buckets", default="4,16")
    gw.add_argument("--lanes", type=int, default=1)
    gw.add_argument("--max-delay-ms", type=float, default=2.0)
    gw.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="shared AOT executable store for the "
                    "replicas (scale-out starts warm; strongly "
                    "recommended)")
    gw.add_argument("--replica-log-dir", default=None, metavar="DIR",
                    help="where replica stdout logs land (default: "
                    "$TMPDIR/keystone-autoscale)")
    gw.add_argument("--gateway-arg", action="append", default=[],
                    metavar="ARG",
                    help="extra raw argument passed to every spawned "
                    "serve-gateway (repeatable)")
    gw.add_argument("--startup-timeout", type=float, default=180.0)
    gw.add_argument("--drain-timeout", type=float, default=30.0)
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    import signal

    from keystone_tpu.autoscale.controller import (
        Autoscaler,
        RouterScraper,
    )
    from keystone_tpu.autoscale.policy import PolicyConfig, PolicyEngine
    from keystone_tpu.autoscale.supervisor import (
        SubprocessLauncher,
        Supervisor,
    )
    from keystone_tpu.fleet import RouterServer
    from keystone_tpu.observability import enable_tracing

    args = build_parser().parse_args(argv)
    # the decision spans + the phase stitching the policy consumes
    # both ride the tracer
    enable_tracing()

    overrides = dict(
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        slo_latency_s=args.slo_latency_ms / 1e3,
        up_burn=args.up_burn,
        down_burn=args.down_burn,
        up_consecutive=args.up_consecutive,
        down_consecutive=args.down_consecutive,
        up_cooldown_s=args.up_cooldown,
        down_cooldown_s=args.down_cooldown,
    )
    if args.plan:
        config = PolicyConfig.from_plan(args.plan, **overrides)
    else:
        config = PolicyConfig(**overrides)

    router = RouterServer(
        port=args.port,
        host=args.host,
        name="autoscaler",
        probe_interval_s=min(1.0, args.interval),
        slo_latency_s=args.slo_latency_ms / 1e3,
        slo_target=args.slo_target,
        slo_fast_window_s=args.slo_fast_window,
        slo_slow_window_s=max(
            args.slo_fast_window * 10, args.slo_fast_window + 1.0
        ),
        slo_sample_interval_s=args.slo_sample_interval,
    ).start()

    gw_args = [
        "--d", str(args.d), "--hidden", str(args.hidden),
        "--depth", str(args.depth), "--buckets", args.buckets,
        "--lanes", str(args.lanes),
        "--max-delay-ms", str(args.max_delay_ms),
        # replicas adopt the router's traceparent so the phase
        # decomposition the policy reads has both halves to stitch
        "--trace",
        *args.gateway_arg,
    ]
    if args.aot_cache:
        gw_args += ["--aot-cache", args.aot_cache]

    def emit_event(doc):
        print(json.dumps(doc), flush=True)

    supervisor = Supervisor(
        SubprocessLauncher(
            router.url(), gw_args, log_dir=args.replica_log_dir
        ),
        router.url(),
        startup_timeout_s=args.startup_timeout,
        drain_timeout_s=args.drain_timeout,
        on_event=emit_event,
    )
    autoscaler = Autoscaler(
        supervisor,
        RouterScraper(
            router.url(), p99_window_s=args.slo_fast_window
        ),
        PolicyEngine(config),
        interval_s=args.interval,
        name="autoscaler",
        on_event=emit_event,
    )

    # the machine-parseable handshake FIRST (smoke scripts read it),
    # then the human summary
    print(
        json.dumps(
            {
                "listening": router.url().rstrip("/"),
                "role": "autoscaler",
                "min_replicas": config.min_replicas,
                "max_replicas": config.max_replicas,
            }
        ),
        flush=True,
    )
    print(
        f"autoscaler: router {router.url()} — POST /predict, "
        f"GET /fleetz /metrics /slz; policy "
        f"[{config.min_replicas}..{config.max_replicas}] replicas, "
        f"SLO p99 <= {args.slo_latency_ms:g}ms"
        + (f", plan {args.plan}" if args.plan else ""),
        flush=True,
    )

    # signal handlers BEFORE the initial scale-up: the first replica
    # cold start can take minutes, and a SIGTERM landing inside it
    # must still reach the graceful path below — the default
    # disposition would kill this process and leak the half-started
    # serve-gateway child
    stop = threading.Event()

    def handle(signum, frame):
        logger.info("autoscaler: signal %d, stopping", signum)
        stop.set()

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)

    supervisor.scale_to(config.min_replicas)
    autoscaler.start()
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    autoscaler.stop()
    supervisor.stop()  # drain-based retirement of every replica
    router.stop()
    return 0


__all__ = ["build_parser", "main"]
