"""The supervisor: replica processes as a managed, elastic set.

``Supervisor`` owns N replica handles and converges them to a target
count (``scale_to``), the way ``EnginePool`` owns lanes — except a
"lane" here is a whole ``serve-gateway`` PROCESS and the membership
protocol is the fleet tier's:

- **launch** — a ``Launcher`` produces handles. The production one
  (``SubprocessLauncher``) spawns ``python -m keystone_tpu
  serve-gateway --gateway-port 0 --register <router> ...`` and reads
  the machine-parseable ``{"listening": ...}`` first-stdout-line
  handshake for the bound address (the same contract the smoke
  drills use — port 0 means no port races, and the replica
  self-registers with the router on its own). ``InprocLauncher``
  runs the same topology as in-process threads over a caller-supplied
  factory — what the bench row and the unit tests use, so the
  supervisor's logic is exercised without paying a JAX import per
  replica.
- **retire** (graceful drain) — scale-down is the three-step
  fleet-exit protocol, in order: (1) ``POST /deregisterz`` on the
  router, so the roster drops the replica and NO new forwards land on
  it; (2) drain the replica (SIGTERM for subprocesses — the gateway's
  handler stops admitting, finishes in-flight windows, deregisters
  itself again harmlessly, and exits); (3) bounded wait, then kill as
  the last resort. Retirement runs on its own daemon thread so a slow
  drain never stalls the control loop.
- **reap** (repair) — a handle whose process died without being
  retired (kill -9, OOM, crash) is detected by ``reap()``, removed
  from the roster (its stale URL deregistered), and REPLACED to hold
  the target — repair is not subject to the policy's cooldowns, it
  is not a scaling decision.

The supervisor never decides anything: the policy engine decides,
the controller calls ``scale_to``/``reap``. Lock discipline follows
the fleet tier's: the lock guards only the handle list — every HTTP
call, process wait, and launch happens outside it.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

# how long a spawned replica gets from exec() to its {"listening"}
# handshake line (a cold start pays the JAX import + warmup; the AOT
# store keeps this in single-digit seconds, but CI boxes are slow)
STARTUP_TIMEOUT_S = 180.0

# graceful-drain bound before a retiring replica is killed outright
DRAIN_TIMEOUT_S = 30.0


def deregister_replica(
    router_url: str, replica_url: str, timeout_s: float = 5.0
) -> bool:
    """``POST /deregisterz`` one replica URL off a router's roster —
    the shared best-effort client (``fleet/client.py``), re-exported
    here because it is half of the supervisor's retirement
    protocol."""
    from keystone_tpu.fleet.client import try_deregister

    return try_deregister(router_url, replica_url, timeout_s=timeout_s)


class SubprocessReplica:
    """One spawned ``serve-gateway`` process. A reader thread tees the
    child's stdout/stderr into a log file and parses the FIRST
    ``{"listening": ...}`` JSON line — the handshake the supervisor
    blocks on before counting the replica toward the fleet."""

    def __init__(self, proc: subprocess.Popen, name: str, log_path: str):
        self.proc = proc
        self.name = name
        self.log_path = log_path
        self.pid = proc.pid
        self._url: Optional[str] = None
        self._url_event = threading.Event()
        self._reader = threading.Thread(
            target=self._read_output,
            name=f"keystone-{name}-output",
            daemon=True,
        )
        self._reader.start()

    def _read_output(self) -> None:
        try:
            with open(
                self.log_path, "a", buffering=1, encoding="utf-8"
            ) as log:
                for raw in self.proc.stdout:
                    line = raw.decode("utf-8", "replace") if isinstance(
                        raw, bytes
                    ) else raw
                    log.write(line)
                    if self._url is None and line.lstrip().startswith("{"):
                        try:
                            doc = json.loads(line)
                        except ValueError:
                            continue
                        url = doc.get("listening")
                        if isinstance(url, str):
                            self._url = url.rstrip("/")
                            self._url_event.set()
        except Exception:
            logger.exception(
                "replica %s: output reader failed", self.name
            )
        finally:
            # a child that exits without ever printing the handshake
            # must not strand wait_listening for the whole timeout
            self._url_event.set()

    @property
    def url(self) -> Optional[str]:
        return self._url

    def wait_listening(self, timeout_s: float) -> Optional[str]:
        """Block until the handshake line arrives (or the child dies /
        the bound expires). Returns the bound base URL or None."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            remaining = deadline - time.perf_counter()
            self._url_event.wait(min(1.0, max(0.0, remaining)))
            if self._url is not None:
                return self._url
            if self.proc.poll() is not None:
                return None  # died before binding
            self._url_event.clear()
        return self._url

    def alive(self) -> bool:
        return self.proc.poll() is None

    def drain(self) -> None:
        """Ask for a graceful exit: SIGTERM -> the gateway's handler
        drains (stop admitting, finish in-flight, deregister) and the
        process exits on its own."""
        if self.alive():
            try:
                self.proc.terminate()
            except OSError:
                pass

    def kill(self) -> None:
        if self.alive():
            try:
                self.proc.kill()
            except OSError:
                pass

    def wait(self, timeout_s: float) -> bool:
        try:
            self.proc.wait(timeout=timeout_s)
            return True
        except subprocess.TimeoutExpired:
            return False

    def status(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "url": self._url,
            "pid": self.pid,
            "alive": self.alive(),
            "log": self.log_path,
        }


class SubprocessLauncher:
    """Spawn real ``serve-gateway`` replica processes (the production
    path — one process per replica, self-registering against the
    router, sharing the AOT executable store so scale-out is warm)."""

    # serve-gateway --register handles its own roster entry; the
    # supervisor must not double-register
    self_registering = True

    def __init__(
        self,
        router_url: str,
        gateway_args: Sequence[str] = (),
        *,
        log_dir: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        python: Optional[str] = None,
    ):
        self.router_url = router_url.rstrip("/")
        self.gateway_args = list(gateway_args)
        self.log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "keystone-autoscale"
        )
        self.env = env
        self.python = python or sys.executable

    def launch(self, index: int) -> SubprocessReplica:
        os.makedirs(self.log_dir, exist_ok=True)
        name = f"replica-{index}"
        log_path = os.path.join(self.log_dir, f"{name}.log")
        cmd = [
            self.python, "-m", "keystone_tpu", "serve-gateway",
            "--gateway-port", "0",
            "--register", self.router_url,
            *self.gateway_args,
        ]
        env = dict(os.environ if self.env is None else self.env)
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        logger.info(
            "supervisor: spawned %s (pid %d) -> %s",
            name, proc.pid, log_path,
        )
        return SubprocessReplica(proc, name, log_path)


class InprocReplica:
    """A replica that is a (gateway, server) pair of in-process
    threads — same lifecycle surface as ``SubprocessReplica``, no
    process. ``kill()`` stops the HTTP listener WITHOUT draining,
    which is as close to kill -9 as one process can get (in-flight
    futures resolve, but the 'host' vanishes from the network)."""

    def __init__(self, gateway, server, name: str):
        self.gateway = gateway
        self.server = server
        self.name = name
        self.pid = None
        self.log_path = None
        self._killed = False
        self._cached_url: Optional[str] = None

    @property
    def url(self) -> Optional[str]:
        # cached at first read: a kill()'d listener can no longer say
        # where it WAS bound, and reap() must still deregister that
        # URL off the router's roster
        if self._cached_url is None:
            try:
                self._cached_url = self.server.url().rstrip("/")
            except RuntimeError:
                return None  # stopped before ever read
        return self._cached_url

    def wait_listening(self, timeout_s: float) -> Optional[str]:
        return self.url

    def alive(self) -> bool:
        return not self._killed and self.gateway.ready

    def drain(self) -> None:
        def run():
            self.gateway.close()
            self.server.stop()
            self._killed = True

        threading.Thread(
            target=run, name=f"keystone-{self.name}-drain", daemon=True
        ).start()

    def kill(self) -> None:
        self._killed = True
        self.server.stop()
        self.gateway.close(timeout=1.0)

    def wait(self, timeout_s: float) -> bool:
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if self._killed:
                return True
            time.sleep(0.05)
        return self._killed

    def status(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "url": self.url,
            "pid": None,
            "alive": self.alive(),
            "log": None,
        }


class InprocLauncher:
    """Build replicas in-process via a caller-supplied
    ``factory(index) -> (gateway, server)`` (server already started).
    The bench row's path: the supervisor/policy/controller machinery
    runs for real while replicas cost threads, not JAX imports. The
    factory owns registration semantics; by default the supervisor
    POSTs ``/registerz`` for these replicas."""

    self_registering = False

    def __init__(self, factory: Callable[[int], tuple]):
        self.factory = factory

    def launch(self, index: int) -> InprocReplica:
        gateway, server = self.factory(index)
        return InprocReplica(gateway, server, f"replica-{index}")


class Supervisor:
    """Converge a replica set to a target count over one launcher.

    Thread-safety: ``scale_to``/``reap``/``stop`` are called from the
    controller's single loop thread (plus ``stop`` from shutdown);
    the lock guards only the handle list and the target — launches,
    drains, HTTP, and process waits all run outside it."""

    def __init__(
        self,
        launcher,
        router_url: Optional[str] = None,
        *,
        startup_timeout_s: float = STARTUP_TIMEOUT_S,
        drain_timeout_s: float = DRAIN_TIMEOUT_S,
        on_event: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.launcher = launcher
        self.router_url = (
            router_url.rstrip("/") if router_url else None
        )
        self.startup_timeout_s = float(startup_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._on_event = on_event
        self._lock = threading.Lock()
        self._handles: List = []  # guarded-by: _lock
        self._target = 0  # guarded-by: _lock
        self._next_index = 0  # guarded-by: _lock
        self._replaced_total = 0  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock

    # -- introspection ------------------------------------------------------

    @property
    def target(self) -> int:
        with self._lock:
            return self._target

    def replicas(self) -> List:
        with self._lock:
            return list(self._handles)

    @property
    def replaced_total(self) -> int:
        with self._lock:
            return self._replaced_total

    def status(self) -> Dict[str, Any]:
        handles = self.replicas()
        return {
            "target": self.target,
            "running": sum(1 for h in handles if h.alive()),
            "replaced_total": self.replaced_total,
            "replicas": [h.status() for h in handles],
        }

    def _event(self, event: str, **fields: Any) -> None:
        doc = {"event": event, **fields}
        logger.info("supervisor: %s", json.dumps(doc))
        if self._on_event is not None:
            try:
                self._on_event(doc)
            except Exception:
                logger.exception("supervisor event sink failed")

    # -- growth -------------------------------------------------------------

    def _launch_one(self) -> Optional[Any]:
        """Launch + handshake + (maybe) register ONE replica; returns
        the handle once it's a routable fleet member, None on a
        launch that never bound (the dead handle is reaped away)."""
        with self._lock:
            if self._stopped:
                return None
            index = self._next_index
            self._next_index += 1
        handle = self.launcher.launch(index)
        url = handle.wait_listening(self.startup_timeout_s)
        if url is None:
            self._event(
                "replica_failed_to_start",
                name=handle.name, pid=handle.pid,
            )
            handle.kill()
            return None
        if (
            not getattr(self.launcher, "self_registering", False)
            and self.router_url is not None
        ):
            self._register(url)
        with self._lock:
            if self._stopped:
                stopped = True
            else:
                self._handles.append(handle)
                stopped = False
        if stopped:
            # stop() won the race: this replica must not outlive the
            # supervisor — retire it instead of appending
            self._retire_handle(handle)
            return None
        self._event(
            "replica_started",
            name=handle.name, url=url, pid=handle.pid,
        )
        return handle

    def _register(self, url: str) -> None:
        from keystone_tpu.fleet.client import REGISTER_ROUTE, post_roster

        try:
            post_roster(self.router_url, REGISTER_ROUTE, url, timeout_s=10)
        except Exception as e:
            logger.warning(
                "supervisor: register of %s failed: %s", url, e
            )

    # -- retirement ---------------------------------------------------------

    def _deregister(self, url: str) -> None:
        """The one roster-removal seam (retirement AND reap use it)."""
        if self.router_url is not None and url:
            deregister_replica(self.router_url, url)

    def _retire_handle(self, handle) -> None:
        """The three-step exit (deregister -> drain -> bounded wait ->
        kill), run on the caller's thread."""
        url = handle.url
        self._deregister(url)
        handle.drain()
        if not handle.wait(self.drain_timeout_s):
            logger.warning(
                "supervisor: %s did not drain within %.0fs; killing",
                handle.name, self.drain_timeout_s,
            )
            handle.kill()
            handle.wait(5.0)
        self._event("replica_retired", name=handle.name, url=url)

    def _retire_async(self, handle) -> None:
        threading.Thread(
            target=self._retire_handle,
            args=(handle,),
            name=f"keystone-retire-{handle.name}",
            daemon=True,
        ).start()

    def _launch_many(self, n: int) -> int:
        """Launch ``n`` replicas CONCURRENTLY and wait for their
        handshakes; returns how many came up. Serial launches would
        multiply scale-out reaction time by the shortfall — a
        capacity-plan feed-forward jump exists precisely so a big
        load step costs ONE cold start of wall clock, not N."""
        if n <= 0:
            return 0
        if n == 1:
            return 1 if self._launch_one() is not None else 0
        results: List = []
        res_lock = threading.Lock()

        def run():
            handle = self._launch_one()
            with res_lock:
                results.append(handle)

        threads = [
            threading.Thread(
                target=run, name="keystone-launch", daemon=True
            )
            for _ in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(1 for h in results if h is not None)

    # -- the convergence entry points ---------------------------------------

    def scale_to(self, n: int) -> int:
        """Converge toward ``n`` replicas: launch the shortfall
        concurrently (each waits out its handshake), retire the
        excess newest-first on background drain threads. Returns the
        new target."""
        if n < 0:
            raise ValueError(f"target must be >= 0, got {n}")
        with self._lock:
            if self._stopped:
                return self._target
            self._target = n
            excess = []
            while len(self._handles) > n:
                # newest-first: the longest-lived replicas hold the
                # warmest caches and the steadiest health history
                excess.append(self._handles.pop())
            shortfall = n - len(self._handles)
        for handle in excess:
            self._retire_async(handle)
        self._launch_many(shortfall)
        return n

    def reap(self) -> int:
        """Detect replicas that died WITHOUT being retired, drop them
        from the roster (deregistering the stale URL), and launch
        replacements up to the target. Returns how many replacements
        actually CAME UP — a death whose replacement failed to start
        must not count as healed (deaths themselves are visible as
        ``replica_died`` events either way)."""
        with self._lock:
            if self._stopped:
                return 0
            dead = [h for h in self._handles if not h.alive()]
            for h in dead:
                self._handles.remove(h)
            target = self._target
            live = len(self._handles)
        for handle in dead:
            url = handle.url
            self._deregister(url)
            self._event(
                "replica_died", name=handle.name, url=url,
                pid=handle.pid,
            )
        launched = self._launch_many(max(0, target - live))
        # launches covering a shortfall that existed WITHOUT a death
        # (an earlier launch that never bound) are convergence, not
        # repair — only death-attributable launches count as replaced
        replaced = min(launched, len(dead))
        if dead:
            with self._lock:
                self._replaced_total += replaced
            self._event(
                "replicas_replaced", died=len(dead), replaced=replaced,
            )
        return replaced

    def stop(self) -> None:
        """Retire every replica (waited on — process exit must not
        strand children; retirements run concurrently so shutdown
        costs one drain, not N) and refuse further work."""
        with self._lock:
            self._stopped = True
            handles, self._handles = self._handles, []
            self._target = 0
        if not handles:
            return
        if len(handles) == 1:
            self._retire_handle(handles[0])
            return
        threads = [
            threading.Thread(
                target=self._retire_handle,
                args=(handle,),
                name=f"keystone-retire-{handle.name}",
                daemon=True,
            )
            for handle in handles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()


__all__ = [
    "DRAIN_TIMEOUT_S",
    "STARTUP_TIMEOUT_S",
    "InprocLauncher",
    "InprocReplica",
    "SubprocessLauncher",
    "SubprocessReplica",
    "Supervisor",
    "deregister_replica",
]
