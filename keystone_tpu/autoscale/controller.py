"""The control loop: scrape the router, decide, converge the fleet.

``RouterScraper`` turns one tick's worth of router surfaces into a
``FleetObservation``:

- ``GET /metrics`` (federated) — the fleet p99 from the merged
  ``keystone_gateway_request_latency_seconds`` ``le`` buckets (the
  TRUE fleet quantile, PR 10), the offered request rate from the
  router's own ``keystone_router_requests_total`` deltas, and the
  summed replica load gauges;
- ``GET /slz`` — the fleet latency SLO's fast/slow burn rates;
- ``GET /fleetz`` — roster counts (healthy / half-open / unhealthy /
  unreachable) and readiness;
- ``GET /tracez`` + ``GET /debugz?trace_id=`` — PHASE EVIDENCE: a few
  recently-finished ``router.forward`` trace ids are sampled and
  stitched, and their ``phases_ms`` decompositions aggregated into
  per-phase shares. Stitching on the scrape path is deliberate —
  each stitched trace also lands on the
  ``keystone_request_phase_seconds{phase}`` histogram, so the signal
  the policy used is the signal an operator can scrape.

A scrape that fails entirely yields ``None`` (counted); partial
surfaces degrade to absent fields — the policy decides on what's
actually known, never on invented zeros.

``Autoscaler`` runs the tick on a daemon thread: reap dead replicas
(repair precedes policy — a kill -9'd replica is replaced regardless
of cooldowns), observe, decide, act through the supervisor. Every
decision is (1) a structured JSON event on the event sink, (2) an
``autoscale.decision`` span riding PR 11's tracer, and (3) exported
as ``keystone_autoscale_*`` series.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from keystone_tpu.autoscale.policy import (
    Decision,
    FleetObservation,
    PolicyEngine,
    phase_shares,
)
from keystone_tpu.autoscale.supervisor import Supervisor
from keystone_tpu.observability.prometheus import (
    parse_samples,
    quantile_from_buckets,
)
from keystone_tpu.observability.registry import get_global_registry
from keystone_tpu.observability.tracing import get_tracer

logger = logging.getLogger(__name__)

# the federated latency family the fleet p99 reads (fleet/router.py)
FLEET_LATENCY_FAMILY = "keystone_gateway_request_latency_seconds"

# replica load gauges summed into the fleet load observation
LOAD_FAMILIES = (
    "keystone_gateway_queue_depth",
    "keystone_gateway_inflight",
)

# stitched phase samples per tick: enough traces to smooth one odd
# request, few enough that the scrape stays cheap
PHASE_SAMPLES_PER_TICK = 4


class AutoscaleMetrics:
    """The ``keystone_autoscale_*`` export surface. Registered on the
    router process's registry so the federated ``/metrics`` carries
    the autoscaler's own series next to the fleet's."""

    def __init__(self, registry=None, autoscaler: str = "autoscaler"):
        reg = registry if registry is not None else get_global_registry()
        self.autoscaler = autoscaler
        self._decisions = reg.counter(
            "keystone_autoscale_decisions_total",
            "control-loop decisions by action (hold ticks included "
            "so the loop's liveness is scrape-visible)",
            ("autoscaler", "action"),
        )
        self._vetoes = reg.counter(
            "keystone_autoscale_vetoes_total",
            "scale decisions blocked, by veto reason (cooldowns, "
            "bounds, device_bound, replica_recovering)",
            ("autoscaler", "reason"),
        )
        self._replicas = reg.gauge(
            "keystone_autoscale_replicas",
            "replica count by kind: target (the policy's goal), "
            "running (live handles)",
            ("autoscaler", "kind"),
        )
        self._replaced = reg.counter(
            "keystone_autoscale_replicas_replaced_total",
            "dead replicas detected and replaced by the supervisor "
            "(repair, not scaling)",
            ("autoscaler",),
        )
        self._scrape_errors = reg.counter(
            "keystone_autoscale_scrape_errors_total",
            "control-loop ticks whose router scrape failed entirely",
            ("autoscaler",),
        )

    def record_decision(self, decision: Decision) -> None:
        self._decisions.inc((self.autoscaler, decision.action))
        if decision.action == "hold" and decision.reason in (
            "up_cooldown", "down_cooldown", "at_max_replicas",
            "at_min_replicas", "device_bound", "replica_recovering",
        ):
            self._vetoes.inc((self.autoscaler, decision.reason))

    def set_replicas(self, target: int, running: int) -> None:
        self._replicas.set(float(target), (self.autoscaler, "target"))
        self._replicas.set(float(running), (self.autoscaler, "running"))

    def record_replaced(self, n: int) -> None:
        self._replaced.inc((self.autoscaler,), by=float(n))

    def record_scrape_error(self) -> None:
        self._scrape_errors.inc((self.autoscaler,))

    def decision_count(self, action: str) -> float:
        return self._decisions.get((self.autoscaler, action))


def _scrape_stats(
    metrics_text: str,
) -> tuple:
    """ONE ``parse_samples`` pass over the federated body -> (latency
    buckets ``{le: count}`` collapsed across label sets, cumulative
    router request count, summed replica load). The exposition grows
    with the fleet and the loop ticks sub-second in drills — parsing
    it once per tick instead of per-question matters."""
    bucket_name = f"{FLEET_LATENCY_FAMILY}_bucket"
    buckets: Dict[float, float] = {}
    requests = load = None
    for name, labels, value in parse_samples(metrics_text):
        if name == bucket_name and "le" in labels:
            le = float(labels["le"])  # "+Inf" parses to math.inf
            buckets[le] = buckets.get(le, 0.0) + value
        elif name == "keystone_router_requests_total":
            requests = (requests or 0.0) + value
        elif name in LOAD_FAMILIES:
            load = (load or 0.0) + value
    return buckets, requests, load


def fleet_latency_buckets(metrics_text: str) -> Dict[float, float]:
    """The federated cumulative latency buckets of one ``/metrics``
    body, collapsed across label sets: ``{le: count}``. (The router's
    federation already dropped conflicting bucket layouts, so the
    per-``le`` sum is exact here.)"""
    return _scrape_stats(metrics_text)[0]


def windowed_p99(
    current: Dict[float, float], base: Optional[Dict[float, float]]
) -> Optional[float]:
    """The p99 of traffic BETWEEN two cumulative bucket snapshots —
    the delta of cumulative ``le`` counts is itself a histogram of
    exactly the window's requests, which is what a control loop must
    react to (the lifetime quantile never comes back down after one
    overload episode, so it could never say "scaled enough").

    Per-bucket deltas clamp at zero: a replica deregistering mid-run
    removes its counts from the federation, and a negative delta is
    membership churn, not traffic. None when the window saw no
    requests."""
    if not current:
        return None
    base = base or {}
    delta = [
        (le, max(0.0, count - base.get(le, 0.0)))
        for le, count in sorted(current.items())
    ]
    if not delta or delta[-1][1] <= 0:
        return None
    return quantile_from_buckets(0.99, delta)


def observation_from(
    metrics_text: Optional[str],
    slz_doc: Optional[Dict[str, Any]],
    fleetz_doc: Optional[Dict[str, Any]],
    phase_samples: List[Dict[str, float]],
    *,
    t: float,
    prev_requests: Optional[float] = None,
    prev_t: Optional[float] = None,
    prev_latency_buckets: Optional[Dict[float, float]] = None,
    slo_name_suffix: str = ":fleet_latency",
) -> FleetObservation:
    """Assemble one observation from the raw scraped surfaces — pure
    parsing, unit-testable on canned bodies. Absent surfaces leave
    their fields None/empty. The fleet p99 is WINDOWED against
    ``prev_latency_buckets`` when given (``windowed_p99``); without a
    baseline it is the lifetime quantile (first tick)."""
    obs = FleetObservation(t=t, phase_shares=phase_shares(phase_samples))
    if fleetz_doc:
        counts = fleetz_doc.get("counts") or {}
        obs.replicas_total = sum(counts.values())
        obs.replicas_half_open = counts.get("half-open", 0)
        obs.replicas_unhealthy = counts.get("unhealthy", 0)
        obs.replicas_unreachable = counts.get("unreachable", 0)
        obs.replicas_ready = sum(
            1
            for r in fleetz_doc.get("replicas", ())
            if r.get("ready") and r.get("healthy")
        )
    if metrics_text:
        buckets, requests, load = _scrape_stats(metrics_text)
        obs.metrics_ok = True
        obs.latency_buckets = buckets
        obs.fleet_p99_s = windowed_p99(buckets, prev_latency_buckets)
        obs.load_total = load
        obs.requests_total = requests
        if (
            requests is not None
            and prev_requests is not None
            and prev_t is not None
            and t > prev_t
        ):
            obs.offered_rps = max(
                0.0, (requests - prev_requests) / (t - prev_t)
            )
    if slz_doc:
        for slo in slz_doc.get("slos", ()):
            if str(slo.get("name", "")).endswith(slo_name_suffix):
                burns = slo.get("burn_rate") or {}
                obs.burn_fast = burns.get("fast")
                obs.burn_slow = burns.get("slow")
                break
    return obs


class RouterScraper:
    """One router's surfaces -> ``FleetObservation`` per tick (keeps
    the previous request-counter sample for the offered-rate delta
    and the set of already-stitched trace ids)."""

    def __init__(
        self,
        router_url: str,
        *,
        timeout_s: float = 10.0,
        phase_samples_per_tick: int = PHASE_SAMPLES_PER_TICK,
        p99_window_s: float = 15.0,
    ):
        self.router_url = router_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.phase_samples_per_tick = int(phase_samples_per_tick)
        # the windowed-p99 baseline: fleet_p99_s reflects the traffic
        # of roughly the last p99_window_s, not the process lifetime
        self.p99_window_s = float(p99_window_s)
        self._prev_requests: Optional[float] = None
        self._prev_t: Optional[float] = None
        # (t, cumulative bucket snapshot) history, oldest first
        self._bucket_history: List = []
        # roster membership of the last tick: a deregistered replica
        # REMOVES its counts from the federation, which would zero
        # every clamped delta and blind the windowed p99 for a whole
        # window — membership churn resets the baseline instead
        self._prev_roster: Optional[tuple] = None
        self._stitched: set = set()

    def _get(self, path: str) -> bytes:
        with urllib.request.urlopen(
            self.router_url + path, timeout=self.timeout_s
        ) as resp:
            return resp.read()

    def _get_json(self, path: str) -> Dict[str, Any]:
        return json.loads(self._get(path))

    def _sample_phases(self) -> List[Dict[str, float]]:
        """Recent ``router.forward`` trace ids off ``/tracez``, each
        stitched once via ``/debugz`` — the returned ``phases_ms``
        maps are the policy's phase evidence, and the stitch itself
        populates ``keystone_request_phase_seconds``."""
        try:
            spans = self._get_json("/tracez").get("spans", ())
        except Exception:
            return []
        tids: List[str] = []
        for span in reversed(list(spans)):  # newest last in the ring
            tid = span.get("trace_id")
            if (
                span.get("name") == "router.forward"
                and tid
                and tid not in self._stitched
                and tid not in tids
            ):
                tids.append(tid)
            if len(tids) >= self.phase_samples_per_tick:
                break
        samples = []
        for tid in tids:
            self._stitched.add(tid)
            try:
                doc = self._get_json(f"/debugz?trace_id={tid}")
            except Exception:
                continue
            phases = doc.get("phases_ms")
            if phases:
                samples.append(phases)
        # the stitched-id memory must not grow unbounded on a
        # long-lived autoscaler
        if len(self._stitched) > 4096:
            self._stitched = set(tids)
        return samples

    def observe(self) -> Optional[FleetObservation]:
        """One tick's observation, or None when even ``/fleetz`` was
        unreachable (the router itself is down — nothing to decide
        on)."""
        t = time.monotonic()
        try:
            fleetz = self._get_json("/fleetz")
        except Exception as e:
            logger.warning(
                "autoscale scrape: /fleetz unreachable: %s", e
            )
            return None
        roster = tuple(sorted(
            r.get("url", "") for r in fleetz.get("replicas", ())
        ))
        if roster != self._prev_roster:
            if self._prev_roster is not None:
                # membership changed: the old cumulative baselines no
                # longer describe the same federation — rebase rather
                # than reading churn as zero traffic
                self._bucket_history = []
            self._prev_roster = roster
        metrics_text = slz = None
        try:
            metrics_text = self._get("/metrics").decode("utf-8", "replace")
        except Exception:
            logger.debug("autoscale scrape: /metrics failed", exc_info=True)
        try:
            slz = self._get_json("/slz")
        except Exception:
            logger.debug("autoscale scrape: /slz failed", exc_info=True)
        obs = observation_from(
            metrics_text,
            slz,
            fleetz,
            self._sample_phases(),
            t=t,
            prev_requests=self._prev_requests,
            prev_t=self._prev_t,
            prev_latency_buckets=self._p99_baseline(t),
        )
        self._prev_requests = obs.requests_total
        self._prev_t = t
        if obs.latency_buckets:
            self._bucket_history.append((t, dict(obs.latency_buckets)))
            # keep one sample older than the window (the baseline)
            horizon = t - self.p99_window_s
            while (
                len(self._bucket_history) > 2
                and self._bucket_history[1][0] <= horizon
            ):
                self._bucket_history.pop(0)
        return obs

    def _p99_baseline(self, now: float) -> Optional[Dict[float, float]]:
        """The newest bucket snapshot at least ``p99_window_s`` old
        (oldest available when history is younger — a young loop
        windows against what it has)."""
        base = None
        for t, buckets in self._bucket_history:
            if t <= now - self.p99_window_s:
                base = buckets
            else:
                break
        if base is None and self._bucket_history:
            base = self._bucket_history[0][1]
        return base


class Autoscaler:
    """The loop: reap -> observe -> decide -> act, every
    ``interval_s`` on a daemon thread. ``tick()`` is also directly
    callable (tests and the bench drive it synchronously)."""

    def __init__(
        self,
        supervisor: Supervisor,
        scraper: RouterScraper,
        engine: PolicyEngine,
        *,
        interval_s: float = 5.0,
        registry=None,
        name: str = "autoscaler",
        on_event: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}"
            )
        self.supervisor = supervisor
        self.scraper = scraper
        self.engine = engine
        self.interval_s = float(interval_s)
        self.name = name
        self.metrics = AutoscaleMetrics(
            registry=registry, autoscaler=name
        )
        self._on_event = on_event
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decisions: List[Decision] = []  # newest last, bounded
        self.max_replicas_seen = 0

    def _emit(self, event: str, **fields: Any) -> None:
        doc = {"event": event, "autoscaler": self.name, **fields}
        logger.info("autoscale: %s", json.dumps(doc))
        if self._on_event is not None:
            try:
                self._on_event(doc)
            except Exception:
                logger.exception("autoscale event sink failed")

    def tick(self) -> Optional[Decision]:
        """One control iteration. Returns the decision (None when the
        router was unreachable)."""
        # repair FIRST, outside policy: a dead replica is replaced
        # regardless of streaks and cooldowns — holding the declared
        # target is the supervisor's job, changing it is the policy's
        replaced = self.supervisor.reap()
        if replaced:
            self.metrics.record_replaced(replaced)
            self._emit(
                "replicas_replaced", replaced=replaced,
                target=self.supervisor.target,
            )
        obs = self.scraper.observe()
        target = self.supervisor.target
        running = sum(
            1 for h in self.supervisor.replicas() if h.alive()
        )
        self.max_replicas_seen = max(self.max_replicas_seen, running)
        self.metrics.set_replicas(target, running)
        if obs is None:
            self.metrics.record_scrape_error()
            return None
        tracer = get_tracer()
        span = tracer.start_span(
            "autoscale.decision", autoscaler=self.name
        )
        decision = None
        try:
            decision = self.engine.decide(target, obs)
        finally:
            if decision is not None:
                span.set_attr("action", decision.action)
                span.set_attr("reason", decision.reason)
            tracer.end_span(span)
        self.metrics.record_decision(decision)
        self.decisions.append(decision)
        if len(self.decisions) > 512:
            del self.decisions[: len(self.decisions) - 512]
        if decision.action in ("scale_up", "scale_down"):
            span2 = tracer.start_span(
                f"autoscale.{decision.action}",
                autoscaler=self.name,
                reason=decision.reason,
                target=decision.target,
            )
            try:
                self.supervisor.scale_to(decision.target)
            finally:
                tracer.end_span(span2)
        self._emit(
            "autoscale_decision",
            action=decision.action,
            reason=decision.reason,
            target=decision.target,
            running=running,
            fleet_p99_ms=(
                round(obs.fleet_p99_s * 1e3, 3)
                if obs.fleet_p99_s is not None else None
            ),
            burn_fast=obs.burn_fast,
            offered_rps=(
                round(obs.offered_rps, 2)
                if obs.offered_rps is not None else None
            ),
            dominant_phase=obs.dominant_phase,
            replicas_half_open=obs.replicas_half_open,
        )
        return decision

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    logger.exception(
                        "autoscale %s: tick failed", self.name
                    )

        self._thread = threading.Thread(
            target=loop,
            name=f"keystone-{self.name}-loop",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


__all__ = [
    "Autoscaler",
    "AutoscaleMetrics",
    "FLEET_LATENCY_FAMILY",
    "LOAD_FAMILIES",
    "RouterScraper",
    "fleet_latency_buckets",
    "observation_from",
    "windowed_p99",
]
