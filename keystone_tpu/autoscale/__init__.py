"""Autonomous fleet elasticity: the loop nobody was closing.

PRs 8–11 built every primitive this package needs — the shared AOT
executable store makes a fresh replica ~5× cheaper to start, the
``--register``/``{"listening": ...}`` handshake makes one spawnable
and routable without port races, the router's federated ``/metrics``
+ ``/slz`` say how the FLEET is doing, and the per-request phase
decomposition says *where* latency goes. This package is the
controller over all of it:

- ``supervisor.py`` — replica processes as a managed set: spawn
  ``serve-gateway`` subprocesses (or in-process replicas for the
  bench/tests), retire through the graceful
  deregister → drain → exit protocol, replace the dead.
- ``policy.py`` — the pure decision engine: SLO burn + fleet p99 +
  per-replica load + phase attribution (scale out only when
  ``queue_wait`` dominates — ``device``-bound latency vetoes, more
  replicas wouldn't help), with hysteresis, per-direction cooldowns,
  min/max bounds, and a scale-down ban while any replica is
  half-open.
- ``controller.py`` — the tick: scrape, decide, converge; every
  decision a structured event + ``keystone_autoscale_*`` series +
  an ``autoscale.decision`` span.
- ``planner.py`` — ``serve-capacity-plan``: replay the recorded peak
  ×1..×N against 1..K replicas, fit replicas-vs-offered-load, derive
  the policy thresholds — measured, not guessed.
- ``cli.py`` — ``serve-autoscale``: router + supervisor + loop in
  one command.

CLI: ``python -m keystone_tpu serve-autoscale --slo-latency-ms 250``;
drill: ``bin/smoke-autoscale.sh``; regression row:
``serving_autoscale_ramp`` (``serve-bench --autoscale-only``).
"""

from keystone_tpu.autoscale.policy import (
    Decision,
    FleetObservation,
    PolicyConfig,
    PolicyEngine,
    phase_shares,
)
from keystone_tpu.autoscale.supervisor import (
    InprocLauncher,
    SubprocessLauncher,
    Supervisor,
    deregister_replica,
)

__all__ = [
    "Decision",
    "FleetObservation",
    "InprocLauncher",
    "PolicyConfig",
    "PolicyEngine",
    "SubprocessLauncher",
    "Supervisor",
    "deregister_replica",
    "phase_shares",
]
