"""Lazy PEP 562 package exports — ONE implementation shared by the
package ``__init__``s (keystone_tpu/, keystone_tpu/loaders/). Must stay
jax-free: the streaming loader's spawn decode workers import through
these ``__getattr__``s and must not pay the jax import.
"""

from __future__ import annotations

import importlib


def make_getattr(pkg_name: str, exports: dict):
    """Module-level ``__getattr__`` for ``pkg_name``: re-export names
    from ``exports`` {name: module}, fall back to importing
    ``pkg_name.name`` submodules on demand (the eager imports used to
    bind subpackages as side effects), and keep missing-DEPENDENCY
    errors loud (only a missing submodule itself becomes
    AttributeError)."""

    def __getattr__(name):
        if name in exports:
            return getattr(importlib.import_module(exports[name]), name)
        try:
            return importlib.import_module(f"{pkg_name}.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"{pkg_name}.{name}":
                raise AttributeError(
                    f"module {pkg_name!r} has no attribute {name!r}"
                ) from None
            raise  # a real missing dependency inside the submodule

    return __getattr__
