"""Immutable untyped dataflow-graph IR.

The pipeline DAG the optimizer rewrites and the executor interprets. Mirrors
the semantics of the reference's ``workflow/Graph.scala`` (KeystoneML,
/root/reference/src/main/scala/workflow/Graph.scala) — sources, sinks, nodes
with ordered dependencies, and functional surgery operations — re-expressed as
a frozen Python dataclass over immutable maps. Node payloads are opaque
``Operator`` objects (see operators.py).

Ids are small wrapper types (not raw ints) so that sources, nodes and sinks
can never be confused; a dependency is a ``NodeId | SourceId``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, Iterable, Mapping, Sequence, Set, Tuple, Union

if TYPE_CHECKING:  # pragma: no cover
    from keystone_tpu.workflow.operators import Operator


@dataclass(frozen=True, order=True)
class NodeId:
    id: int

    def __repr__(self) -> str:
        return f"node{self.id}"


@dataclass(frozen=True, order=True)
class SourceId:
    id: int

    def __repr__(self) -> str:
        return f"source{self.id}"


@dataclass(frozen=True, order=True)
class SinkId:
    id: int

    def __repr__(self) -> str:
        return f"sink{self.id}"


NodeOrSourceId = Union[NodeId, SourceId]
GraphId = Union[NodeId, SourceId, SinkId]


def _max_id(ids: Iterable[int]) -> int:
    m = -1
    for i in ids:
        if i > m:
            m = i
    return m


@dataclass(frozen=True)
class Graph:
    """An immutable DAG of operators.

    - ``sources``: dangling inputs (runtime data gets spliced in here)
    - ``sink_dependencies``: sink -> the node/source whose value it exposes
    - ``operators``: node -> Operator payload
    - ``dependencies``: node -> ordered inputs (nodes or sources)

    All surgery methods return a new Graph (and freshly allocated ids where
    applicable); the receiver is never mutated.
    """

    sources: frozenset  # frozenset[SourceId]
    sink_dependencies: Mapping[SinkId, NodeOrSourceId]
    operators: Mapping[NodeId, "Operator"]
    dependencies: Mapping[NodeId, Tuple[NodeOrSourceId, ...]]

    # -- accessors ---------------------------------------------------------

    @property
    def nodes(self) -> Set[NodeId]:
        return set(self.operators.keys())

    @property
    def sinks(self) -> Set[SinkId]:
        return set(self.sink_dependencies.keys())

    def get_operator(self, node: NodeId) -> "Operator":
        return self.operators[node]

    def get_dependencies(self, node: NodeId) -> Tuple[NodeOrSourceId, ...]:
        return self.dependencies[node]

    def get_sink_dependency(self, sink: SinkId) -> NodeOrSourceId:
        return self.sink_dependencies[sink]

    # -- id allocation -----------------------------------------------------

    def _next_node_id(self) -> NodeId:
        return NodeId(_max_id(n.id for n in self.operators) + 1)

    def _next_source_id(self) -> SourceId:
        return SourceId(_max_id(s.id for s in self.sources) + 1)

    def _next_sink_id(self) -> SinkId:
        return SinkId(_max_id(s.id for s in self.sink_dependencies) + 1)

    # -- surgery -----------------------------------------------------------

    def add_node(
        self, op: "Operator", deps: Sequence[NodeOrSourceId]
    ) -> Tuple["Graph", NodeId]:
        nid = self._next_node_id()
        ops = dict(self.operators)
        ops[nid] = op
        dps = dict(self.dependencies)
        dps[nid] = tuple(deps)
        return dataclasses.replace(self, operators=ops, dependencies=dps), nid

    def add_source(self) -> Tuple["Graph", SourceId]:
        sid = self._next_source_id()
        return dataclasses.replace(self, sources=self.sources | {sid}), sid

    def add_sink(self, dep: NodeOrSourceId) -> Tuple["Graph", SinkId]:
        kid = self._next_sink_id()
        sd = dict(self.sink_dependencies)
        sd[kid] = dep
        return dataclasses.replace(self, sink_dependencies=sd), kid

    def set_dependencies(
        self, node: NodeId, deps: Sequence[NodeOrSourceId]
    ) -> "Graph":
        if node not in self.dependencies:
            raise KeyError(f"{node} not in graph")
        dps = dict(self.dependencies)
        dps[node] = tuple(deps)
        return dataclasses.replace(self, dependencies=dps)

    def set_operator(self, node: NodeId, op: "Operator") -> "Graph":
        if node not in self.operators:
            raise KeyError(f"{node} not in graph")
        ops = dict(self.operators)
        ops[node] = op
        return dataclasses.replace(self, operators=ops)

    def set_sink_dependency(self, sink: SinkId, dep: NodeOrSourceId) -> "Graph":
        if sink not in self.sink_dependencies:
            raise KeyError(f"{sink} not in graph")
        sd = dict(self.sink_dependencies)
        sd[sink] = dep
        return dataclasses.replace(self, sink_dependencies=sd)

    def remove_sink(self, sink: SinkId) -> "Graph":
        sd = dict(self.sink_dependencies)
        del sd[sink]
        return dataclasses.replace(self, sink_dependencies=sd)

    def remove_source(self, source: SourceId) -> "Graph":
        """Remove a source. Fails if anything still depends on it."""
        self._check_unreferenced(source)
        return dataclasses.replace(self, sources=self.sources - {source})

    def remove_node(self, node: NodeId) -> "Graph":
        """Remove a node. Fails if anything still depends on it."""
        self._check_unreferenced(node)
        ops = dict(self.operators)
        del ops[node]
        dps = dict(self.dependencies)
        del dps[node]
        return dataclasses.replace(self, operators=ops, dependencies=dps)

    def _check_unreferenced(self, target: NodeOrSourceId) -> None:
        for n, deps in self.dependencies.items():
            if target in deps:
                raise ValueError(f"{target} still referenced by {n}")
        for k, dep in self.sink_dependencies.items():
            if dep == target:
                raise ValueError(f"{target} still referenced by {k}")

    def replace_dependency(
        self, old: NodeOrSourceId, new: NodeOrSourceId
    ) -> "Graph":
        """Rewrite every dependency (node & sink) on ``old`` to ``new``."""
        dps = {
            n: tuple(new if d == old else d for d in deps)
            for n, deps in self.dependencies.items()
        }
        sd = {
            k: (new if d == old else d)
            for k, d in self.sink_dependencies.items()
        }
        return dataclasses.replace(self, dependencies=dps, sink_dependencies=sd)

    # -- whole-graph composition ------------------------------------------

    def add_graph(
        self, other: "Graph"
    ) -> Tuple["Graph", Dict[SourceId, SourceId], Dict[SinkId, SinkId]]:
        """Disjoint union: import ``other`` with freshly re-numbered ids.

        Returns (new graph, other-source -> new-source map,
        other-sink -> new-sink map).
        """
        node_base = _max_id(n.id for n in self.operators) + 1
        source_base = _max_id(s.id for s in self.sources) + 1
        sink_base = _max_id(s.id for s in self.sink_dependencies) + 1

        node_map = {
            n: NodeId(node_base + i)
            for i, n in enumerate(sorted(other.operators.keys()))
        }
        source_map = {
            s: SourceId(source_base + i)
            for i, s in enumerate(sorted(other.sources))
        }
        sink_map = {
            s: SinkId(sink_base + i)
            for i, s in enumerate(sorted(other.sink_dependencies.keys()))
        }

        def remap(d: NodeOrSourceId) -> NodeOrSourceId:
            return node_map[d] if isinstance(d, NodeId) else source_map[d]

        ops = dict(self.operators)
        dps = dict(self.dependencies)
        for n, op in other.operators.items():
            ops[node_map[n]] = op
            dps[node_map[n]] = tuple(remap(d) for d in other.dependencies[n])
        sd = dict(self.sink_dependencies)
        for k, d in other.sink_dependencies.items():
            sd[sink_map[k]] = remap(d)

        g = Graph(
            sources=self.sources | frozenset(source_map.values()),
            sink_dependencies=sd,
            operators=ops,
            dependencies=dps,
        )
        return g, source_map, sink_map

    def connect_graph(
        self, other: "Graph", splice: Mapping[SourceId, SinkId]
    ) -> Tuple["Graph", Dict[SourceId, SourceId], Dict[SinkId, SinkId]]:
        """Import ``other`` and splice: for (src -> snk) in ``splice``,
        other's source ``src`` is replaced by whatever this graph's sink
        ``snk`` points at; both the source and the sink are removed.

        Returns (graph, source map for other's *unspliced* sources, sink map
        for other's sinks).
        """
        g, source_map, sink_map = self.add_graph(other)
        for other_src, self_snk in splice.items():
            new_src = source_map[other_src]
            target = self.sink_dependencies[self_snk]
            g = g.replace_dependency(new_src, target)
            g = g.remove_source(new_src)
            g = g.remove_sink(self_snk)
            del source_map[other_src]
        return g, source_map, sink_map

    def replace_nodes(
        self,
        nodes_to_remove: Set[NodeId],
        replacement: "Graph",
        replacement_source_splice: Mapping[SourceId, NodeOrSourceId],
        replacement_sink_splice: Mapping[NodeId, SinkId],
    ) -> "Graph":
        """Swap a node subset for a replacement subgraph.

        ``replacement_source_splice``: replacement source -> existing
        node/source feeding it. ``replacement_sink_splice``: removed node ->
        replacement sink standing in for it (all outside edges onto the
        removed node are rerouted to the sink's dependency).
        """
        g, source_map, sink_map = self.add_graph(replacement)
        # Reroute edges onto removed nodes to the replacement sinks' targets.
        for removed, rsink in replacement_sink_splice.items():
            new_sink = sink_map[rsink]
            target = g.sink_dependencies[new_sink]
            g = g.replace_dependency(removed, target)
        # Splice replacement sources onto existing feeders.
        for rsource, feeder in replacement_source_splice.items():
            new_src = source_map[rsource]
            g = g.replace_dependency(new_src, feeder)
            g = g.remove_source(new_src)
        # Drop the imported replacement sinks.
        for rsink in replacement_sink_splice.values():
            g = g.remove_sink(sink_map[rsink])
        # Remove the dead nodes (dependents first is unnecessary: all
        # references were rerouted above).
        for n in nodes_to_remove:
            g = g.remove_node(n)
        return g

    # -- introspection -----------------------------------------------------

    def to_dot(self, name: str = "pipeline") -> str:
        """Graphviz export (reference: Graph.toDOTString)."""
        lines = [f"digraph {name} {{", "  rankdir=LR;"]
        for s in sorted(self.sources):
            lines.append(f'  "{s!r}" [shape=oval, style=dashed];')
        for n in sorted(self.operators):
            label = getattr(self.operators[n], "label", None) or type(
                self.operators[n]
            ).__name__
            lines.append(f'  "{n!r}" [shape=box, label="{label}"];')
        for k in sorted(self.sink_dependencies):
            lines.append(f'  "{k!r}" [shape=oval, style=bold];')
        for n, deps in sorted(self.dependencies.items()):
            for i, d in enumerate(deps):
                lines.append(f'  "{d!r}" -> "{n!r}" [label="{i}"];')
        for k, d in sorted(self.sink_dependencies.items()):
            lines.append(f'  "{d!r}" -> "{k!r}";')
        lines.append("}")
        return "\n".join(lines)


EMPTY_GRAPH = Graph(
    sources=frozenset(), sink_dependencies={}, operators={}, dependencies={}
)


# -- analyses (reference: workflow/AnalysisUtils.scala) ---------------------


def get_parents(graph: Graph, gid: GraphId) -> Set[NodeOrSourceId]:
    if isinstance(gid, SinkId):
        return {graph.sink_dependencies[gid]}
    if isinstance(gid, SourceId):
        return set()
    return set(graph.dependencies[gid])


def get_ancestors(graph: Graph, gid: GraphId) -> Set[NodeOrSourceId]:
    seen: Set[NodeOrSourceId] = set()
    stack = list(get_parents(graph, gid))
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(get_parents(graph, cur))
    return seen


def get_children(graph: Graph, gid: NodeOrSourceId) -> Set[GraphId]:
    out: Set[GraphId] = set()
    for n, deps in graph.dependencies.items():
        if gid in deps:
            out.add(n)
    for k, d in graph.sink_dependencies.items():
        if d == gid:
            out.add(k)
    return out


def get_descendants(graph: Graph, gid: NodeOrSourceId) -> Set[GraphId]:
    seen: Set[GraphId] = set()
    stack = list(get_children(graph, gid))
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        if not isinstance(cur, SinkId):
            stack.extend(get_children(graph, cur))
    return seen


def linearize(graph: Graph) -> Tuple[GraphId, ...]:
    """Deterministic topological order over sources, nodes, then sinks.

    Depth-first from each sink in sorted order (reference:
    AnalysisUtils.linearize) so equal graphs linearize identically.
    """
    order: list = []
    seen: Set[GraphId] = set()

    def visit(gid: GraphId) -> None:
        if gid in seen:
            return
        seen.add(gid)
        for p in sorted(get_parents(graph, gid), key=_id_sort_key):
            visit(p)
        order.append(gid)

    for k in sorted(graph.sink_dependencies.keys()):
        visit(k)
    return tuple(order)


def _id_sort_key(gid: GraphId) -> Tuple[int, int]:
    kind = 0 if isinstance(gid, SourceId) else (1 if isinstance(gid, NodeId) else 2)
    return (kind, gid.id)
