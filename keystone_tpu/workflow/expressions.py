"""Lazy memoized value wrappers passed between operators at execution time.

Reference semantics: workflow/Expression.scala (DatasetExpression /
DatumExpression / TransformerExpression) — call-by-name thunks whose value is
computed at most once.
"""

from __future__ import annotations

from typing import Any, Callable


class Expression:
    """A lazily computed, memoized value."""

    _UNSET = object()

    def __init__(self, thunk: Callable[[], Any]):
        self._thunk = thunk
        self._value: Any = Expression._UNSET

    def get(self) -> Any:
        if self._value is Expression._UNSET:
            self._value = self._thunk()
            self._thunk = None  # free captured state
        return self._value

    @property
    def is_computed(self) -> bool:
        return self._value is not Expression._UNSET

    @classmethod
    def of(cls, value: Any) -> "Expression":
        e = cls(lambda: value)
        e.get()
        return e


class DatasetExpression(Expression):
    """Wraps a (lazy) Dataset — the N-example collection type."""


class DatumExpression(Expression):
    """Wraps a (lazy) single datum."""


class TransformerExpression(Expression):
    """Wraps a (lazy) fit TransformerOperator."""
