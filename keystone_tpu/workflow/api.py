"""Typed user-facing pipeline API.

Reference semantics: workflow/{Transformer,Estimator,LabelEstimator,Chainable,
Pipeline,PipelineResult,PipelineDataset,PipelineDatum,FittedPipeline}.scala and
GatherTransformerOperator.scala, re-designed for JAX:

- ``Transformer.apply(x)`` is a pure function on arrays; the batch path
  defaults to ``vmap`` over the dataset's example axis when data is in array
  mode (one XLA program over the sharded batch) and a host map otherwise.
- ``Pipeline.fit()`` executes estimator fits (memoized by structural prefix
  across pipelines — the "do not fit estimators multiple times" guarantee)
  and returns a serializable ``FittedPipeline`` whose steady-state apply path
  can be staged into a single jit-compiled function (``FittedPipeline.jit``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.executor import GraphExecutor, PipelineEnv
from keystone_tpu.workflow.expressions import (
    DatasetExpression,
    DatumExpression,
)
from keystone_tpu.workflow.graph import (
    EMPTY_GRAPH,
    Graph,
    NodeId,
    SinkId,
    SourceId,
    linearize,
)
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    Operator,
    TransformerOperator,
)
from keystone_tpu.workflow.rules import UnusedBranchRemovalRule


def _array_digest(a: np.ndarray) -> Any:
    """Fixed-size fingerprint of an array's contents. CSE/prefix keys hold
    this digest, never the raw bytes, so key size (and key comparison cost)
    doesn't scale with parameter bytes."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    return ("arr", a.shape, str(a.dtype), h.hexdigest())


def _hashable(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return _array_digest(v)
    if isinstance(v, jax.Array):
        return _array_digest(np.asarray(v))
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    try:
        hash(v)
        return v
    except TypeError:
        return id(v)


def _cached_hashable(self, v: Any) -> Any:
    """_hashable with the expensive array-digest step memoized per
    (instance, array identity) — but ONLY for immutable arrays
    (jax.Array, or np.ndarray with writeable=False): identity is a sound
    cache key only when the bytes can't change underneath it. Mutable
    np.ndarrays and cheap scalar fields are digested fresh each call, so
    in-place mutation still produces a fresh key."""
    immutable = isinstance(v, jax.Array) or (
        isinstance(v, np.ndarray) and not v.flags.writeable
    )
    if immutable:
        cache = self.__dict__.setdefault("_arr_digest_cache", {})
        hit = cache.get(id(v))
        if hit is None:
            hit = _hashable(v)
            cache[id(v)] = hit
            # hold a reference so id() can't be recycled
            cache[(id(v), "ref")] = v
        return hit
    if isinstance(v, (np.ndarray, jax.Array)):
        return _hashable(v)
    if isinstance(v, (list, tuple)):
        return tuple(_cached_hashable(self, x) for x in v)
    if isinstance(v, dict):
        return tuple(
            sorted((k, _cached_hashable(self, x)) for k, x in v.items())
        )
    return _hashable(v)


def _dataclass_eq_key(self) -> Any:
    """Structural key for dataclass operators. The device->host transfer +
    serialization of array fields happens at most once per distinct array
    per operator no matter how often the optimizer recomputes prefixes/CSE
    signatures (the reference relies on case-class equality, Scala-side
    cheap; EquivalentNodeMergeRule.scala:13-15)."""
    if not dataclasses.is_dataclass(self):
        return id(self)
    return (
        type(self),
        tuple(
            (f.name, _cached_hashable(self, getattr(self, f.name)))
            for f in dataclasses.fields(self)
        ),
    )


class Chainable:
    """Anything composable into a pipeline via ``and_then``."""

    def to_pipeline(self) -> "Pipeline":
        raise NotImplementedError

    def and_then(
        self,
        nxt: Union["Chainable", "Estimator", "LabelEstimator"],
        data: Any = None,
        labels: Any = None,
    ) -> "Pipeline":
        pipe = self.to_pipeline()
        if isinstance(nxt, LabelEstimator):
            if data is None or labels is None:
                raise TypeError("LabelEstimator chaining needs data and labels")
            return pipe._concat(nxt.with_data(pipe(data), labels))
        if isinstance(nxt, Estimator):
            if data is None:
                raise TypeError("Estimator chaining needs data")
            return pipe._concat(nxt.with_data(pipe(data)))
        return pipe._concat(nxt.to_pipeline())

    def __call__(self, data: Any) -> "PipelineResult":
        return self.to_pipeline().apply(data)

    def apply(self, data: Any) -> "PipelineResult":
        return self.to_pipeline().apply(data)


class Pipeline(Chainable):
    """A (GraphExecutor, source, sink) triple — one dangling input, one
    output. Applying data splices it in place of the source; execution stays
    lazy until ``PipelineResult.get()``."""

    def __init__(self, executor: GraphExecutor, source: SourceId, sink: SinkId):
        self.executor = executor
        self.source = source
        self.sink = sink

    # -- construction ------------------------------------------------------

    @property
    def _graph(self) -> Graph:
        return self.executor.raw_graph

    def to_pipeline(self) -> "Pipeline":
        return self

    def _concat(self, nxt: "Pipeline") -> "Pipeline":
        g, _, sink_map = self._graph.connect_graph(
            nxt._graph, {nxt.source: self.sink}
        )
        return Pipeline(GraphExecutor(g), self.source, sink_map[nxt.sink])

    # -- application -------------------------------------------------------

    def apply(self, data: Any) -> "PipelineResult":
        if isinstance(data, PipelineDataset):
            g, _, sink_map = data._graph.connect_graph(
                self._graph, {self.source: data._sink}
            )
            return PipelineDataset(GraphExecutor(g), sink_map[self.sink])
        if isinstance(data, PipelineDatum):
            g, _, sink_map = data._graph.connect_graph(
                self._graph, {self.source: data._sink}
            )
            return PipelineDatum(GraphExecutor(g), sink_map[self.sink])
        if isinstance(data, Dataset) or isinstance(data, (list,)) or (
            hasattr(data, "ndim") and data.ndim >= 2
        ):
            return self.apply(PipelineDataset.of(Dataset.of(data)))
        return self.apply_datum(data)

    def apply_datum(self, datum: Any) -> "PipelineDatum":
        g, nid = self._graph.add_node(DatumOperator(datum), ())
        g = g.replace_dependency(self.source, nid)
        g = g.remove_source(self.source)
        return PipelineDatum(GraphExecutor(g), self.sink)

    # -- training ----------------------------------------------------------

    def fit(self) -> "FittedPipeline":
        """Execute every estimator fit (prefix-memoized), swap delegating
        nodes for the fit transformers, prune, freeze."""
        executor = self.executor
        g = executor.graph  # optimized
        for n in sorted(g.operators.keys()):
            if isinstance(g.operators[n], DelegatingOperator):
                deps = g.dependencies[n]
                est_dep = deps[0]
                fit_transformer = executor.execute(est_dep).get()
                if not isinstance(fit_transformer, TransformerOperator):
                    raise TypeError(
                        f"estimator fit returned {type(fit_transformer)}"
                    )
                g = g.set_operator(n, fit_transformer)
                g = g.set_dependencies(n, deps[1:])
        # keep only the apply path from source to sink
        g_pruned, _ = UnusedBranchRemovalRule().apply(
            Graph(
                sources=g.sources,
                sink_dependencies={self.sink: g.sink_dependencies[self.sink]},
                operators=g.operators,
                dependencies=g.dependencies,
            ),
            {},
        )
        for n, op in g_pruned.operators.items():
            if not isinstance(op, TransformerOperator):
                raise TypeError(
                    f"fit pipeline contains non-transformer node {n}: {op!r}"
                )
        return FittedPipeline(g_pruned, self.source, self.sink)

    # -- combinators -------------------------------------------------------

    @staticmethod
    def gather(branches: Sequence[Chainable]) -> "Pipeline":
        """Merge N single-input branches onto one shared source; output per
        example is the tuple of branch outputs (reference: Pipeline.gather +
        GatherTransformerOperator)."""
        g, src = EMPTY_GRAPH.add_source()
        ends: List = []
        for branch in branches:
            bp = branch.to_pipeline()
            g, smap, kmap = g.add_graph(bp._graph)
            g = g.replace_dependency(smap[bp.source], src)
            g = g.remove_source(smap[bp.source])
            end = g.sink_dependencies[kmap[bp.sink]]
            g = g.remove_sink(kmap[bp.sink])
            ends.append(end)
        g, gather_node = g.add_node(GatherTransformerOperator(), ends)
        g, sink = g.add_sink(gather_node)
        return Pipeline(GraphExecutor(g), src, sink)

    def to_dot(self) -> str:
        return self._graph.to_dot()


class PipelineResult:
    """Lazily executed sink value."""

    def __init__(self, executor: GraphExecutor, sink: SinkId):
        self._executor = executor
        self._sink = sink
        self._result: Any = None
        self._done = False

    @property
    def _graph(self) -> Graph:
        return self._executor.raw_graph

    def get(self) -> Any:
        if not self._done:
            self._result = self._executor.execute(self._sink).get()
            self._done = True
        return self._result


class PipelineDataset(PipelineResult):
    def get(self) -> Dataset:
        return super().get()

    @staticmethod
    def of(dataset: Dataset) -> "PipelineDataset":
        g, nid = EMPTY_GRAPH.add_node(DatasetOperator(dataset), ())
        g, sink = g.add_sink(nid)
        return PipelineDataset(GraphExecutor(g), sink)


class PipelineDatum(PipelineResult):
    @staticmethod
    def of(datum: Any) -> "PipelineDatum":
        g, nid = EMPTY_GRAPH.add_node(DatumOperator(datum), ())
        g, sink = g.add_sink(nid)
        return PipelineDatum(GraphExecutor(g), sink)


class Transformer(Chainable, TransformerOperator):
    """A pure per-example function, liftable to a one-node pipeline.

    Subclasses override ``apply(x)``; override ``apply_batch(ds)`` for a
    hand-batched path (most array ops should — one matmul beats vmap of
    per-row ops only when XLA can't fuse, but explicit batch code also skips
    per-item host dispatch for items-mode data). ``vmap_batch=False`` forces
    host-side per-item mapping (non-traceable transformers).
    """

    vmap_batch: bool = True
    # shape-bucketed vmap for ragged items-mode data: group items by
    # shape, one jit(vmap) dispatch per group. Per-image host mapping of
    # featurizers costs ~100 ms/image through a remote dispatch link;
    # bucketing runs the same code ~35x faster (measured: dense SIFT at
    # 256x256 — 9.3 imgs/s host-mapped vs 335 imgs/s bucketed).
    bucket_vmap: bool = False

    def apply(self, x: Any) -> Any:  # single datum
        raise NotImplementedError

    def _jitted_vmap(self):
        fn = self.__dict__.get("_vmapped_apply")
        if fn is None:
            fn = jax.jit(jax.vmap(self.apply))
            self.__dict__["_vmapped_apply"] = fn
        return fn

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array and (self.vmap_batch or self.bucket_vmap):
            return Dataset.from_array(
                self._jitted_vmap()(ds.padded()), n=ds.n
            )
        if self.bucket_vmap:
            return self._bucketed_batch(ds)
        return ds.map(self.apply)

    def _bucketed_batch(self, ds: Dataset) -> Dataset:
        items = ds.items()
        by_shape: Dict[tuple, List[int]] = {}
        arrays = []
        for i, x in enumerate(items):
            a = jnp.asarray(x)
            arrays.append(a)
            by_shape.setdefault((a.shape, str(a.dtype)), []).append(i)
        out: List[Any] = [None] * len(items)
        fn = self._jitted_vmap()
        for idxs in by_shape.values():
            res = fn(jnp.stack([arrays[i] for i in idxs]))
            for j, i in enumerate(idxs):
                out[i] = jax.tree_util.tree_map(lambda a, j=j: a[j], res)
        return Dataset.from_items(out)

    # TransformerOperator ABI
    def single_transform(self, inputs: Sequence[Any]) -> Any:
        return self.apply(inputs[0])

    def batch_transform(self, inputs: Sequence[Dataset]) -> Dataset:
        return self.apply_batch(inputs[0])

    def to_pipeline(self) -> Pipeline:
        g, src = EMPTY_GRAPH.add_source()
        g, nid = g.add_node(self, (src,))
        g, sink = g.add_sink(nid)
        return Pipeline(GraphExecutor(g), src, sink)

    def __call__(self, data: Any) -> Any:
        return self.to_pipeline().apply(data)

    def eq_key(self) -> Any:
        return _dataclass_eq_key(self)

    @property
    def label(self) -> str:  # type: ignore[override]
        return type(self).__name__


def transformer(fn: Callable[[Any], Any], name: str = None) -> Transformer:
    """Factory: lift a plain function into a Transformer
    (reference: Transformer.apply(f))."""

    class _FnTransformer(Transformer):
        def apply(self, x):
            return fn(x)

        def eq_key(self):
            return ("fn", fn)

    t = _FnTransformer()
    t.__class__.__name__ = name or getattr(fn, "__name__", "fn")
    return t


class Estimator(Chainable, EstimatorOperator):
    """fit(Dataset) -> Transformer; splice-able into a pipeline."""

    def fit(self, data: Dataset) -> Transformer:
        raise NotImplementedError

    def fit_datasets(self, datasets: Sequence[Dataset]) -> TransformerOperator:
        return self.fit(datasets[0])

    def with_data(self, data: Any) -> Pipeline:
        g, data_end = _splice_data(EMPTY_GRAPH, data)
        g, est_node = g.add_node(self, (data_end,))
        g, src = g.add_source()
        g, delegate = g.add_node(DelegatingOperator(), (est_node, src))
        g, sink = g.add_sink(delegate)
        return Pipeline(GraphExecutor(g), src, sink)

    def to_pipeline(self) -> Pipeline:
        raise TypeError(
            "an Estimator is not directly chainable; use and_then(est, data)"
        )

    def eq_key(self) -> Any:
        return _dataclass_eq_key(self)

    @property
    def label(self) -> str:  # type: ignore[override]
        return type(self).__name__


class LabelEstimator(Estimator):
    """fit(Dataset, labels: Dataset) -> Transformer."""

    def fit(self, data: Dataset, labels: Dataset) -> Transformer:  # type: ignore[override]
        raise NotImplementedError

    def fit_datasets(self, datasets: Sequence[Dataset]) -> TransformerOperator:
        return self.fit(datasets[0], datasets[1])

    def with_data(self, data: Any, labels: Any = None) -> Pipeline:
        if labels is None:
            raise TypeError("LabelEstimator.with_data needs labels")
        g, data_end = _splice_data(EMPTY_GRAPH, data)
        g, labels_end = _splice_data(g, labels)
        g, est_node = g.add_node(self, (data_end, labels_end))
        g, src = g.add_source()
        g, delegate = g.add_node(DelegatingOperator(), (est_node, src))
        g, sink = g.add_sink(delegate)
        return Pipeline(GraphExecutor(g), src, sink)


def _splice_data(g: Graph, data: Any):
    """Attach a data producer to ``g``: a constant dataset node, or the whole
    upstream graph of a PipelineDataset (so shared prefixes stay shared)."""
    if isinstance(data, PipelineResult):
        if data._graph.sources:
            raise ValueError("cannot splice a pipeline with dangling sources")
        g2, _, kmap = g.add_graph(data._graph)
        end = g2.sink_dependencies[kmap[data._sink]]
        g2 = g2.remove_sink(kmap[data._sink])
        return g2, end
    ds = Dataset.of(data)
    return g.add_node(DatasetOperator(ds), ())


class FunctionNode:
    """Eagerly-applied pipeline-construction-time function (reference:
    pipelines/FunctionNode.scala) — not a DAG node."""

    def __call__(self, data: Any) -> Any:
        return self.apply(data)

    def apply(self, data: Any) -> Any:
        raise NotImplementedError


class GatherTransformerOperator(TransformerOperator):
    """Zips N branch outputs into a per-example tuple."""

    label = "gather"

    def single_transform(self, inputs: Sequence[Any]) -> Any:
        return tuple(inputs)

    def batch_transform(self, inputs: Sequence[Dataset]) -> Dataset:
        n = inputs[0].n
        if any(ds.n != n for ds in inputs):
            raise ValueError("gather branches disagree on dataset length")
        if all(ds.is_array for ds in inputs):
            pn = max(ds.padded_n for ds in inputs)
            arrs = tuple(ds._pad_to(pn).padded() for ds in inputs)
            return Dataset.from_array(arrs, n=n)
        cols = [ds.items() for ds in inputs]
        return Dataset.from_items([tuple(row) for row in zip(*cols)])

    def eq_key(self) -> Any:
        return ("gather",)


class Identity(Transformer):
    def apply(self, x):
        return x

    def apply_batch(self, ds: Dataset) -> Dataset:
        return ds

    def eq_key(self):
        return ("identity",)


class FittedPipeline:
    """A train-free, serializable transformer-only pipeline.

    ``apply`` interprets the graph node-by-node (cheap — the work is inside
    batched XLA ops); ``jit()`` stages the whole single-example path into one
    compiled XLA program for steady-state serving.
    """

    def __init__(self, graph: Graph, source: SourceId, sink: SinkId):
        self.graph = graph
        self.source = source
        self.sink = sink
        self._topo = [
            gid for gid in linearize(graph) if isinstance(gid, NodeId)
        ]

    def _run(self, feed: Any, batch: bool) -> Any:
        values: Dict[Any, Any] = {self.source: feed}
        for n in self._topo:
            op = self.graph.operators[n]
            ins = [values[d] for d in self.graph.dependencies[n]]
            if batch:
                values[n] = op.batch_transform(ins)
            else:
                values[n] = op.single_transform(ins)
        return values[self.graph.sink_dependencies[self.sink]]

    def apply(self, data: Any) -> Any:
        if isinstance(data, PipelineResult):
            data = data.get()
        if isinstance(data, Dataset):
            return self._run(data, batch=True)
        return self._run(data, batch=False)

    __call__ = apply

    def jit(self) -> Callable[[Any], Any]:
        """The single-example apply path as one jitted function."""
        return jax.jit(lambda x: self._run(x, batch=False))

    def _batch_run(self, arr: Any) -> Any:
        """The traceable whole-batch apply path: array(s) in, array(s)
        out. Shared staging surface of ``jit_batch`` and the serving
        engine (serving/engine.py), so the two can't drift. Rows past
        the valid count are zeros by the Dataset pad discipline; callers
        slice outputs back to their valid rows."""
        out = self._run(Dataset.from_array(arr), batch=True)
        return out.padded() if isinstance(out, Dataset) else out

    def jit_batch(self, donate: bool = False) -> Callable[[Any], Any]:
        """The WHOLE batched apply path as ONE compiled XLA program —
        the SURVEY §7 lowering: array in, array out, every node's
        batch_transform traced into a single staged computation (XLA
        fuses across node boundaries; no per-node dispatch). Requires an
        array-mode transformer chain (host-side items-mode nodes, e.g.
        string tokenizers, cannot trace — use ``apply`` for those).

        NOTE: one program per distinct batch shape — every new batch
        size recompiles. For serving arbitrary request sizes use
        ``compiled()`` (bucketed execution, bounded compiles).

        ``donate=True`` donates the input buffer to XLA (halves peak
        HBM for the staged batch; the caller's array is consumed)."""
        return jax.jit(
            self._batch_run, donate_argnums=(0,) if donate else ()
        )

    def compiled(self, buckets=None, **kwargs):
        """This pipeline as a serving engine: bucketed compiled
        execution with bounded recompiles, input donation, and optional
        mesh sharding (see serving/engine.py ``CompiledPipeline``)."""
        from keystone_tpu.serving.engine import (
            DEFAULT_BUCKETS, CompiledPipeline,
        )

        return CompiledPipeline(
            self, buckets if buckets is not None else DEFAULT_BUCKETS,
            **kwargs,
        )

    def and_then(self, nxt: "FittedPipeline") -> "FittedPipeline":
        g, _, sink_map = self.graph.connect_graph(
            nxt.graph, {nxt.source: self.sink}
        )
        return FittedPipeline(g, self.source, sink_map[nxt.sink])

    # -- persistence (reference: FittedPipeline is Serializable) ----------

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(path: str) -> "FittedPipeline":
        with open(path, "rb") as f:
            return pickle.load(f)
