"""Profile-driven automatic cache placement.

Reference: workflow/AutoCacheRule.scala:12-664 — profile nodes by executing
the graph on sample scales (partitionScales=Seq(2,4), numTrials=1) timing
wall-clock and measuring RDD/driver memory, fit per-node linear models of
time/memory vs scale (generalizeProfiles solves X \\ y), estimate the total
runtime implied by a candidate cache set via per-node run counts weighted
by WeightedNode.weight (number of passes an op makes over its input), then
either AggressiveCache (cache anything used more than once, :503) or
GreedyCache under a memory budget = 75% of cluster-remaining
(greedyCache:559-602, selectNext:542); finally insert Cacher() nodes
(addCachesToPipeline:492).

TPU translation: "RDD memory" is device-buffer bytes (jax arrays report
nbytes), "driver memory" is host-object size, and the default budget is a
fraction of the accelerator's per-device memory.
"""

from __future__ import annotations

import dataclasses
import logging
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.expressions import (
    DatasetExpression,
    Expression,
)
from keystone_tpu.workflow.graph import (
    Graph,
    NodeId,
    SinkId,
    SourceId,
    get_children,
    linearize,
)
from keystone_tpu.workflow.operators import DatasetOperator, Operator
from keystone_tpu.workflow.rules import PrefixMap, Rule

logger = logging.getLogger(__name__)

DEFAULT_SAMPLE_SCALES = (2, 4)  # reference: partitionScales = Seq(2, 4)
DEFAULT_BUDGET_FRACTION = 0.75  # reference: 75% of remaining memory


@dataclasses.dataclass
class Profile:
    """Per-node cost estimate (reference: AutoCacheRule.scala:18
    Profile(ns, rddMem, driverMem))."""

    ns: float  # estimated execution time, nanoseconds
    device_mem: float  # bytes of device-resident output
    host_mem: float  # bytes of host-resident output

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(
            self.ns + other.ns,
            self.device_mem + other.device_mem,
            self.host_mem + other.host_mem,
        )


def _measure_size(value) -> Tuple[float, float]:
    """(device_bytes, host_bytes) of an operator output."""
    if isinstance(value, Dataset):
        if value.is_array:
            leaves = jax.tree_util.tree_leaves(value.padded())
            return float(sum(x.nbytes for x in leaves)), 0.0
        return 0.0, float(
            sum(sys.getsizeof(x) for x in value.items())
        )
    if isinstance(value, jax.Array) or isinstance(value, np.ndarray):
        return float(value.nbytes), 0.0
    return 0.0, float(sys.getsizeof(value))


def get_node_weights(graph: Graph) -> Dict[NodeId, int]:
    """WeightedNode.weight = passes an operator makes over its input
    (reference: AutoCacheRule.getNodeWeights:23)."""
    return {
        n: int(getattr(op, "weight", 1))
        for n, op in graph.operators.items()
    }


def get_runs(
    graph: Graph,
    cache_set: Set[NodeId],
    weights: Dict[NodeId, int],
) -> Dict[NodeId, int]:
    """Times each node's expression is evaluated given the cached set
    (reference: AutoCacheRule.getRuns:57): a cached node evaluates once;
    otherwise once per pass each consumer makes. Sink reads count as one
    weight-1 consumer each."""
    runs: Dict[NodeId, int] = {}
    for n in reversed([g for g in linearize(graph) if isinstance(g, NodeId)]):
        total = 0
        for c in get_children(graph, n):
            if isinstance(c, SinkId):
                total += 1
            elif isinstance(c, NodeId):
                c_runs = 1 if c in cache_set else runs.get(c, 1)
                total += c_runs * weights.get(c, 1)
        runs[n] = max(total, 1)
    return runs


def estimate_cached_runtime(
    graph: Graph,
    cache_set: Set[NodeId],
    profiles: Dict[NodeId, Profile],
    weights: Dict[NodeId, int],
) -> float:
    """Total ns to execute everything given the cache set (reference:
    estimateCachedRunTime:471)."""
    runs = get_runs(graph, cache_set, weights)
    total = 0.0
    for n, p in profiles.items():
        effective = 1 if n in cache_set else runs[n]
        total += p.ns * effective
    return total


class _ScaledProfiler:
    """Executes the graph with dataset constants truncated to n/scale
    examples, timing each operator and measuring outputs (reference:
    profileNodes:153-465)."""

    def __init__(self, graph: Graph, scale: int):
        self.graph = graph
        self.scale = scale
        self.times: Dict[NodeId, float] = {}
        self.sizes: Dict[NodeId, Tuple[float, float]] = {}
        self.sample_n: Dict[NodeId, int] = {}
        self._memo: Dict[NodeId, Expression] = {}

    def execute(self, nid: NodeId) -> Expression:
        if nid in self._memo:
            return self._memo[nid]
        op = self.graph.operators[nid]
        if isinstance(op, DatasetOperator):
            ds = op.dataset
            k = max(1, ds.n // self.scale)
            self.sample_n[nid] = k
            sample = Dataset.from_items(ds.take(k))
            expr: Expression = DatasetExpression.of(sample)
            self.sizes[nid] = _measure_size(sample)
            self.times[nid] = 0.0
        else:
            deps = [self.execute(d) for d in self.graph.dependencies[nid]
                    if isinstance(d, NodeId)]
            if len(deps) != len(self.graph.dependencies[nid]):
                # source-dependent: not profilable
                raise _SourceDependent()
            t0 = time.perf_counter()
            expr = op.execute(deps)
            value = expr.get()  # force
            if isinstance(value, Dataset) and value.is_array:
                jax.block_until_ready(value.padded())
            self.times[nid] = (time.perf_counter() - t0) * 1e9
            self.sizes[nid] = _measure_size(value)
        self._memo[nid] = expr
        return expr


class _SourceDependent(Exception):
    pass


def profile_nodes(
    graph: Graph,
    nodes: List[NodeId],
    scales=DEFAULT_SAMPLE_SCALES,
) -> Dict[NodeId, Profile]:
    """Profile at each scale and linearly extrapolate to full size
    (reference: generalizeProfiles:104 — per-node least squares of
    time/memory vs scale).

    Each scale pass is timed through a ``PhaseTimer`` published into the
    global ``MetricsRegistry``
    (``keystone_phase_seconds_total{timer="auto_cache_profile"}``) and
    wrapped in a tracer span, so the cost the optimizer itself pays to
    decide cache placement is visible on the same plane as the serving
    numbers it optimizes for."""
    from keystone_tpu.observability.tracing import get_tracer
    from keystone_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer("auto_cache_profile")
    per_scale: Dict[int, _ScaledProfiler] = {}
    for scale in scales:
        prof = _ScaledProfiler(graph, scale)
        with timer.phase(f"scale_{scale}"), get_tracer().span(
            "auto_cache.profile", scale=scale, nodes=len(nodes)
        ):
            for n in nodes:
                try:
                    prof.execute(n)
                except _SourceDependent:
                    continue
        per_scale[scale] = prof
    timer.publish()

    profiles: Dict[NodeId, Profile] = {}
    for n in nodes:
        xs, ts, dm, hm = [], [], [], []
        for scale, prof in per_scale.items():
            if n in prof.times:
                xs.append(1.0 / scale)  # fraction of full data
                ts.append(prof.times[n])
                d, h = prof.sizes[n]
                dm.append(d)
                hm.append(h)
        if not xs:
            continue
        profiles[n] = Profile(
            _extrapolate(xs, ts), _extrapolate(xs, dm), _extrapolate(xs, hm)
        )
    return profiles


def _extrapolate(fractions: List[float], values: List[float]) -> float:
    """Fit value = a + b·fraction, evaluate at fraction=1 (full scale)."""
    if len(set(fractions)) == 1:
        return values[0] / fractions[0]
    b, a = np.polyfit(fractions, values, 1)
    return float(max(a + b, 0.0))


class AutoCacheRule(Rule):
    def __init__(
        self,
        strategy: str = "greedy",
        mem_budget_bytes: Optional[int] = None,
        scales=DEFAULT_SAMPLE_SCALES,
    ):
        self.strategy = strategy
        self.mem_budget_bytes = mem_budget_bytes
        self.scales = scales

    # -- cache-set selection ----------------------------------------------

    def _budget(self) -> float:
        if self.mem_budget_bytes is not None:
            return float(self.mem_budget_bytes)
        # the shared None-guarded memory_stats probe
        # (observability/device.py — one code path with weighted_ls
        # and the device memory gauges)
        from keystone_tpu.observability.device import device_memory_stats

        stats = device_memory_stats()
        if stats and "bytes_limit" in stats:
            free = stats["bytes_limit"] - stats.get("bytes_in_use", 0)
            return DEFAULT_BUDGET_FRACTION * free
        return DEFAULT_BUDGET_FRACTION * 8e9  # CPU-host fallback

    def aggressive_cache(
        self, graph: Graph, weights: Dict[NodeId, int]
    ) -> Set[NodeId]:
        """Cache every node whose DIRECT output is consumed more than
        once — Σ over direct children of the child's weight (sinks count
        1) — excluding descendants of sources (test-time data; reference
        AutoCacheRule.aggressiveCache:503-518). NOT the transitive run
        count: a node feeding a single hot consumer is NOT cached (its
        consumer is), matching the reference suite's {+2, +5} selection
        on its 13-node plan."""
        from keystone_tpu.workflow.graph import get_descendants

        source_desc: Set[NodeId] = set()
        for src in graph.sources:
            source_desc |= {
                d for d in get_descendants(graph, src)
                if isinstance(d, NodeId)
            }
        selected: Set[NodeId] = set()
        for n in graph.operators:
            if n in source_desc:
                continue
            total = 0
            for c in get_children(graph, n):
                if isinstance(c, NodeId):
                    total += weights.get(c, 1)
                else:
                    total += 1
            if total > 1:
                selected.add(n)
        return selected

    def greedy_cache(
        self,
        graph: Graph,
        profiles: Dict[NodeId, Profile],
        weights: Dict[NodeId, int],
    ) -> Set[NodeId]:
        """Iteratively cache the node with the best runtime improvement
        until nothing improves or the budget is exhausted (reference:
        greedyCache:559-602, selectNext:542)."""
        budget = self._budget()
        cached: Set[NodeId] = set()
        used = 0.0
        while True:
            base = estimate_cached_runtime(graph, cached, profiles, weights)
            best, best_rt = None, base
            runs = get_runs(graph, cached, weights)
            for n, p in profiles.items():
                # reference selectNext:542 — only nodes still evaluated
                # more than once and fitting the remaining budget
                if (
                    n in cached
                    or runs.get(n, 1) <= 1
                    or p.device_mem + used > budget
                ):
                    continue
                rt = estimate_cached_runtime(
                    graph, cached | {n}, profiles, weights
                )
                if rt < best_rt:
                    best, best_rt = n, rt
            if best is None:
                return cached
            cached.add(best)
            used += profiles[best].device_mem

    # -- graph surgery ----------------------------------------------------

    @staticmethod
    def add_caches(graph: Graph, cache_set: Set[NodeId]) -> Graph:
        """Insert a Cacher() node downstream of each selected node
        (reference: addCachesToPipeline:492)."""
        from keystone_tpu.ops.util.cacher import Cacher

        for n in sorted(cache_set):
            graph, cacher = graph.add_node(Cacher(), ())
            graph = graph.replace_dependency(n, cacher)
            graph = graph.set_dependencies(cacher, (n,))
        return graph

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        from keystone_tpu.ops.util.cacher import Cacher

        weights = get_node_weights(graph)
        already = {
            n for n, op in graph.operators.items() if isinstance(op, Cacher)
        }
        # candidates: nodes not already cached and not feeding a Cacher
        candidates = [
            n
            for n in sorted(graph.operators)
            if n not in already
            and not any(
                isinstance(c, NodeId)
                and isinstance(graph.operators.get(c), Cacher)
                for c in get_children(graph, n)
            )
        ]
        if self.strategy == "aggressive":
            to_cache = self.aggressive_cache(graph, weights) - already
            to_cache = {n for n in to_cache if n in candidates}
        else:
            profiles = profile_nodes(graph, candidates, self.scales)
            if logger.isEnabledFor(logging.INFO):
                for n in sorted(profiles):
                    p = profiles[n]
                    logger.info(
                        "auto-cache profile node %s [%s]: %.1f ms, "
                        "%.0f device bytes, weight %d",
                        n,
                        graph.operators[n].label,
                        p.ns / 1e6,
                        p.device_mem,
                        weights.get(n, 1),
                    )
            to_cache = self.greedy_cache(graph, profiles, weights)
        logger.info(
            "auto-cache decision (%s): caching %s",
            self.strategy,
            sorted(to_cache) or "nothing",
        )
        if not to_cache:
            return graph, prefixes
        return self.add_caches(graph, to_cache), prefixes
