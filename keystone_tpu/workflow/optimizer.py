"""Default optimizer pipelines.

Reference semantics: workflow/DefaultOptimizer.scala — batches:
(1) load saved state (extract saveable prefixes, substitute saved results,
    prune the now-dead branches), once;
(2) common-subexpression elimination, fixed point;
(3) cost-based physical node optimization, once.
``AutoCachingOptimizer`` appends profile-driven cache insertion.
"""

from __future__ import annotations

from typing import List

from keystone_tpu.workflow.rules import (
    Batch,
    EquivalentNodeMergeRule,
    ExtractSaveablePrefixes,
    FixedPoint,
    Once,
    RuleExecutor,
    SavedStateLoadRule,
    UnusedBranchRemovalRule,
)


class DefaultOptimizer(RuleExecutor):
    def batches(self) -> List[Batch]:
        from keystone_tpu.workflow.node_optimization import NodeOptimizationRule

        return [
            Batch(
                "Load Saved State",
                Once(),
                [
                    ExtractSaveablePrefixes(),
                    SavedStateLoadRule(),
                    UnusedBranchRemovalRule(),
                ],
            ),
            Batch(
                "Common Sub-expression Elimination",
                FixedPoint(100),
                [EquivalentNodeMergeRule()],
            ),
            Batch("Node Level Optimization", Once(), [NodeOptimizationRule()]),
        ]


class AutoCachingOptimizer(RuleExecutor):
    """DefaultOptimizer + profile-driven automatic cache placement."""

    def __init__(self, strategy: str = "greedy", mem_budget_bytes: int = None):
        self.strategy = strategy
        self.mem_budget_bytes = mem_budget_bytes

    def batches(self) -> List[Batch]:
        from keystone_tpu.workflow.auto_cache import AutoCacheRule

        return DefaultOptimizer().batches() + [
            Batch(
                "Auto Cache",
                Once(),
                [AutoCacheRule(self.strategy, self.mem_budget_bytes)],
            )
        ]
