"""Workflow core: the typed pipeline API over an optimizable dataflow DAG."""

from keystone_tpu.workflow.api import (  # noqa: F401
    Chainable,
    Estimator,
    FittedPipeline,
    FunctionNode,
    GatherTransformerOperator,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineResult,
    Transformer,
    transformer,
)
from keystone_tpu.workflow.executor import (  # noqa: F401
    GraphExecutor,
    PipelineEnv,
)
from keystone_tpu.workflow.graph import (  # noqa: F401
    EMPTY_GRAPH,
    Graph,
    NodeId,
    SinkId,
    SourceId,
)
from keystone_tpu.workflow.node_optimization import Optimizable  # noqa: F401
from keystone_tpu.workflow.optimizer import (  # noqa: F401
    AutoCachingOptimizer,
    DefaultOptimizer,
)
