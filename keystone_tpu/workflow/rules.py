"""Catalyst-style rule engine + the structural optimization rules.

Reference semantics: workflow/Rule.scala, RuleExecutor.scala (batches with
Once/FixedPoint strategies), EquivalentNodeMergeRule (CSE),
UnusedBranchRemovalRule (dead-code elimination), ExtractSaveablePrefixes +
SavedStateLoadRule (cross-pipeline prefix memoization).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.expressions import Expression
from keystone_tpu.workflow.graph import (
    Graph,
    NodeId,
    SinkId,
    get_ancestors,
)
from keystone_tpu.workflow.operators import (
    EstimatorOperator,
    ExpressionOperator,
    Operator,
)
from keystone_tpu.workflow.prefix import Prefix, find_prefix

logger = logging.getLogger(__name__)

PrefixMap = Dict[NodeId, Prefix]


class Rule:
    """Graph -> Graph rewrite, threading the saveable-prefix map through."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        raise NotImplementedError


class Once:
    max_iterations = 1


class FixedPoint:
    def __init__(self, max_iterations: int = 100):
        self.max_iterations = max_iterations


@dataclass
class Batch:
    name: str
    strategy: object
    rules: Sequence[Rule] = field(default_factory=list)


class RuleExecutor:
    """Runs batches of rules to convergence per their strategies."""

    def batches(self) -> List[Batch]:
        raise NotImplementedError

    def execute(self, graph: Graph) -> Tuple[Graph, PrefixMap]:
        prefixes: PrefixMap = {}
        for batch in self.batches():
            iteration = 0
            while iteration < batch.strategy.max_iterations:
                iteration += 1
                before = (graph, dict(prefixes))
                for rule in batch.rules:
                    pre = graph
                    graph, prefixes = rule.apply(graph, prefixes)
                    if logger.isEnabledFor(logging.INFO) and graph != pre:
                        # Per-rule diff logging (reference:
                        # RuleExecutor.scala:44-50 logs a DOT of the plan
                        # after every effective rule application).
                        logger.info(
                            "optimizer batch %r rule %s (iter %d): "
                            "%d -> %d nodes, %d -> %d sources",
                            batch.name,
                            rule.name,
                            iteration,
                            len(pre.operators),
                            len(graph.operators),
                            len(pre.sources),
                            len(graph.sources),
                        )
                        if logger.isEnabledFor(logging.DEBUG):
                            logger.debug(
                                "graph after %s:\n%s",
                                rule.name,
                                graph.to_dot(),
                            )
                if graph == before[0] and prefixes == before[1]:
                    break
            else:
                if not isinstance(batch.strategy, Once):
                    logger.warning(
                        "optimizer batch %r hit max iterations (%d)",
                        batch.name,
                        batch.strategy.max_iterations,
                    )
        return graph, prefixes


class EquivalentNodeMergeRule(Rule):
    """CSE: merge nodes with equal (operator, dependencies).

    Equality of operators is ``Operator.eq_key()`` — shared instances always
    merge; dataclass-keyed operators merge structurally.
    """

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        by_sig: Dict[tuple, List[NodeId]] = {}
        for n in sorted(graph.operators.keys()):
            sig = (graph.operators[n].eq_key(), graph.dependencies[n])
            by_sig.setdefault(sig, []).append(n)
        changed = False
        for sig, group in by_sig.items():
            if len(group) < 2:
                continue
            keep, *drop = group
            for n in drop:
                graph = graph.replace_dependency(n, keep)
                graph = graph.remove_node(n)
                prefixes.pop(n, None)
                changed = True
        if changed:
            # Dep rewrites may expose new merges; FixedPoint re-runs us.
            pass
        return graph, prefixes


class UnusedBranchRemovalRule(Rule):
    """Drop nodes and sources that are not ancestors of any sink."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        live: Set = set()
        for k in graph.sink_dependencies:
            live.add(graph.sink_dependencies[k])
            live |= get_ancestors(graph, k)
        dead_nodes = [n for n in graph.operators if n not in live]
        dead_sources = [s for s in graph.sources if s not in live]
        # Remove in reverse-topological order: repeatedly delete unreferenced.
        pending = set(dead_nodes)
        while pending:
            progress = False
            for n in sorted(pending):
                try:
                    graph = graph.remove_node(n)
                except ValueError:
                    continue
                pending.discard(n)
                prefixes.pop(n, None)
                progress = True
                break
            if not progress:
                raise RuntimeError("cycle among dead nodes?")
        for s in dead_sources:
            graph = graph.remove_source(s)
        return graph, prefixes


def _is_saveable_op(op: Operator) -> bool:
    from keystone_tpu.ops.util.cacher import Cacher

    return isinstance(op, (EstimatorOperator, Cacher))


class ExtractSaveablePrefixes(Rule):
    """Compute prefixes for nodes whose results are worth persisting:
    estimator fits and explicit Cacher materialization points."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        new = dict(prefixes)
        for n, op in graph.operators.items():
            if _is_saveable_op(op):
                p = find_prefix(graph, n)
                if p is not None:
                    new[n] = p
        return graph, new


class SavedStateLoadRule(Rule):
    """Substitute already-computed expressions for nodes whose prefix is in
    the global state — this makes re-running/refitting pipelines free."""

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        state = PipelineEnv.get_or_create().state
        new_prefixes = dict(prefixes)
        for n, p in list(prefixes.items()):
            if n not in graph.operators:
                continue
            expr = state.get(p)
            if expr is not None and not isinstance(
                graph.operators[n], ExpressionOperator
            ):
                graph = graph.set_operator(n, ExpressionOperator(expr))
                graph = graph.set_dependencies(n, ())
        return graph, new_prefixes
