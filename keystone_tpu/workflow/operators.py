"""Operator ABI — the untyped execution contract of graph nodes.

Reference semantics: workflow/Operator.scala — ``execute(deps) -> Expression``
with concrete operators for constant datasets/datums, transformers (dual
single/batch paths), estimators (fit -> transformer), the delegating operator
(applies a fit transformer expression), and constant-expression operators
(loaded saved state).

Equality drives common-subexpression elimination (EquivalentNodeMergeRule):
operators compare by ``eq_key()`` which defaults to identity; dataclass-style
nodes should override (the Transformer/Estimator base classes in api.py do).
"""

from __future__ import annotations

from typing import Any, Sequence

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.expressions import (
    DatasetExpression,
    DatumExpression,
    Expression,
    TransformerExpression,
)


class Operator:
    label: str = ""

    def execute(self, deps: Sequence[Expression]) -> Expression:
        raise NotImplementedError

    def eq_key(self) -> Any:
        """Key for CSE equality. Default: object identity."""
        return id(self)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Operator) and self.eq_key() == other.eq_key()

    def __hash__(self) -> int:
        return hash(self.eq_key())

    def __getstate__(self):
        # process-local state that must not bloat or poison pickles
        # (FittedPipeline.save): the eq_key digest cache holds array
        # references keyed by id(); the vmap cache holds a jitted closure
        state = dict(self.__dict__)
        state.pop("_arr_digest_cache", None)
        state.pop("_vmapped_apply", None)
        return state


class DatasetOperator(Operator):
    """Constant dataset (reference: DatasetOperator wrapping an RDD)."""

    def __init__(self, dataset: Dataset, label: str = "dataset"):
        self.dataset = Dataset.of(dataset)
        self.label = label

    def eq_key(self):
        # Same underlying Dataset object => same operator (the reference's
        # case-class equality over a shared RDD reference), so prefixes built
        # from the same data compare equal across pipelines.
        return ("dataset", id(self.dataset))

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if deps:
            raise AssertionError(
                f"DatasetOperator takes no dependencies, got {len(deps)}"
            )
        return DatasetExpression.of(self.dataset)


class DatumOperator(Operator):
    """Constant single datum."""

    def __init__(self, datum: Any, label: str = "datum"):
        self.datum = datum
        self.label = label

    def eq_key(self):
        return ("datum", id(self.datum))

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if deps:
            raise AssertionError(
                f"DatumOperator takes no dependencies, got {len(deps)}"
            )
        return DatumExpression.of(self.datum)


class TransformerOperator(Operator):
    """A data -> data operator with single-datum and batch paths."""

    def single_transform(self, inputs: Sequence[Any]) -> Any:
        raise NotImplementedError

    def batch_transform(self, inputs: Sequence[Dataset]) -> Dataset:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if any(isinstance(d, DatasetExpression) for d in deps):
            return DatasetExpression(
                lambda: self.batch_transform([d.get() for d in deps])
            )
        return DatumExpression(
            lambda: self.single_transform([d.get() for d in deps])
        )


class EstimatorOperator(Operator):
    """fit(datasets) -> TransformerOperator."""

    def fit_datasets(self, datasets: Sequence[Dataset]) -> TransformerOperator:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> Expression:
        return TransformerExpression(
            lambda: self.fit_datasets([d.get() for d in deps])
        )


class DelegatingOperator(Operator):
    """Applies a fit transformer (dep 0) to the remaining deps.

    This is the node an ``Estimator.with_data`` splice leaves downstream of
    the estimator; Pipeline.fit() swaps it for the concrete fit transformer.
    """

    label = "delegate"

    def execute(self, deps: Sequence[Expression]) -> Expression:
        transformer_expr = deps[0]
        data_deps = deps[1:]
        if not data_deps:
            raise AssertionError(
                "delegating operator needs data dependencies"
            )
        if any(isinstance(d, DatasetExpression) for d in data_deps):
            return DatasetExpression(
                lambda: transformer_expr.get().batch_transform(
                    [d.get() for d in data_deps]
                )
            )
        return DatumExpression(
            lambda: transformer_expr.get().single_transform(
                [d.get() for d in data_deps]
            )
        )


class ExpressionOperator(Operator):
    """Constant pre-computed expression (loaded saved state)."""

    label = "saved"

    def __init__(self, expression: Expression):
        self.expression = expression

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if deps:
            raise AssertionError(
                f"ExpressionOperator takes no dependencies, got {len(deps)}"
            )
        return self.expression
