"""Cost-based physical operator selection.

Reference semantics: workflow/NodeOptimizationRule.scala +
OptimizableNodes.scala — nodes that declare themselves Optimizable expose a
``default`` implementation plus ``optimize(sample, n_total)`` which inspects a
small sample of their actual input (shape, sparsity, size) and returns the
physical operator to run (e.g. LeastSquaresEstimator picking between L-BFGS,
block coordinate descent, and an exact solve by cost model).
"""

from __future__ import annotations

from typing import Dict, Tuple

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.expressions import (
    DatasetExpression,
    Expression,
)
from keystone_tpu.workflow.graph import (
    Graph,
    NodeId,
    SourceId,
    get_ancestors,
)
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    Operator,
)
from keystone_tpu.workflow.rules import PrefixMap, Rule

DEFAULT_SAMPLE_SIZE = 96


class Optimizable:
    """Mix-in for operators with selectable physical implementations."""

    def optimize(self, samples, n_total: int) -> Operator:
        """``samples``: list of sampled dep values (Datasets for dataset
        deps); ``n_total``: true example count of the first dataset dep."""
        raise NotImplementedError


class _SampleCollector:
    """Executes a node's upstream graph with dataset constants truncated to a
    sample, recording each dataset's true size."""

    def __init__(self, graph: Graph, sample_size: int):
        self.graph = graph
        self.sample_size = sample_size
        self.full_sizes: Dict[NodeId, int] = {}
        self._memo: Dict[NodeId, Expression] = {}

    def execute(self, nid: NodeId) -> Expression:
        if nid in self._memo:
            return self._memo[nid]
        op = self.graph.operators[nid]
        if isinstance(op, DatasetOperator):
            ds = op.dataset
            self.full_sizes[nid] = ds.n
            sample = Dataset.from_items(ds.take(self.sample_size))
            expr: Expression = DatasetExpression.of(sample)
        else:
            deps = [self.execute(d) for d in self.graph.dependencies[nid]]
            expr = op.execute(deps)
        self._memo[nid] = expr
        return expr

    def true_n(self, nid: NodeId) -> int:
        """Best-effort true example count upstream of ``nid``: the size of
        the nearest dataset constant feeding it (transformers preserve n)."""
        op = self.graph.operators[nid]
        if isinstance(op, DatasetOperator):
            return self.full_sizes.get(nid, op.dataset.n)
        for d in self.graph.dependencies[nid]:
            if isinstance(d, NodeId):
                n = self.true_n(d)
                if n >= 0:
                    return n
        return -1


class NodeOptimizationRule(Rule):
    def __init__(self, sample_size: int = DEFAULT_SAMPLE_SIZE):
        self.sample_size = sample_size

    def apply(self, graph: Graph, prefixes: PrefixMap) -> Tuple[Graph, PrefixMap]:
        optimizable = [
            n
            for n in sorted(graph.operators.keys())
            if isinstance(graph.operators[n], Optimizable)
        ]
        if not optimizable:
            return graph, prefixes
        collector = _SampleCollector(graph, self.sample_size)
        for n in optimizable:
            # Nodes fed (transitively) by a source can't be sampled: their
            # input is runtime data not yet spliced in.
            if any(
                isinstance(a, SourceId) for a in get_ancestors(graph, n)
            ):
                continue
            deps = graph.dependencies[n]
            samples = [collector.execute(d) for d in deps if isinstance(d, NodeId)]
            if len(samples) != len(deps):
                continue
            # optimize() inspects DATASET samples; a datum-fed node (e.g.
            # a transformer applied to single test items) keeps its
            # default — the reference's rule only matches DatasetExpression
            # inputs (NodeOptimizationRuleSuite: "the optimizable
            # transformer should use the default on test data")
            if not all(isinstance(s, DatasetExpression) for s in samples):
                continue
            sample_values = [s.get() for s in samples]
            n_total = collector.true_n(deps[0]) if deps else -1
            new_op = graph.operators[n].optimize(sample_values, n_total)
            if new_op is not None and new_op is not graph.operators[n]:
                graph = graph.set_operator(n, new_op)
                prefixes.pop(n, None)
        return graph, prefixes
