"""Transformer/estimator fusion chains.

Reference: workflow/ChainUtils.scala:12,22,35 — TransformerChain,
TransformerEstimatorChain, TransformerLabelEstimatorChain: fuse a
transformer in front of an estimator so the pair presents as ONE estimator
(used by LeastSquaresEstimator's physical options, e.g. Densify() +
BlockLeastSquaresEstimator).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import (
    Estimator,
    LabelEstimator,
    Transformer,
)


@dataclasses.dataclass(eq=False)
class TransformerChain(Transformer):
    """Apply a sequence of transformers as one (reference:
    ChainUtils.scala:12)."""

    transformers: Sequence[Transformer]

    def apply(self, x):
        for t in self.transformers:
            x = t.apply(x)
        return x

    def apply_batch(self, ds: Dataset) -> Dataset:
        for t in self.transformers:
            ds = t.apply_batch(ds)
        return ds


@dataclasses.dataclass(eq=False)
class TransformerEstimatorChain(Estimator):
    """transformer + estimator fused into one estimator; the fit result is
    transformer andThen fitted (reference: ChainUtils.scala:22)."""

    transformer: Transformer
    estimator: Estimator

    def fit(self, data: Dataset) -> Transformer:
        fitted = self.estimator.fit(self.transformer.apply_batch(data))
        return TransformerChain([self.transformer, fitted])

    @property
    def weight(self) -> int:
        return getattr(self.estimator, "weight", 1)


@dataclasses.dataclass(eq=False)
class TransformerLabelEstimatorChain(LabelEstimator):
    """Same with a LabelEstimator (reference: ChainUtils.scala:35)."""

    transformer: Transformer
    estimator: LabelEstimator

    def fit(self, data: Dataset, labels: Dataset) -> Transformer:
        fitted = self.estimator.fit(
            self.transformer.apply_batch(data), labels
        )
        return TransformerChain([self.transformer, fitted])

    @property
    def weight(self) -> int:
        return getattr(self.estimator, "weight", 1)
