"""Memoizing graph executor + process-global pipeline environment.

Reference semantics: workflow/GraphExecutor.scala (memoized recursive
interpretation, optimize-once-lazily, refuse to execute source-dependent ids,
save executed prefixes into the global state) and workflow/PipelineEnv.scala
(process singleton holding cross-pipeline prefix state and the optimizer).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Set, Tuple

from keystone_tpu.observability.tracing import get_tracer
from keystone_tpu.workflow.expressions import Expression
from keystone_tpu.workflow.graph import (
    Graph,
    GraphId,
    NodeId,
    SinkId,
    SourceId,
    get_ancestors,
)
from keystone_tpu.workflow.prefix import Prefix


class PipelineEnv:
    """Process-global: prefix-keyed saved state + the active optimizer."""

    _instance: Optional["PipelineEnv"] = None

    def __init__(self):
        self.state: Dict[Prefix, Expression] = {}
        self._optimizer = None

    @classmethod
    def get_or_create(cls) -> "PipelineEnv":
        if cls._instance is None:
            cls._instance = PipelineEnv()
        return cls._instance

    @property
    def optimizer(self):
        if self._optimizer is None:
            from keystone_tpu.workflow.optimizer import DefaultOptimizer

            self._optimizer = DefaultOptimizer()
        return self._optimizer

    @optimizer.setter
    def optimizer(self, opt) -> None:
        self._optimizer = opt

    def reset(self) -> None:
        self.state = {}
        self._optimizer = None

    # -- persistence (SURVEY §5 checkpoint level 2: the prefix state is a
    # content-addressed cache keyed by structural prefix hash; persisting
    # it lets re-built pipelines in a NEW process skip recompute) --------

    def save_state(
        self,
        path: str,
        *,
        large_array_bytes: int = 1 << 20,
        max_total_bytes: Optional[int] = None,
    ) -> None:
        """Persist every materialized prefix expression to a directory:
        ``index.pkl`` plus one ``.npy`` file per large array.

        Arrays over ``large_array_bytes`` stream to their own file one at
        a time (device -> host -> disk, then released) so a flagship-scale
        cached feature dataset never needs the whole state resident on
        host at once. ``max_total_bytes`` caps what gets written: an
        entry that would exceed the budget is skipped whole (its partial
        files are removed and un-charged), in state-iteration order.
        Unevaluated (never-forced) expressions are skipped, not forced.
        """
        import os
        import pickle

        import jax
        import numpy as np

        from keystone_tpu.parallel.dataset import Dataset

        os.makedirs(path, exist_ok=True)
        index = {}
        written = 0
        counter = 0

        def persist_tree(tree):
            """Replace large arrays with .npy file references; returns
            the persisted tree, or None (with files and budget rolled
            back) if the entry would exceed the budget."""
            nonlocal counter, written
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            out_leaves = []
            entry_files = []
            entry_bytes = 0

            def rollback():
                nonlocal written
                for f in entry_files:
                    try:
                        os.remove(os.path.join(path, f))
                    except OSError:
                        pass
                written -= entry_bytes

            for leaf in leaves:
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    a = np.asarray(leaf)
                    if (
                        max_total_bytes is not None
                        and written + a.nbytes > max_total_bytes
                    ):
                        rollback()
                        return None
                    if a.nbytes >= large_array_bytes:
                        fname = f"arr{counter:05d}.npy"
                        counter += 1
                        np.save(os.path.join(path, fname), a)
                        written += a.nbytes
                        entry_bytes += a.nbytes
                        entry_files.append(fname)
                        out_leaves.append(("npy", fname))
                        del a
                        continue
                    written += a.nbytes
                    entry_bytes += a.nbytes
                    out_leaves.append(("arr", a))
                else:
                    out_leaves.append(("raw", leaf))
            return jax.tree_util.tree_unflatten(treedef, out_leaves)

        for prefix, expr in self.state.items():
            if not expr.is_computed:
                continue
            value = expr.get()
            if isinstance(value, Dataset):
                if value.is_array:
                    tree = persist_tree(value.padded())
                    if tree is None:
                        continue
                    entry = ("dataset_array", tree, value.n)
                else:
                    tree = persist_tree(value.items())
                    if tree is None:
                        continue
                    entry = ("dataset_items", tree, None)
            else:
                entry = ("raw", value, None)
            try:
                pickle.dumps(entry)
            except Exception:
                continue  # unpicklable (e.g. closure-defined transformer)
            index[prefix] = entry
        with open(os.path.join(path, "index.pkl"), "wb") as f:
            pickle.dump(index, f)

    def load_state(self, path: str) -> int:
        """Load persisted prefix state; returns the number of entries."""
        import os
        import pickle

        import jax
        import numpy as np

        from keystone_tpu.parallel.dataset import Dataset
        from keystone_tpu.workflow.expressions import (
            DatasetExpression,
            DatumExpression,
        )

        with open(os.path.join(path, "index.pkl"), "rb") as f:
            saved = pickle.load(f)

        def restore_tree(tree):
            def restore(leaf):
                kind, payload = leaf
                if kind == "npy":
                    return np.load(os.path.join(path, payload))
                return payload

            return jax.tree_util.tree_map(
                restore, tree,
                is_leaf=lambda x: isinstance(x, tuple)
                and len(x) == 2
                and isinstance(x[0], str)
                and x[0] in ("npy", "arr", "raw"),
            )

        for prefix, (kind, payload, n) in saved.items():
            if kind == "dataset_array":
                ds = Dataset.from_array(restore_tree(payload), n=n)
                self.state[prefix] = DatasetExpression.of(ds)
            elif kind == "dataset_items":
                ds = Dataset.from_items(restore_tree(payload))
                self.state[prefix] = DatasetExpression.of(ds)
            else:
                self.state[prefix] = DatumExpression.of(payload)
        return len(saved)


class GraphExecutor:
    """Executes a graph, memoizing per-id expressions.

    ``optimize=True`` runs the environment's optimizer once, lazily, before
    the first execution. Ids with a source ancestor cannot be executed (their
    value depends on unspliced runtime data).

    Observability: ``node_hook`` is an optional
    ``callable(node_id, label, seconds)`` invoked with each node's own
    operator-execution wall time (excluding dependency time) the first
    time the node runs — ``utils.profiling.instrument_executor`` sets it.
    Independently, when the process-global tracer
    (``observability.tracing``) is enabled, every first-time node
    evaluation records a ``node:<label>`` span whose parent is the span
    of the consumer that demanded it, so ``/tracez`` shows the executed
    DAG as a span tree. Both are off by default and cost one attribute
    check per node when off.
    """

    def __init__(
        self,
        graph: Graph,
        optimize: bool = True,
        node_hook: Optional[Callable[[GraphId, str, float], None]] = None,
    ):
        self._raw_graph = graph
        self._optimize = optimize
        self._optimized: Optional[Tuple[Graph, Dict[NodeId, Prefix]]] = None
        self._execution_state: Dict[GraphId, Expression] = {}
        self._source_dependants: Optional[Set[GraphId]] = None
        self.node_hook = node_hook

    @property
    def raw_graph(self) -> Graph:
        return self._raw_graph

    @property
    def graph(self) -> Graph:
        return self._optimized_graph_and_prefixes()[0]

    @property
    def prefixes(self) -> Dict[NodeId, Prefix]:
        return self._optimized_graph_and_prefixes()[1]

    def _optimized_graph_and_prefixes(self):
        if self._optimized is None:
            if self._optimize:
                env = PipelineEnv.get_or_create()
                self._optimized = env.optimizer.execute(self._raw_graph)
            else:
                self._optimized = (self._raw_graph, {})
        return self._optimized

    def _unexecutable(self) -> Set[GraphId]:
        if self._source_dependants is None:
            g = self.graph
            bad: Set[GraphId] = set(g.sources)
            for s in g.sources:
                from keystone_tpu.workflow.graph import get_descendants

                bad |= get_descendants(g, s)
            self._source_dependants = bad
        return self._source_dependants

    def execute(self, graph_id: GraphId) -> Expression:
        if graph_id in self._unexecutable():
            raise ValueError(
                f"{graph_id} depends on an unconnected source; splice data in "
                "with pipeline.apply(...) before executing"
            )
        if graph_id in self._execution_state:
            return self._execution_state[graph_id]

        g, prefixes = self._optimized_graph_and_prefixes()
        if isinstance(graph_id, SourceId):
            raise ValueError(f"cannot execute source {graph_id}")
        if isinstance(graph_id, SinkId):
            expr = self.execute(g.sink_dependencies[graph_id])
        else:
            tracer = get_tracer()
            if tracer.enabled or self.node_hook is not None:
                expr = self._execute_instrumented(graph_id, g, tracer)
            else:
                dep_exprs = [
                    self.execute(d) for d in g.dependencies[graph_id]
                ]
                expr = g.operators[graph_id].execute(dep_exprs)
            # Cross-pipeline prefix memoization (GraphExecutor.scala:68-70):
            # expose this node's expression under its structural prefix.
            prefix = prefixes.get(graph_id)
            if prefix is not None:
                PipelineEnv.get_or_create().state.setdefault(prefix, expr)
        self._execution_state[graph_id] = expr
        return expr

    def _execute_instrumented(self, graph_id, g, tracer) -> Expression:
        """First-time node evaluation with a ``node:<label>`` span around
        the whole demand (so dependency spans nest under their consumer,
        mirroring the executed DAG in ``/tracez``) and the node's OWN
        operator wall time — dependencies excluded — reported to
        ``node_hook`` and stamped on the span."""
        op = g.operators[graph_id]
        label = getattr(op, "label", type(op).__name__)
        with tracer.span(f"node:{label}", node_id=str(graph_id)) as span:
            dep_exprs = [self.execute(d) for d in g.dependencies[graph_id]]
            t0 = time.perf_counter()
            expr = op.execute(dep_exprs)
            self_seconds = time.perf_counter() - t0
            span.set_attr("self_ms", round(self_seconds * 1e3, 6))
        if self.node_hook is not None:
            self.node_hook(graph_id, label, self_seconds)
        return expr
