"""Structural prefix hashing for cross-pipeline memoization.

Reference semantics: workflow/Prefix.scala — a node's Prefix is the structural
identity of its entire upstream subgraph (its operator plus the prefixes of
its dependencies, in order). Two nodes in *different* pipelines that share a
prefix computed the same value, so the executed Expression can be reused
(SavedStateLoadRule). Undefined for nodes with a source ancestor (their value
depends on runtime data).
"""

from __future__ import annotations

from typing import Dict, Optional

from keystone_tpu.workflow.graph import Graph, NodeId, SourceId


class Prefix:
    """Hash-consed structural identity of a node's upstream subgraph."""

    __slots__ = ("op_key", "dep_prefixes", "_hash")

    def __init__(self, op_key, dep_prefixes):
        self.op_key = op_key
        self.dep_prefixes = tuple(dep_prefixes)
        self._hash = hash((op_key, self.dep_prefixes))

    def __eq__(self, other):
        return (
            isinstance(other, Prefix)
            and self._hash == other._hash
            and self.op_key == other.op_key
            and self.dep_prefixes == other.dep_prefixes
        )

    def __hash__(self):
        return self._hash

    def __repr__(self):
        return f"Prefix({self.op_key!r}, deps={len(self.dep_prefixes)})"


def find_prefix(graph: Graph, node: NodeId) -> Optional[Prefix]:
    """Prefix of ``node``, or None if it depends on any source."""
    memo: Dict[NodeId, Optional[Prefix]] = {}

    def rec(n: NodeId) -> Optional[Prefix]:
        if n in memo:
            return memo[n]
        deps = graph.dependencies[n]
        dep_prefixes = []
        result: Optional[Prefix] = None
        ok = True
        for d in deps:
            if isinstance(d, SourceId):
                ok = False
                break
            dp = rec(d)
            if dp is None:
                ok = False
                break
            dep_prefixes.append(dp)
        if ok:
            result = Prefix(graph.operators[n].eq_key(), dep_prefixes)
        memo[n] = result
        return result

    return rec(node)
