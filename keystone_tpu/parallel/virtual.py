"""Virtual-device provisioning for multi-chip code paths without chips.

The reference tests simulate a cluster with multi-partition local RDDs
(SURVEY.md §4); the JAX equivalent is a virtual n-device CPU platform.
This is the ONE place that knows how to provision it — used by both
tests/conftest.py and the driver's ``dryrun_multichip`` entry point so the
two can't drift.

JAX constraint: ``jax_platforms`` / ``jax_num_cpu_devices`` must be set
before the backend initializes, and initializing is the only in-process
way to count real devices. So when the backend is uninitialized we probe
the real device count in a THROWAWAY SUBPROCESS and only downgrade the
parent to the virtual CPU platform when the real platform is short.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE = "import jax; print(len(jax.devices()))"


def backend_initialized() -> bool:
    """Whether a jax backend already exists, WITHOUT creating one."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def _probe_real_device_count(timeout: float = 120.0) -> int:
    """Count devices the parent process would get, in a subprocess so the
    parent's backend stays uninitialized (and configurable). The probe
    inherits the environment unchanged — a user-forced JAX_PLATFORMS must
    be counted the same way the parent will experience it."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        return int(out.stdout.strip().splitlines()[-1])
    except Exception:
        return 0


def provision_devices(n_devices: int, *, probe_real: bool = True) -> None:
    """Ensure ``jax.devices()`` will return >= n_devices.

    Real devices are preferred: if the default platform already has enough
    (probed in a subprocess when the backend is uninitialized), it is left
    untouched. Otherwise the process is switched to a virtual CPU platform
    with exactly ``n_devices`` devices. Raises if the backend is already
    initialized with too few devices (too late to reconfigure).
    """
    import jax

    if backend_initialized():
        have = len(jax.devices())
        if have < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices but the jax backend is already "
                f"initialized with {have}; call provision_devices() before "
                f"any jax operation (fresh process)"
            )
        return

    if probe_real and _probe_real_device_count() >= n_devices:
        return  # real platform suffices; leave config alone

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # older jax: the device count is an XLA flag, honored only if set
        # before backend init (which provision_devices guarantees)
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if flag not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    have = len(jax.devices())
    if have < n_devices:
        raise RuntimeError(
            f"could not provision {n_devices} virtual CPU devices; "
            f"got {have}"
        )
