"""Device-mesh management.

One process-wide default mesh, settable via ``use_mesh``. Axis conventions:

- ``DATA_AXIS`` ("data"): examples are sharded along this axis — the
  equivalent of the reference's RDD partitioning of rows
  (workflow/Transformer.scala:46 maps over partitions).
- ``MODEL_AXIS`` ("model"): feature/model-block axis — the equivalent of the
  reference's VectorSplitter feature blocking (nodes/util/VectorSplitter.scala)
  when a solver shards its weights.

On a single chip the mesh is 1x1 and all collectives are no-ops; the same
code scales to a multi-host slice by building a bigger mesh (the driver
validates this via __graft_entry__.dryrun_multichip on a virtual CPU mesh).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"

_current_mesh: Optional[Mesh] = None


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over ``devices`` (default: all devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_model
    if n_data * n_model != len(devs):
        raise ValueError(
            f"mesh {n_data}x{n_model} != {len(devs)} devices"
        )
    arr = np.array(devs).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def current_mesh() -> Mesh:
    """The active mesh: the one set by ``use_mesh``, else all devices."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh()
    return _current_mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh) -> Iterator[Mesh]:
    global _current_mesh
    prev = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _current_mesh
    _current_mesh = mesh


def _example_axes(mesh: Mesh):
    """Mesh axes the example dimension shards over: ("dcn", "data") on a
    multi-slice mesh (DP spans slices; the per-slice Gram partials meet in
    one small DCN all-reduce), plain "data" otherwise."""
    if "dcn" in mesh.axis_names:
        return ("dcn", DATA_AXIS)
    return DATA_AXIS


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Shard the leading (example) axis over the data axes; replicate the
    rest."""
    mesh = mesh or current_mesh()
    spec = PartitionSpec(_example_axes(mesh), *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, PartitionSpec())


def n_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or current_mesh()
    n = mesh.shape[DATA_AXIS]
    if "dcn" in mesh.axis_names:
        n *= mesh.shape["dcn"]
    return n
