"""Multi-host / multi-slice runtime.

The reference's distributed substrate is a Spark cluster launched by
``bin/run-pipeline.sh:9-55`` (spark-submit against $SPARK_HOME) and
provisioned by ``bin/keystone-ec2.sh``. The TPU-native equivalent is a
**SPMD process group**: one Python process per host, every process runs
the same program, ``jax.distributed.initialize`` wires them into one
runtime, and XLA collectives ride ICI within a slice and DCN across
slices. There is no driver/executor split — the "driver-side solve"
pattern of the reference becomes a replicated small computation.

Axis layout (the scaling-book recipe):

- ``dcn``   — the slice axis. Only data parallelism crosses it: per-slice
  partial Gram/gradient sums are combined with one small all-reduce over
  DCN, which is latency-tolerant.
- ``data``  — intra-slice example sharding (ICI).
- ``model`` — intra-slice feature/model-block sharding (ICI, bandwidth-
  hungry collectives stay on ICI).

Example pod launch (one command per host, e.g. via ``gcloud compute tpus
tpu-vm ssh --worker=all``)::

    python -m keystone_tpu TimitPipeline --trainLocation gs://... \
        # jax.distributed auto-detects coordinator/process ids on TPU VMs

On TPU VMs ``initialize()`` needs no arguments (cluster metadata supplies
coordinator address / process count). On CPU/GPU clusters pass them
explicitly or via env (COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from keystone_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

logger = logging.getLogger(__name__)

DCN_AXIS = "dcn"

_initialized = False
_cache_dir: Optional[str] = None
_aot_dir: Optional[str] = None


def setup_compilation_cache(
    cache_dir: Optional[str] = None,
    min_compile_time_secs: float = 0.0,
) -> Optional[str]:
    """Wire up JAX's persistent XLA compilation cache (idempotent).

    A restarted server pays ZERO cold compiles for shapes it has seen:
    ``CompiledPipeline.warmup`` replays each bucket's compile from this
    on-disk cache instead of re-running XLA (seconds per program). The
    dir resolves from the argument, ``$KEYSTONE_COMPILE_CACHE``, then
    ``~/.cache/keystone_tpu/xla``. ``min_compile_time_secs=0`` caches
    every program — serving wants even fast compiles persisted, unlike
    one-shot training scripts where tiny entries are churn.

    Returns the cache dir, or None when this jax build lacks the
    persistent-cache config knobs (the call is then a no-op — serving
    still works, restarts just recompile)."""
    global _cache_dir
    if _cache_dir is not None:
        return _cache_dir
    cache_dir = (
        cache_dir
        or os.environ.get("KEYSTONE_COMPILE_CACHE")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "keystone_tpu", "xla"
        )
    )
    prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_time_secs),
        )
    except Exception as e:
        # roll back to the PRE-CALL state so jax config never
        # contradicts the None return (and a cache the user configured
        # themselves isn't silently disabled by our failure)
        try:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
        except Exception:
            pass
        logger.info("persistent compilation cache unavailable: %s", e)
        return None
    try:
        # cache regardless of entry size where the knob exists
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:
        pass
    _cache_dir = cache_dir
    logger.info("persistent compilation cache at %s", cache_dir)
    return cache_dir


def setup_aot_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Configure the AOT serialized-executable store dir (idempotent)
    — the second half of the restart story. The persistent compilation
    cache above removes the XLA *compile* from a restart but the
    process still pays trace + lowering + cache replay per bucket;
    with this store configured, ``CompiledPipeline.warmup``
    deserializes each bucket's whole executable
    (``serving/aot.py``) and a fresh replica goes from exec() to
    serving without tracing anything. The dir resolves from the
    argument, ``$KEYSTONE_AOT_CACHE``, then
    ``~/.cache/keystone_tpu/aot``.

    Returns the store dir, or None when it can't be created (the call
    is then a no-op — serving works, cold starts just compile)."""
    global _aot_dir
    if _aot_dir is not None:
        return _aot_dir
    cache_dir = (
        cache_dir
        or os.environ.get("KEYSTONE_AOT_CACHE")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "keystone_tpu", "aot"
        )
    )
    try:
        # 0700: the store dir is a trust boundary (entries are pickled
        # executables — write access there is code execution in the
        # server; serving/aot.py documents the contract). Pre-existing
        # dirs keep the operator's chosen mode.
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    except OSError as e:
        logger.info("AOT executable cache unavailable: %s", e)
        return None
    _aot_dir = cache_dir
    logger.info("AOT executable cache at %s", cache_dir)
    return cache_dir


def aot_cache_dir() -> Optional[str]:
    """The configured AOT store dir (None until ``setup_aot_cache``)."""
    return _aot_dir


def _looks_like_pod() -> bool:
    """Whether this host appears to be one of several in a TPU pod /
    multislice deployment — the situation where silently falling back to
    single-host mode would make every host train its own model."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hosts:
        return True
    addrs = os.environ.get("TPU_PROCESS_ADDRESSES", "")
    if "," in addrs:
        return True
    try:
        if int(os.environ.get("MEGASCALE_NUM_SLICES", "1")) > 1:
            return True
    except ValueError:
        pass
    return False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join this process to the multi-host runtime (idempotent).

    Wraps ``jax.distributed.initialize``. On Cloud TPU the three
    arguments are auto-detected from instance metadata; elsewhere they
    come from the arguments or the COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID environment variables (the launch script sets these, the
    way run-pipeline.sh exported SPARK_HOME/KEYSTONE_MEM).

    Failure contract: a PARTIAL explicit config (some of the three set,
    the rest missing) raises ``ValueError`` naming what's missing; a
    complete explicit config that fails to connect raises; with no
    explicit config, auto-detect failure degrades to single-host ONLY
    when the host doesn't look like part of a pod — on a configured pod
    (worker-hostnames/multislice env present) it raises instead of
    letting every host silently train its own model.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    explicit = {
        "COORDINATOR_ADDRESS": coordinator_address,
        "NUM_PROCESSES": num_processes,
        "PROCESS_ID": process_id,
    }
    given = [k for k, v in explicit.items() if v is not None]
    missing = [k for k, v in explicit.items() if v is None]
    if given and missing:
        raise ValueError(
            "partial multi-host config: "
            f"{'/'.join(given)} set but {'/'.join(missing)} missing — "
            "set all three of COORDINATOR_ADDRESS / NUM_PROCESSES / "
            "PROCESS_ID (env or arguments), or none of them for "
            "single-host / TPU-VM auto-detect"
        )
    if not given:
        # single-process (or TPU-VM auto-detect) path
        try:
            jax.distributed.initialize()
        except Exception as e:
            if _looks_like_pod():
                raise RuntimeError(
                    "this host looks like part of a multi-host pod "
                    "(TPU_WORKER_HOSTNAMES / TPU_PROCESS_ADDRESSES / "
                    "MEGASCALE_NUM_SLICES env) but "
                    "jax.distributed.initialize() failed — refusing to "
                    "fall back to single-host mode, which would train a "
                    "separate model per host"
                ) from e
            logger.info("jax.distributed not initialized (%s); single host", e)
            _initialized = True
            return
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    _initialized = True
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def multislice_shape(
    n_devices: int,
    n_slices: Optional[int] = None,
    n_model: int = 1,
) -> Tuple[int, int, int]:
    """Resolve the (dcn, data, model) mesh shape for ``n_devices``.

    ``n_slices`` defaults to the number of distinct slices the platform
    reports (1 when undetectable). ``n_model`` divides the per-slice
    device count; the remainder is the intra-slice data axis.
    """
    if n_slices is None:
        n_slices = _detect_num_slices()
    if n_devices % n_slices:
        raise ValueError(
            f"{n_devices} devices not divisible into {n_slices} slices"
        )
    per_slice = n_devices // n_slices
    if per_slice % n_model:
        raise ValueError(
            f"per-slice device count {per_slice} not divisible by "
            f"model axis {n_model}"
        )
    return n_slices, per_slice // n_model, n_model


def _detect_num_slices(devices: Optional[Sequence[jax.Device]] = None) -> int:
    devs = list(devices) if devices is not None else jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devs}
    return max(len(slice_ids), 1)


def make_multislice_mesh(
    n_slices: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dcn, data, model) mesh.

    Devices are grouped so that the ``dcn`` axis exactly follows slice
    boundaries (each mesh row is one slice's devices) — DCN-crossing
    collectives then appear only on the ``dcn`` axis. Solvers that psum
    over the example axis shard data over ``("dcn", "data")`` jointly
    (mesh.data_sharding handles this), which XLA lowers to an
    ICI reduce(-scatter) per slice plus one small DCN all-reduce of the
    (b, b)-shaped partials — the treeReduce topology of the reference
    (MLMatrixUtils.treeReduce) realized in hardware.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n_slices_, n_data, n_model_ = multislice_shape(
        len(devs), n_slices if n_slices is not None
        else _detect_num_slices(devs),
        n_model,
    )
    # stable grouping: sort by (slice, process, id) so each dcn row is one
    # physical slice when slice metadata exists
    devs.sort(
        key=lambda d: (
            getattr(d, "slice_index", 0),
            getattr(d, "process_index", 0),
            d.id,
        )
    )
    arr = np.array(devs).reshape(n_slices_, n_data, n_model_)
    return Mesh(arr, (DCN_AXIS, DATA_AXIS, MODEL_AXIS))
