"""``Dataset`` — the framework's N-example collection type (the RDD stand-in).

Three physical modes:

- **array mode**: a pytree of arrays (usually one matrix) with a leading
  example axis, optionally zero-padded to a multiple of the mesh's data-shard
  count and placed with a ``NamedSharding`` on the data axis. This is the fast
  path: transformers become batched jnp ops, solvers see one sharded matrix,
  XLA inserts the collectives.
- **items mode**: a host-side list of per-example Python objects (ragged
  arrays, images of varying size, token lists). This replaces RDDs of
  non-uniform records; operators map over it on host and convert to array
  mode as soon as shapes become uniform.
- **host-blocks mode**: a feature matrix column-blocked into HOST-RAM
  numpy arrays (each (padded_n, w_i), C-contiguous). This is the
  out-of-aggregate-HBM training substrate: the reference caches features
  in cluster RAM and streams them block-by-block through the block
  solvers (BlockLinearMapper.scala:50-73 iterates per-block feature
  RDDs; AutoCacheRule.scala:559-602 budgets 75% of cluster memory for
  the cache). Here host RAM is the cache tier and the BCD solvers
  double-buffer each slab onto the chip per pass — a fit's feature
  footprint is bounded by host RAM, not HBM. Blocks mirror the
  reference's Seq[RDD] layout, so slabs transfer without a strided-copy
  repack.

Padding discipline: ``n`` is the valid example count; rows past ``n`` are
zeros. Reductions that care divide by ``n`` or use ``mask()``; zero rows
contribute nothing to Gram matrices / sums, so linear solvers are exact
without explicit masking.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as mesh_lib


def _leading_dim(tree: Any) -> int:
    # a BCOO (or any array-like) IS the array — don't descend into its
    # pytree leaves (a BCOO's first leaf is the nse-length values array)
    if hasattr(tree, "shape"):
        return tree.shape[0]
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("empty pytree")
    return leaves[0].shape[0]


class Dataset:
    def __init__(
        self,
        *,
        arrays: Any = None,
        items: Optional[List[Any]] = None,
        host_blocks: Optional[List[np.ndarray]] = None,
        n: Optional[int] = None,
    ):
        modes = sum(x is not None for x in (arrays, items, host_blocks))
        if modes != 1:
            raise ValueError(
                "exactly one of arrays/items/host_blocks required"
            )
        self._arrays = arrays
        self._items = items
        self._host_blocks = host_blocks
        if arrays is not None:
            self._n = int(n) if n is not None else _leading_dim(arrays)
        elif host_blocks is not None:
            if not host_blocks:
                raise ValueError("host_blocks must be non-empty")
            rows = {b.shape[0] for b in host_blocks}
            if len(rows) != 1:
                raise ValueError(
                    f"host blocks disagree on row count: {sorted(rows)}"
                )
            self._n = int(n) if n is not None else host_blocks[0].shape[0]
        else:
            self._n = len(items)
        self._cached = False

    # -- constructors ------------------------------------------------------

    @staticmethod
    def of(data: Any) -> "Dataset":
        """Lift a list/array into a Dataset (lists -> items mode unless all
        leaves are uniform arrays, arrays -> array mode)."""
        if isinstance(data, Dataset):
            return data
        if isinstance(data, (list, tuple)):
            return Dataset(items=list(data))
        return Dataset(arrays=jnp.asarray(data))

    @staticmethod
    def from_array(arrays: Any, n: Optional[int] = None) -> "Dataset":
        return Dataset(arrays=arrays, n=n)

    @staticmethod
    def from_items(items: Sequence[Any]) -> "Dataset":
        return Dataset(items=list(items))

    @staticmethod
    def from_host_blocks(
        blocks: Sequence[np.ndarray], n: Optional[int] = None
    ) -> "Dataset":
        """Column-blocked feature matrix resident in host RAM (the
        cluster-RAM feature cache of BlockLinearMapper.scala:50-73).
        Each block is (padded_n, w_i); solvers stream one slab to the
        device at a time, so the fit is bounded by host RAM, not HBM.
        Blocks are made C-contiguous here (one-time cost) so every
        later ``device_put`` is a straight memcpy, never a strided
        repack inside the transfer path."""
        return Dataset(
            host_blocks=[np.ascontiguousarray(b) for b in blocks], n=n
        )

    @staticmethod
    def from_host_array(
        arr: np.ndarray, block_size: int, n: Optional[int] = None
    ) -> "Dataset":
        """Split one host matrix into contiguous column blocks (test /
        convenience path; production featurizers emit blocks directly)."""
        blocks = [
            arr[:, s : s + block_size]
            for s in range(0, arr.shape[1], block_size)
        ]
        return Dataset.from_host_blocks(blocks, n=n)

    @staticmethod
    def host_blocks_from_batches(
        batches, block_size: int, n: Optional[int] = None
    ) -> "Dataset":
        """Accumulate ROW batches of features (a featurize stream's
        output — e.g. ``featurize(chunk)`` per loader batch) into
        host-RAM COLUMN blocks: the glue between the out-of-core input
        pipeline and the out-of-aggregate-HBM solvers, covering the
        reference's featurize→cache-in-cluster-RAM→solve flow
        (ImageNetSiftLcsFV.scala:106-142) without the features ever
        being resident in HBM or as one host matrix.

        ``batches`` yields (rows_i, D) arrays (device or host; device
        batches are pulled to host here — on the producer side keep the
        featurize chunk loop async and let this pull be the sync
        point). Peak host memory is the features plus one column-block
        copy (the per-block row chunks are freed as each block is
        assembled)."""
        per_block: List[List[np.ndarray]] = []
        total = 0
        width: Optional[int] = None
        for batch in batches:
            host = np.asarray(batch)
            total += host.shape[0]
            d = host.shape[1]
            if width is None:
                if d == 0:
                    raise ValueError("zero-width feature batch")
                width = d
                per_block = [
                    [] for _ in range(-(-d // block_size))
                ]
            elif d != width:
                raise ValueError(
                    f"feature width changed mid-stream: {d} vs {width}"
                )
            for bi in range(len(per_block)):
                s = bi * block_size
                # slice views; the final per-block concatenate makes
                # the contiguous copy exactly once
                per_block[bi].append(host[:, s : s + block_size])
        if width is None:
            raise ValueError("empty feature stream")
        blocks = []
        for bi in range(len(per_block)):
            blocks.append(np.concatenate(per_block[bi], axis=0))
            per_block[bi] = []  # free the row chunks as we go
        return Dataset.from_host_blocks(blocks, n=n if n is not None else total)

    # -- inspection --------------------------------------------------------

    @property
    def n(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    @property
    def is_array(self) -> bool:
        return self._arrays is not None

    @property
    def is_host(self) -> bool:
        return self._host_blocks is not None

    @property
    def host_blocks(self) -> List[np.ndarray]:
        if self._host_blocks is None:
            raise ValueError("not a host-blocks dataset")
        return self._host_blocks

    @property
    def block_widths(self) -> List[int]:
        return [b.shape[1] for b in self.host_blocks]

    @property
    def padded_n(self) -> int:
        if self.is_array:
            return _leading_dim(self._arrays)
        if self.is_host:
            return self._host_blocks[0].shape[0]
        return self._n

    # -- views -------------------------------------------------------------

    def padded(self) -> Any:
        """Arrays with the (possibly padded) leading axis — the solver view."""
        return self.to_array_mode()._arrays

    def array(self) -> Any:
        """Arrays sliced to exactly ``n`` valid rows (unsharded host view)."""
        arrs = self.to_array_mode()._arrays
        if _leading_dim(arrs) == self._n:
            return arrs
        return jax.tree_util.tree_map(lambda a: a[: self._n], arrs)

    def mask(self) -> jnp.ndarray:
        """(padded_n,) float32 validity mask (cached: solvers ask for it
        on every fit, and each eager arange/compare dispatch costs real
        latency on a remote-tunnel device)."""
        m = getattr(self, "_mask", None)
        if m is None:
            pn = self.padded_n
            m = (jnp.arange(pn) < self._n).astype(jnp.float32)
            self._mask = m
        return m

    def items(self) -> List[Any]:
        if self._items is not None:
            return self._items
        arrs = self.array()
        host = jax.tree_util.tree_map(np.asarray, arrs)
        return [
            jax.tree_util.tree_map(lambda a, i=i: a[i], host)
            for i in range(self._n)
        ]

    def __iter__(self):
        return iter(self.items())

    def first(self) -> Any:
        if self._items is not None:
            return self._items[0]
        return jax.tree_util.tree_map(lambda a: a[0], self.array())

    def take(self, k: int) -> List[Any]:
        return self.items()[:k]

    # -- conversions -------------------------------------------------------

    def to_array_mode(self) -> "Dataset":
        if self.is_array:
            return self
        if self.is_host:
            # materializes the WHOLE feature matrix in HBM — the thing
            # host-blocks mode exists to avoid; legitimate only for
            # small datasets (tests, cross-checks)
            full = jnp.concatenate(
                [jnp.asarray(b) for b in self._host_blocks], axis=1
            )
            return Dataset(arrays=full, n=self._n)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *self._items
        )
        return Dataset(arrays=stacked, n=self._n)

    # -- transforms (eager; graph-level laziness lives in Expressions) -----

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Per-example host map (items mode result)."""
        return Dataset(items=[fn(x) for x in self.items()])

    def map_arrays(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Whole-batch array transform; ``fn`` must preserve the leading axis
        and map zero pad rows to values safe to keep as padding."""
        return Dataset(arrays=fn(self.padded()), n=self._n)

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        out: List[Any] = []
        for x in self.items():
            out.extend(fn(x))
        return Dataset(items=out)

    def filter(self, pred: Callable[[Any], bool]) -> "Dataset":
        return Dataset(items=[x for x in self.items() if pred(x)])

    def zip(self, other: "Dataset") -> "Dataset":
        if self.n != other.n:
            raise ValueError(f"zip length mismatch: {self.n} vs {other.n}")
        if self.is_array and other.is_array:
            pn = max(self.padded_n, other.padded_n)
            a = self._pad_to(pn)._arrays
            b = other._pad_to(pn)._arrays
            return Dataset(arrays=(a, b), n=self.n)
        return Dataset(
            items=list(zip(self.items(), other.items()))
        )

    def _pad_to(self, pn: int) -> "Dataset":
        arrs = self.to_array_mode()._arrays
        cur = _leading_dim(arrs)
        if cur == pn:
            return self.to_array_mode()
        if cur > pn:
            raise ValueError("cannot shrink padding")
        pad = pn - cur
        padded = jax.tree_util.tree_map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
            ),
            arrs,
        )
        return Dataset(arrays=padded, n=self._n)

    # -- placement ---------------------------------------------------------

    def shard(self, mesh=None) -> "Dataset":
        """Pad to a multiple of the data-shard count and place the leading
        axis over the mesh's data axis."""
        mesh = mesh or mesh_lib.current_mesh()
        nshards = mesh.shape[mesh_lib.DATA_AXIS]
        ds = self.to_array_mode()
        pn = -(-ds.padded_n // nshards) * nshards
        ds = ds._pad_to(pn)
        sharded = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                a, mesh_lib.data_sharding(mesh, ndim=a.ndim)
            ),
            ds._arrays,
        )
        return Dataset(arrays=sharded, n=self._n)

    def cache(self) -> "Dataset":
        """Materialize device buffers now (reference: Cacher / rdd.cache)."""
        if self.is_array:
            jax.block_until_ready(self._arrays)
        self._cached = True
        return self

    @property
    def is_cached(self) -> bool:
        return self._cached

    def __repr__(self) -> str:
        if self.is_host:
            return (
                f"Dataset(host_blocks, n={self._n}, "
                f"widths={self.block_widths})"
            )
        if self.is_array:
            shapes = jax.tree_util.tree_map(
                lambda a: tuple(a.shape), self._arrays
            )
            return f"Dataset(array, n={self._n}, shapes={shapes})"
        return f"Dataset(items, n={self._n})"
