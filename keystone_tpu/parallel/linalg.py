"""Sharded linear algebra — the mlmatrix replacement.

Reference call surface (SURVEY.md §2.9.3): edu.berkeley.cs.amplab.mlmatrix
{TSQR, NormalEquations, BlockCoordinateDescent, QRUtils, treeReduce} used by
nodes/learning/{DistributedPCA.scala:20, LBFGS.scala:5,
BlockLinearMapper.scala:4}. Here the same capabilities are sharded-JAX:

- ``tsqr_r``: tree-structured QR of a row-sharded (n, d) matrix. Each data
  shard QRs locally (shard_map), the (d, d) R factors are all-gathered and
  reduced by one final QR — the reference's treeReduce combine collapses to
  one ICI all-gather because d is small.
- ``gram``: AᵀA with f32 accumulation (the NormalEquations building block);
  under jit the contraction over the sharded row axis becomes per-shard MXU
  matmuls + a psum over the "data" axis.
- Block coordinate descent lives in ops/learning/block_ls.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as mesh_lib


@jax.jit
def gram(A):
    """AᵀA with f32 accumulation. f32 inputs force HIGHEST precision:
    TPU's DEFAULT truncates f32 matmul operands to bf16 passes (see
    ops/learning/block_ls._f32_mm for the measured failure)."""
    hp = (
        jax.lax.Precision.HIGHEST
        if A.dtype == jnp.float32
        else None
    )
    return jax.lax.dot_general(
        A.T, A, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hp,
    )


def tsqr_r(A, mesh=None):
    """R factor of a thin QR of a row-sharded (n, d) matrix, n >> d.

    Reference: mlmatrix TSQR().qrR (DistributedPCA.scala:47) — per-partition
    local QR + tree combine. Sign convention: R has non-negative diagonal so
    the result is deterministic across shard counts.
    """
    mesh = mesh or mesh_lib.current_mesh()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P(mesh_lib.DATA_AXIS, None),
        out_specs=P(mesh_lib.DATA_AXIS, None),
    )
    def local_qr(block):
        r = jnp.linalg.qr(block, mode="r")
        return _fix_sign(r)

    rs = local_qr(A)  # (nshards * d, d) — stacked local R factors
    r = jnp.linalg.qr(rs, mode="r")
    return _fix_sign(r)


def _fix_sign(r):
    s = jnp.sign(jnp.diagonal(r))
    s = jnp.where(s == 0, 1.0, s)
    return r * s[:, None]


def qr_q(A, mesh=None):
    """Explicit thin Q of a row-sharded matrix: Q = A R⁻¹ (CholeskyQR-style
    using the TSQR R, stable because R comes from orthogonal reductions)."""
    r = tsqr_r(A, mesh)
    return jax.scipy.linalg.solve_triangular(
        r.T, A.T, lower=True
    ).T, r
