"""Distributed substrate: device mesh, sharding helpers, collectives, Dataset.

This layer replaces the reference's Spark runtime (RDDs, broadcast, shuffle,
treeReduce — SURVEY.md §2.10) with JAX-native equivalents: a
``jax.sharding.Mesh`` over TPU chips, ``NamedSharding`` annotations that let
XLA insert ICI/DCN collectives, and a ``Dataset`` container whose leading
example axis is sharded over the mesh's data axis.
"""

from keystone_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    current_mesh,
    make_mesh,
    use_mesh,
)
from keystone_tpu.parallel.dataset import Dataset  # noqa: F401
