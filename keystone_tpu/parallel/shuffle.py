"""Device-side shuffle / repartition via ``lax.all_to_all`` under shard_map.

Reference: the Spark shuffle behind ``Shuffler`` (nodes/util/Shuffler.scala,
repartition) and the HashPartitioner ``groupBy`` the per-class solvers used
(BlockWeightedLeastSquaresEstimator.scala groupByClasses). On TPU a shuffle
is not a runtime service but ONE collective: each shard packs its rows into
fixed-capacity per-destination buckets, a single ``lax.all_to_all`` rides
the ICI, and receivers unpack. Static shapes require the MoE router's
capacity-factor discipline — per-(src, dst) buckets have a fixed capacity,
overflow rows are dropped and *counted* (callers size capacity so the count
is provably zero; `device_shuffle`'s slot-exact routing needs no slack).

Memory: the packed buffer is ``(n_shards, capacity, ...)`` per shard, so
capacity should be ~rows_per_shard / n_shards for balanced exchanges (or
rows_per_shard for worst-case-skew guarantees).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from keystone_tpu.parallel import mesh as mesh_lib


def _pack_buckets(payload, dest, n_shards: int, capacity: int):
    """Pack rows into per-destination buckets on one shard.

    ``payload`` is a tuple of arrays sharing their leading dim; ``dest`` is
    an int32 row destination in ``[0, n_shards)`` — or ``>= n_shards`` to
    discard the row (pad rows). Returns bucket tree ``(n_shards, capacity,
    ...)``, validity mask ``(n_shards, capacity)``, and the number of
    non-discarded rows that overflowed their bucket.
    """
    m = dest.shape[0]
    sentinel = n_shards
    d = jnp.where(dest < n_shards, dest, sentinel).astype(jnp.int32)
    counts = jax.ops.segment_sum(
        jnp.ones((m,), jnp.int32), d, num_segments=n_shards + 1
    )
    offsets = jnp.cumsum(counts) - counts  # (n_shards + 1,)
    order = jnp.argsort(d, stable=True)
    ds = d[order]
    pos = jnp.arange(m, dtype=jnp.int32) - offsets[ds]
    keep = (ds < n_shards) & (pos < capacity)
    row_idx = jnp.where(keep, ds, n_shards)  # OOB => dropped by scatter
    slot = jnp.where(keep, pos, capacity)

    def pack(x):
        xs = jnp.take(x, order, axis=0)
        buf = jnp.zeros((n_shards, capacity) + x.shape[1:], x.dtype)
        return buf.at[row_idx, slot].set(xs, mode="drop")

    buckets = jax.tree_util.tree_map(pack, payload)
    valid = jnp.zeros((n_shards, capacity), jnp.int32)
    valid = valid.at[row_idx, slot].set(1, mode="drop")
    overflowed = jnp.sum(counts[:n_shards]) - jnp.sum(valid)
    return buckets, valid, overflowed


def all_to_all_repartition(
    payload,
    dest: jnp.ndarray,
    capacity: int,
    mesh=None,
) -> Tuple[tuple, jnp.ndarray, jnp.ndarray]:
    """Route rows of a data-sharded array (tree) to the shard named per-row.

    ``payload``: tuple of arrays with a common sharded leading (example)
    axis. ``dest``: per-row destination shard id (>= n_shards discards the
    row). Each shard returns ``(n_shards * capacity, ...)`` received rows
    (source-major), an int32 validity mask, and the global overflow count
    (replicated scalar) — ``0`` when ``capacity`` was sufficient.
    """
    mesh = mesh or mesh_lib.current_mesh()
    axes = mesh_lib._example_axes(mesh)
    n_shards = mesh_lib.n_data_shards(mesh)

    row_spec = lambda x: P(axes, *([None] * (x.ndim - 1)))
    in_specs = (
        jax.tree_util.tree_map(row_spec, payload),
        P(axes),
    )
    out_specs = (
        jax.tree_util.tree_map(row_spec, payload),
        P(axes),
        P(),
    )

    @partial(
        jax.shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    def exchange(local_payload, local_dest):
        buckets, valid, over = _pack_buckets(
            local_payload, local_dest, n_shards, capacity
        )
        swap = lambda b: jax.lax.all_to_all(
            b, axes, split_axis=0, concat_axis=0, tiled=True
        )
        recv = jax.tree_util.tree_map(swap, buckets)
        recv_valid = swap(valid)
        total_over = jax.lax.psum(over, axes)
        flat = jax.tree_util.tree_map(
            lambda b: b.reshape((n_shards * capacity,) + b.shape[2:]), recv
        )
        return flat, recv_valid.reshape(-1), total_over[None]

    out, valid, over = exchange(payload, dest.astype(jnp.int32))
    return out, valid, over[0]


def repartition_by_key(
    payload, keys: jnp.ndarray, capacity: int, mesh=None
):
    """Hash-partition rows onto shards by ``key % n_shards`` — the
    HashPartitioner ``groupBy`` analogue (negative keys discard)."""
    mesh = mesh or mesh_lib.current_mesh()
    n_shards = mesh_lib.n_data_shards(mesh)
    dest = jnp.where(keys >= 0, keys % n_shards, n_shards)
    return all_to_all_repartition(payload, dest, capacity, mesh)


def device_shuffle(
    x: jnp.ndarray,
    n: int,
    seed: int = 0,
    mesh=None,
) -> jnp.ndarray:
    """Exact random permutation of the first ``n`` (valid) rows of a padded
    row-sharded array, entirely on device: ``out[j] = x[perm[j]]`` with
    ``perm = default_rng(seed).permutation(n)`` — bit-identical to the
    host-side ``Shuffler`` path. Every row is routed to its permuted global
    slot (destination shard + local slot payload) in ONE all_to_all; pad
    rows stay zero.
    """
    mesh = mesh or mesh_lib.current_mesh()
    n_shards = mesh_lib.n_data_shards(mesh)
    n_pad = x.shape[0]
    if n_pad % n_shards:
        raise ValueError(f"padded rows {n_pad} not divisible by {n_shards}")
    rows_per_shard = n_pad // n_shards

    perm = np.random.default_rng(seed).permutation(n)
    inv = np.argsort(perm)  # row g lands at out slot inv[g]
    target = np.full((n_pad,), n_pad, np.int32)  # pad rows -> discard
    target[:n] = inv
    dest_h = np.where(target < n_pad, target // rows_per_shard, n_shards)

    # The permutation is known host-side, so size the per-(src, dst)
    # buckets at their exact max occupancy (~rows_per_shard / n_shards
    # for a random perm) — never rows_per_shard, which would materialize
    # a global-size buffer on every shard and defeat the sharding.
    src = np.arange(n_pad) // rows_per_shard
    pair_counts = np.zeros((n_shards, n_shards + 1), np.int64)
    np.add.at(pair_counts, (src, dest_h), 1)
    capacity = max(int(pair_counts[:, :n_shards].max()), 1)

    dest = jnp.asarray(dest_h.astype(np.int32))
    slot = jnp.asarray((target % rows_per_shard).astype(np.int32))
    (rows, slots), valid, over = all_to_all_repartition(
        (x, slot), dest, capacity, mesh
    )

    axes = mesh_lib._example_axes(mesh)
    row_spec = P(axes, *([None] * (x.ndim - 1)))

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(row_spec, P(axes), P(axes)),
        out_specs=row_spec,
        check_vma=False,
    )
    def place(rows, slots, valid):
        idx = jnp.where(valid > 0, slots, rows_per_shard)  # OOB => drop
        out = jnp.zeros((rows_per_shard,) + rows.shape[1:], rows.dtype)
        return out.at[idx].set(rows, mode="drop")

    out = place(rows, slots, valid)
    # Capacity above is exact only under contiguous block sharding of the
    # example axis; if that assumption is ever violated, fail loudly
    # instead of silently zeroing dropped rows. The scalar sync happens
    # AFTER place() is dispatched, so it doesn't stall the async stream
    # mid-pipeline (~100 ms per host sync through the remote tunnel).
    over_count = int(over)
    if over_count:
        raise RuntimeError(
            f"device_shuffle dropped {over_count} rows: the input's example"
            " axis is not contiguously block-sharded over the mesh"
        )
    return out
