"""Distributed full-batch L-BFGS with L2 regularization.

Reference: nodes/learning/LBFGS.scala — per-partition gradients over
partition-stacked matrices, treeReduce sum, Breeze LBFGS driver on the
master; nodes/learning/Gradient.scala for the least-squares gradients.

TPU-native split: the O(n·d·k) value-and-gradient is ONE jitted program
over the sharded feature matrix (per-shard MXU matmuls + psum over "data"
— the treeReduce); the O(m·d·k) two-loop L-BFGS direction update and
backtracking line search run on host in f64 (the Breeze-driver
equivalent), keeping the history in host memory instead of HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.ops.learning.cost import CostModel
from keystone_tpu.ops.learning.linear import LinearMapper, SparseLinearMapper
from keystone_tpu.ops.stats.nodes import StandardScaler
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


class Gradient:
    """loss(W; A, b) total + gradient over a batch (reference:
    nodes/learning/Gradient.scala:10)."""

    def value_and_grad(self, A, b, W) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError


class LeastSquaresDenseGradient(Gradient):
    """0.5·‖AW − b‖² summed over examples; grad = Aᵀ(AW − b)
    (reference: Gradient.scala:29)."""

    def value_and_grad(self, A, b, W):
        res = (
            jax.lax.dot_general(
                A, W, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            - b
        )
        loss = 0.5 * jnp.sum(res * res)
        grad = jax.lax.dot_general(
            A.T, res, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return loss, grad


class LeastSquaresSparseGradient(Gradient):
    """Same objective with a BCOO feature matrix (reference:
    Gradient.scala:58 — hand-rolled sparse loops; here BCOO dot_generals
    that XLA lowers to gather/scatter kernels)."""

    def value_and_grad(self, A, b, W):
        res = jsparse.bcoo_dot_general(
            A, W, dimension_numbers=(([1], [0]), ([], []))
        ) - b
        loss = 0.5 * jnp.sum(res * res)
        grad = jsparse.bcoo_dot_general(
            A, res, dimension_numbers=(([0], [0]), ([], []))
        )
        return loss, grad


def run_lbfgs(
    value_and_grad: Callable[[np.ndarray], Tuple[float, np.ndarray]],
    w0: np.ndarray,
    num_iterations: int,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
) -> np.ndarray:
    """Two-loop-recursion L-BFGS with Armijo backtracking, host f64
    (the Breeze LBFGS driver stand-in, LBFGS.scala:135)."""
    w = w0.astype(np.float64).ravel()
    f, g = value_and_grad(w)
    s_hist: list = []
    y_hist: list = []
    for _ in range(num_iterations):
        # two-loop recursion
        q = g.copy()
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / (y @ s)
            a = rho * (s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if y_hist:
            y = y_hist[-1]
            s = s_hist[-1]
            q *= (s @ y) / (y @ y)
        for a, rho, s, y in reversed(alphas):
            b = rho * (y @ q)
            q += (a - b) * s
        direction = -q
        # backtracking Armijo line search
        step = 1.0
        dg = direction @ g
        if dg >= 0:  # not a descent direction; reset
            direction = -g
            dg = -(g @ g)
        f_new, g_new, w_new = f, g, w
        for _ in range(30):
            w_try = w + step * direction
            f_try, g_try = value_and_grad(w_try)
            if f_try <= f + 1e-4 * step * dg:
                f_new, g_new, w_new = f_try, g_try, w_try
                break
            step *= 0.5
        else:
            break  # line search failed
        s_vec = w_new - w
        y_vec = g_new - g
        if s_vec @ y_vec > 1e-10:
            s_hist.append(s_vec)
            y_hist.append(y_vec)
            if len(s_hist) > num_corrections:
                s_hist.pop(0)
                y_hist.pop(0)
        improvement = abs(f - f_new) / max(abs(f), abs(f_new), 1.0)
        w, f, g = w_new, f_new, g_new
        if improvement < convergence_tol:
            break
    return w


@dataclasses.dataclass(eq=False)
class LBFGSwithL2(LabelEstimator, CostModel):
    """min_W (1/n)·Σ loss(W; a_i, b_i) + 0.5·λ‖W‖²
    (reference: LBFGS.scala:14). ``fit_intercept`` mean-centers via
    StandardScaler like the reference (:150-166)."""

    gradient: Gradient = dataclasses.field(
        default_factory=LeastSquaresDenseGradient
    )
    fit_intercept: bool = True
    num_corrections: int = 10
    convergence_tol: float = 1e-4
    num_iterations: int = 20
    reg_param: float = 0.0
    sparse: bool = False

    def fit(self, data: Dataset, labels: Dataset):
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        A = data.padded()
        b = labels.padded()
        is_sparse = isinstance(A, jsparse.BCOO)
        d = A.shape[1]
        k = b.shape[1]
        n = data.n

        feat_scaler = label_scaler = None
        if self.fit_intercept and not is_sparse:
            feat_scaler = StandardScaler(normalize_std_dev=False).fit(data)
            label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
            data = feat_scaler.apply_batch(data)
            labels = label_scaler.apply_batch(labels)
            A = data.padded()
            b = labels.padded()

        grad_fn = self.gradient

        @jax.jit
        def device_vg(A, b, W):
            loss, g = grad_fn.value_and_grad(A, b, W)
            return (
                loss / n + 0.5 * self.reg_param * jnp.sum(W * W),
                g / n + self.reg_param * W,
            )

        def vg(w_flat: np.ndarray):
            W = jnp.asarray(
                w_flat.reshape(d, k).astype(np.float32)
            )
            loss, g = device_vg(A, b, W)
            return float(loss), np.asarray(g, np.float64).ravel()

        w = run_lbfgs(
            vg,
            np.zeros((d, k)),
            self.num_iterations,
            self.num_corrections,
            self.convergence_tol,
        )
        W = jnp.asarray(w.reshape(d, k).astype(np.float32))
        if is_sparse:
            return SparseLinearMapper(W)
        if self.fit_intercept:
            # reference: LinearMapper(model, Some(labelScaler.mean),
            # Some(featureScaler)) — center input, add back label mean
            return LinearMapper(
                W, intercept=label_scaler.mean, feature_scaler=feat_scaler
            )
        return LinearMapper(W)

    @property
    def weight(self) -> int:
        # reference: LBFGS.scala weight = numIterations + 1
        return self.num_iterations + 1


@dataclasses.dataclass(eq=False)
class DenseLBFGSwithL2(LBFGSwithL2):
    """Dense-gradient variant (reference: LBFGS.scala:135); cost model from
    :175-191."""

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        flops = n * float(d) * k / num_machines
        bytes_scanned = n * float(d) / num_machines
        network = 2.0 * d * k * max(np.log2(num_machines), 1.0)
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


@dataclasses.dataclass(eq=False)
class SparseLBFGSwithL2(LBFGSwithL2):
    """Sparse-gradient variant (reference: LBFGS.scala:208); cost model
    from :264-280 (sparseOverhead ~ 3x the dense per-element cost)."""

    sparse_overhead: float = 3.0

    def __post_init__(self):
        self.gradient = LeastSquaresSparseGradient()
        self.fit_intercept = False
        self.sparse = True

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        flops = n * sparsity * float(d) * k / num_machines
        bytes_scanned = n * float(d) * sparsity / num_machines
        network = 2.0 * d * k * max(np.log2(num_machines), 1.0)
        return self.num_iterations * (
            self.sparse_overhead
            * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )
