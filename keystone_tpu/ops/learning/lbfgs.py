"""Distributed full-batch L-BFGS with L2 regularization.

Reference: nodes/learning/LBFGS.scala — per-partition gradients over
partition-stacked matrices, treeReduce sum, Breeze LBFGS driver on the
master; nodes/learning/Gradient.scala for the least-squares gradients.

TPU-native split: the O(n·d·k) value-and-gradient is ONE jitted program
over the sharded feature matrix (per-shard MXU matmuls + psum over "data"
— the treeReduce). Two optimizer drivers: the default fused device
driver (``run_lbfgs_device`` — the ENTIRE optimization, two-loop
recursion + Armijo line search + convergence test, is one
``lax.while_loop`` program with zero host syncs), and the f64 host
driver (``run_lbfgs``, the Breeze-driver equivalent) for problems that
need double-precision history.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.ops.learning.cost import CostModel
from keystone_tpu.ops.learning.linear import LinearMapper, SparseLinearMapper
from keystone_tpu.ops.stats.nodes import StandardScaler
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


class Gradient:
    """loss(W; A, b) total + gradient over a batch (reference:
    nodes/learning/Gradient.scala:10).

    Gradients are stateless, so equality/hash are type-based — this makes
    ``regularized_vg`` bound methods from different instances of the same
    gradient class hit the same jit cache entry in the fused driver
    (fresh estimators per fit would otherwise recompile the optimizer).
    """

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def value_and_grad(self, A, b, W) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def regularized_vg(self, W, A, b, reg, n):
        """Mean loss + L2, in the ``vg(W, *data)`` shape the fused device
        driver consumes (bound method: stable jit cache key per gradient
        instance)."""
        loss, g = self.value_and_grad(A, b, W)
        return (
            loss / n + 0.5 * reg * jnp.sum(W * W),
            g / n + reg * W,
        )


class LeastSquaresDenseGradient(Gradient):
    """0.5·‖AW − b‖² summed over examples; grad = Aᵀ(AW − b)
    (reference: Gradient.scala:29)."""

    def value_and_grad(self, A, b, W):
        # HIGHEST for f32 inputs — TPU DEFAULT truncates f32 matmul
        # operands to bf16 (see block_ls._f32_mm); bf16 data keeps the
        # native MXU path
        hp = (
            jax.lax.Precision.HIGHEST
            if A.dtype == jnp.float32
            else None
        )
        res = (
            jax.lax.dot_general(
                A, W, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=hp,
            )
            - b
        )
        loss = 0.5 * jnp.sum(res * res)
        grad = jax.lax.dot_general(
            A.T, res, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=hp,
        )
        return loss, grad


class LeastSquaresSparseGradient(Gradient):
    """Same objective with a BCOO feature matrix (reference:
    Gradient.scala:58 — hand-rolled sparse loops; here BCOO dot_generals
    that XLA lowers to gather/scatter kernels)."""

    def value_and_grad(self, A, b, W):
        res = jsparse.bcoo_dot_general(
            A, W, dimension_numbers=(([1], [0]), ([], []))
        ) - b
        loss = 0.5 * jnp.sum(res * res)
        grad = jsparse.bcoo_dot_general(
            A, res, dimension_numbers=(([0], [0]), ([], []))
        )
        return loss, grad


def run_lbfgs_device(
    device_vg: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
    w0: jnp.ndarray,
    num_iterations: int,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    data: tuple = (),
) -> jnp.ndarray:
    """The ENTIRE L-BFGS optimization as one device program: two-loop
    recursion over a ring-buffered (m, ...) history, Armijo backtracking
    via ``lax.while_loop``, convergence test in-graph. Zero host syncs —
    where the host driver (``run_lbfgs``) pays a dispatch round trip per
    line-search trial, this pays one per *fit*. f32 on device (the host
    driver is the f64 fallback for ill-conditioned problems).

    ``device_vg``: traceable ``(W, *data) -> (loss, grad)`` with ``W``
    in its natural (d, k) shape. It is a STATIC jit argument — pass a
    module-level function or bound method (not a fresh lambda) with the
    arrays in ``data``, or every call re-traces and re-compiles the
    whole nested-loop program (~70 s of XLA compile measured).
    """
    return _lbfgs_device_run(
        device_vg, num_iterations, num_corrections,
        jnp.float32(convergence_tol), jnp.asarray(w0, jnp.float32), *data
    )


@partial(
    jax.jit, static_argnames=("device_vg", "num_iterations", "m")
)
def _lbfgs_device_run(
    device_vg, num_iterations: int, m: int, convergence_tol, w0, *data
):
    shape = w0.shape

    def dot(a, b):
        return jnp.sum(a * b)

    def vg(w):
        return device_vg(w, *data)

    f0, g0 = vg(w0)
    S = jnp.zeros((m,) + shape, jnp.float32)
    Y = jnp.zeros((m,) + shape, jnp.float32)

    def cond(st):
        it, w, f, g, S, Y, count, done = st
        return (it < num_iterations) & ~done

    def body(st):
        it, w, f, g, S, Y, count, done = st
        n_hist = jnp.minimum(count, m)

        # two-loop recursion (ring buffer, newest first)
        def loop1(i, carry):
            q, alphas = carry
            j = (count - 1 - i) % m
            valid = i < n_hist
            s, y = S[j], Y[j]
            rho = 1.0 / jnp.where(valid, dot(y, s), 1.0)
            a = jnp.where(valid, rho * dot(s, q), 0.0)
            return q - a * y, alphas.at[i].set(a)

        q, alphas = jax.lax.fori_loop(
            0, m, loop1, (g, jnp.zeros((m,), jnp.float32))
        )
        jl = (count - 1) % m
        gamma = jnp.where(
            count > 0,
            dot(S[jl], Y[jl]) / jnp.maximum(dot(Y[jl], Y[jl]), 1e-30),
            1.0,
        )
        q = q * gamma

        def loop2(i2, q):
            i = m - 1 - i2
            j = (count - 1 - i) % m
            valid = i < n_hist
            s, y = S[j], Y[j]
            rho = 1.0 / jnp.where(valid, dot(y, s), 1.0)
            b = jnp.where(valid, rho * dot(y, q), 0.0)
            return q + (alphas[i] - b) * s

        q = jax.lax.fori_loop(0, m, loop2, q)

        direction = -q
        dg = dot(direction, g)
        bad = dg >= 0
        direction = jnp.where(bad, -g, direction)
        dg = jnp.where(bad, -dot(g, g), dg)

        # Armijo backtracking: state carries the step to try next
        def ls_cond(ls):
            step, f_t, g_t, w_t, ok, tries = ls
            return ~ok & (tries < 30)

        def ls_body(ls):
            step, _, _, _, _, tries = ls
            w_try = w + step * direction
            f_try, g_try = vg(w_try)
            ok = f_try <= f + 1e-4 * step * dg
            return (
                jnp.where(ok, step, step * 0.5),
                f_try, g_try, w_try, ok, tries + 1,
            )

        _, f_new, g_new, w_new, ok, _ = jax.lax.while_loop(
            ls_cond, ls_body,
            (jnp.float32(1.0), f, g, w, jnp.bool_(False), 0),
        )

        s_vec = w_new - w
        y_vec = g_new - g
        store = ok & (dot(s_vec, y_vec) > 1e-10)
        j = count % m
        S = jnp.where(store, S.at[j].set(s_vec), S)
        Y = jnp.where(store, Y.at[j].set(y_vec), Y)
        count = count + jnp.where(store, 1, 0)

        improvement = jnp.abs(f - f_new) / jnp.maximum(
            jnp.maximum(jnp.abs(f), jnp.abs(f_new)), 1.0
        )
        done = ~ok | (improvement < convergence_tol)
        keep = lambda new, old: jnp.where(ok, new, old)
        return (
            it + 1, keep(w_new, w), keep(f_new, f), keep(g_new, g),
            S, Y, count, done,
        )

    st = (jnp.int32(0), w0, f0, g0, S, Y, jnp.int32(0),
          jnp.bool_(False))
    _, w, _, _, _, _, _, _ = jax.lax.while_loop(cond, body, st)
    return w


def run_lbfgs(
    value_and_grad: Callable[[np.ndarray], Tuple[float, np.ndarray]],
    w0: np.ndarray,
    num_iterations: int,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
) -> np.ndarray:
    """Two-loop-recursion L-BFGS with Armijo backtracking, host f64
    (the Breeze LBFGS driver stand-in, LBFGS.scala:135)."""
    w = w0.astype(np.float64).ravel()
    f, g = value_and_grad(w)
    s_hist: list = []
    y_hist: list = []
    for _ in range(num_iterations):
        # two-loop recursion
        q = g.copy()
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / (y @ s)
            a = rho * (s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if y_hist:
            y = y_hist[-1]
            s = s_hist[-1]
            q *= (s @ y) / (y @ y)
        for a, rho, s, y in reversed(alphas):
            b = rho * (y @ q)
            q += (a - b) * s
        direction = -q
        # backtracking Armijo line search
        step = 1.0
        dg = direction @ g
        if dg >= 0:  # not a descent direction; reset
            direction = -g
            dg = -(g @ g)
        f_new, g_new, w_new = f, g, w
        for _ in range(30):
            w_try = w + step * direction
            f_try, g_try = value_and_grad(w_try)
            if f_try <= f + 1e-4 * step * dg:
                f_new, g_new, w_new = f_try, g_try, w_try
                break
            step *= 0.5
        else:
            break  # line search failed
        s_vec = w_new - w
        y_vec = g_new - g
        if s_vec @ y_vec > 1e-10:
            s_hist.append(s_vec)
            y_hist.append(y_vec)
            if len(s_hist) > num_corrections:
                s_hist.pop(0)
                y_hist.pop(0)
        improvement = abs(f - f_new) / max(abs(f), abs(f_new), 1.0)
        w, f, g = w_new, f_new, g_new
        if improvement < convergence_tol:
            break
    return w


@dataclasses.dataclass(eq=False)
class LBFGSwithL2(LabelEstimator, CostModel):
    """min_W (1/n)·Σ loss(W; a_i, b_i) + 0.5·λ‖W‖²
    (reference: LBFGS.scala:14). ``fit_intercept`` mean-centers via
    StandardScaler like the reference (:150-166)."""

    gradient: Gradient = dataclasses.field(
        default_factory=LeastSquaresDenseGradient
    )
    fit_intercept: bool = True
    num_corrections: int = 10
    convergence_tol: float = 1e-4
    num_iterations: int = 20
    reg_param: float = 0.0
    sparse: bool = False
    driver: str = "device"  # "device": whole optimization fused in one
    # program, zero host syncs (run_lbfgs_device) | "host": f64 Breeze-
    # driver equivalent, one device round trip per line-search trial

    def fit(self, data: Dataset, labels: Dataset):
        if self.driver not in ("device", "host"):
            raise ValueError(f"driver must be 'device' or 'host', got {self.driver!r}")
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        A = data.padded()
        b = labels.padded()
        is_sparse = isinstance(A, jsparse.BCOO)
        d = A.shape[1]
        k = b.shape[1]
        n = data.n

        feat_scaler = label_scaler = None
        if self.fit_intercept and not is_sparse:
            feat_scaler = StandardScaler(normalize_std_dev=False).fit(data)
            label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
            data = feat_scaler.apply_batch(data)
            labels = label_scaler.apply_batch(labels)
            A = data.padded()
            b = labels.padded()

        grad_fn = self.gradient

        @jax.jit
        def device_vg(A, b, W):
            loss, g = grad_fn.value_and_grad(A, b, W)
            return (
                loss / n + 0.5 * self.reg_param * jnp.sum(W * W),
                g / n + self.reg_param * W,
            )

        if self.driver == "device":
            W = run_lbfgs_device(
                self.gradient.regularized_vg,  # bound method: stable key
                jnp.zeros((d, k), jnp.float32),
                self.num_iterations,
                self.num_corrections,
                self.convergence_tol,
                data=(A, b, jnp.float32(self.reg_param), jnp.float32(n)),
            )
        else:
            def vg(w_flat: np.ndarray):
                W = jnp.asarray(
                    w_flat.reshape(d, k).astype(np.float32)
                )
                loss, g = device_vg(A, b, W)
                return float(loss), np.asarray(g, np.float64).ravel()

            w = run_lbfgs(
                vg,
                np.zeros((d, k)),
                self.num_iterations,
                self.num_corrections,
                self.convergence_tol,
            )
            W = jnp.asarray(w.reshape(d, k).astype(np.float32))
        if is_sparse:
            return SparseLinearMapper(W)
        if self.fit_intercept:
            # reference: LinearMapper(model, Some(labelScaler.mean),
            # Some(featureScaler)) — center input, add back label mean
            return LinearMapper(
                W, intercept=label_scaler.mean, feature_scaler=feat_scaler
            )
        return LinearMapper(W)

    @property
    def weight(self) -> int:
        # reference: LBFGS.scala weight = numIterations + 1
        return self.num_iterations + 1


@dataclasses.dataclass(eq=False)
class DenseLBFGSwithL2(LBFGSwithL2):
    """Dense-gradient variant (reference: LBFGS.scala:135); cost model from
    :175-191."""

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        flops = n * float(d) * k / num_machines
        bytes_scanned = n * float(d) / num_machines
        network = 2.0 * d * k * max(np.log2(num_machines), 1.0)
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


@dataclasses.dataclass(eq=False)
class SparseLBFGSwithL2(LBFGSwithL2):
    """Sparse-gradient variant (reference: LBFGS.scala:208); cost model
    from :264-280 (sparseOverhead ~ 3x the dense per-element cost)."""

    sparse_overhead: float = 3.0

    def __post_init__(self):
        self.gradient = LeastSquaresSparseGradient()
        self.fit_intercept = False
        self.sparse = True

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        flops = n * sparsity * float(d) * k / num_machines
        bytes_scanned = n * float(d) * sparsity / num_machines
        network = 2.0 * d * k * max(np.log2(num_machines), 1.0)
        return self.num_iterations * (
            self.sparse_overhead
            * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )
