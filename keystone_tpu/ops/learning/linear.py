"""Dense/sparse linear maps and exact least-squares solvers.

Reference: nodes/learning/LinearMapper.scala (LinearMapper/LinearMapEstimator
— mlmatrix NormalEquations), LocalLeastSquaresEstimator.scala (dual-form OLS
for d >> n), SparseLinearMapper.scala.

TPU-first: the normal-equation Gram matrices are contractions over the
sharded example axis of one device-resident matrix — under jit XLA lowers
them to per-shard MXU matmuls plus a psum over the mesh's data axis, which
is exactly the reference's executor-GEMM + treeReduce pattern with the
driver roundtrip removed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.ops.learning.hostsolve import psd_solve_host
from keystone_tpu.utils.precision import mm
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator, Transformer


@jax.jit
def _grams(A, b):
    # HIGHEST for f32 (TPU DEFAULT truncates operands to bf16 —
    # block_ls._f32_mm); bf16 data keeps the native MXU path
    hp = (
        jax.lax.Precision.HIGHEST
        if A.dtype == jnp.float32
        else None
    )
    return (
        jnp.matmul(A.T, A, precision=hp),
        jnp.matmul(A.T, b, precision=hp),
    )


@dataclasses.dataclass(eq=False)
class LinearMapper(Transformer):
    """x -> x @ W (+ intercept), optionally standard-scaling the input first
    (reference: nodes/learning/LinearMapper.scala:18)."""

    W: Any  # (d, k)
    intercept: Optional[Any] = None  # (k,)
    feature_scaler: Optional[Any] = None  # StandardScalerModel or None

    def apply(self, x):
        if self.feature_scaler is not None:
            x = self.feature_scaler.apply(x)
        out = mm(x, self.W)
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_batch(self, ds: Dataset) -> Dataset:
        if self.feature_scaler is not None:
            ds = self.feature_scaler.apply_batch(ds)
        out = mm(ds.padded(), self.W)
        if self.intercept is not None:
            out = (out + self.intercept) * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class LinearMapEstimator(LabelEstimator):
    """Exact OLS via normal equations with optional L2
    (reference: nodes/learning/LinearMapper.scala:69-116 — mlmatrix
    NormalEquations: solve (AᵀA + λI) W = Aᵀb)."""

    lam: float = 0.0

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = data.padded()
        b = labels.padded()
        gram, rhs = _grams(A, b)
        # f64 host solve of the (d,d) system (reference: driver-side
        # NormalEquations; see hostsolve.py for the precision rationale).
        W = jnp.asarray(psd_solve_host(gram, rhs, self.lam), A.dtype)
        return LinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        """Exact normal-equations cost (reference:
        LinearMapper.scala:100-115)."""
        flops = n * float(d) * (d + k) / num_machines
        bytes_scanned = n * float(d) / num_machines + float(d) * d
        network = float(d) * (d + k)
        return (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )

    @staticmethod
    def compute_cost(
        data: Dataset, labels: Dataset, lam: float, W, intercept=None
    ) -> float:
        """0.5·‖AW − b‖² + 0.5·λ‖W‖² (reference: LinearMapper.computeCost)."""
        A = data.padded()
        b = labels.padded()
        pred = mm(A, W)
        if intercept is not None:
            pred = (pred + intercept) * data.mask()[:, None]
        res = jnp.sum((pred - b) ** 2)
        return float(0.5 * res + 0.5 * lam * jnp.sum(W * W))


@dataclasses.dataclass(eq=False)
class LocalLeastSquaresEstimator(LabelEstimator):
    """Dual-form OLS for d >> n: W = Aᵀ (A Aᵀ + λ n I)⁻¹ b
    (reference: nodes/learning/LocalLeastSquaresEstimator.scala:35 — driver
    local; here one small-n device solve)."""

    lam: float = 0.0

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = data.array()
        b = labels.array()
        n = A.shape[0]
        from keystone_tpu.ops.learning.block_ls import _f32_mm

        # solver internal: f32 accumulation even for bf16 data
        K = jax.jit(lambda A: _f32_mm(A, A.T))(A)
        alpha = psd_solve_host(K, np.asarray(b), self.lam * n)
        W = jnp.asarray(np.asarray(A).T @ alpha, A.dtype)
        return LinearMapper(W)


@dataclasses.dataclass(eq=False)
class SparseLinearMapper(Transformer):
    """Sparse-input linear map (reference:
    nodes/learning/SparseLinearMapper.scala:13). Inputs are BCOO vectors or
    a batched BCOO matrix; the model stays dense and replicated."""

    W: Any  # (d, k)
    intercept: Optional[Any] = None
    vmap_batch = False

    def apply(self, x):
        if isinstance(x, jsparse.BCOO):
            out = x @ self.W
        else:
            out = mm(jnp.asarray(x), self.W)
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        if isinstance(x, jsparse.BCOO):
            out = jsparse.bcoo_dot_general(
                x, self.W, dimension_numbers=(([1], [0]), ([], []))
            )
        else:
            out = mm(x, self.W)
        if self.intercept is not None:
            out = out + self.intercept
        return Dataset.from_array(out, n=ds.n)
