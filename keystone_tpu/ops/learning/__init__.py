from keystone_tpu.ops.learning.linear import (
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
    SparseLinearMapper,
)
from keystone_tpu.ops.learning.block_ls import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
)

__all__ = [
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
    "LocalLeastSquaresEstimator",
    "SparseLinearMapper",
]
