from keystone_tpu.ops.learning.linear import (
    LinearMapEstimator,
    LinearMapper,
    LocalLeastSquaresEstimator,
    SparseLinearMapper,
)
from keystone_tpu.ops.learning.block_ls import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
)
from keystone_tpu.ops.learning.lbfgs import (
    DenseLBFGSwithL2,
    LeastSquaresDenseGradient,
    LeastSquaresSparseGradient,
    SparseLBFGSwithL2,
)
from keystone_tpu.ops.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.ops.learning.pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from keystone_tpu.ops.learning.zca import ZCAWhitener, ZCAWhitenerEstimator
from keystone_tpu.ops.learning.kmeans import (
    KMeansModel,
    KMeansPlusPlusEstimator,
)
from keystone_tpu.ops.learning.gmm import (
    FusedGMMEstimator,
    OptimizableGMMEstimator,
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.ops.learning.classifiers import (
    LinearDiscriminantAnalysis,
    LogisticRegressionEstimator,
    LogisticRegressionModel,
    NaiveBayesEstimator,
    NaiveBayesModel,
)
from keystone_tpu.ops.learning.weighted_ls import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
)
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    GaussianKernelTransformer,
    KernelBlockLinearMapper,
    KernelMatrix,
    KernelRidgeRegression,
)
from keystone_tpu.ops.learning.cost import CostModel
from keystone_tpu.ops.learning.sparse_ell import (
    EllLeastSquaresEstimator,
    EllLinearMapper,
    ell_dataset,
)

__all__ = [
    "ApproximatePCAEstimator",
    "BatchPCATransformer",
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "BlockWeightedLeastSquaresEstimator",
    "GaussianKernelGenerator",
    "GaussianKernelTransformer",
    "KernelBlockLinearMapper",
    "KernelMatrix",
    "KernelRidgeRegression",
    "PerClassWeightedLeastSquaresEstimator",
    "ColumnPCAEstimator",
    "CostModel",
    "DenseLBFGSwithL2",
    "DistributedColumnPCAEstimator",
    "DistributedPCAEstimator",
    "GaussianMixtureModel",
    "GaussianMixtureModelEstimator",
    "KMeansModel",
    "EllLeastSquaresEstimator",
    "FusedGMMEstimator",
    "EllLinearMapper",
    "KMeansPlusPlusEstimator",
    "LeastSquaresDenseGradient",
    "ell_dataset",
    "LeastSquaresEstimator",
    "LeastSquaresSparseGradient",
    "LinearDiscriminantAnalysis",
    "LinearMapEstimator",
    "LinearMapper",
    "LocalColumnPCAEstimator",
    "LocalLeastSquaresEstimator",
    "LogisticRegressionEstimator",
    "LogisticRegressionModel",
    "NaiveBayesEstimator",
    "OptimizableGMMEstimator",
    "NaiveBayesModel",
    "PCAEstimator",
    "PCATransformer",
    "SparseLBFGSwithL2",
    "SparseLinearMapper",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
]
