"""ZCA whitening.

Reference: nodes/learning/ZCAWhitener.scala:12,30,37 — fit from a single
stacked sample matrix via LAPACK sgesvd; whitener =
V diag((s²/(n−1) + ε)^−½) Vᵀ; apply = (x − means) · whitener.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, Transformer


@dataclasses.dataclass(eq=False)
class ZCAWhitener(Transformer):
    whitener: Any  # (d, d)
    means: Any  # (d,)

    def apply(self, x):
        # works for a (d,) vector or an (m, d) row-major patch matrix
        return mm(x - self.means, self.whitener)

    def apply_batch(self, ds: Dataset) -> Dataset:
        out = mm(ds.padded() - self.means, self.whitener)
        out = out * ds.mask()[:, None] if out.ndim == 2 else out
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class ZCAWhitenerEstimator(Estimator):
    """Fit from the (single) stacked sample matrix (n, d)."""

    eps: float = 0.1

    def fit(self, data) -> ZCAWhitener:
        if isinstance(data, Dataset):
            x = jnp.asarray(data.array())
        else:
            x = jnp.asarray(data)
        return self.fit_single(x)

    def fit_single(self, x: jnp.ndarray) -> ZCAWhitener:
        n = x.shape[0]
        means = jnp.mean(x, axis=0)
        centered = x - means
        _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
        scale = 1.0 / jnp.sqrt(s * s / (n - 1.0) + self.eps)
        whitener = mm(vt.T * scale[None, :], vt)
        return ZCAWhitener(whitener, means)
