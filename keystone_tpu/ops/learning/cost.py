"""Solver cost models.

Reference: nodes/learning/CostModel.scala:6 — ``cost(n, d, k, sparsity,
numMachines, cpuWeight, memWeight, networkWeight)``. The reference's
empirical weights (cpu=3.8e-4, mem=2.9e-1, network=1.32) were fit on a
16x r3.4xlarge cluster (LeastSquaresEstimator.scala:17,29-31); the TPU
defaults below rescale them to a v5e chip's envelope: the flops term is
normalized to MXU bf16 throughput, bytes-scanned to HBM bandwidth, and the
network term to ICI all-reduce bandwidth. The *relative* formulas per
solver (flops/mem/net) carry over unchanged — they count work, not
hardware.
"""

from __future__ import annotations

# cost-model unit weights for one TPU v5e chip, in seconds per unit:
# cpu: 1 / (197e12 bf16 flops/s), mem: 1 / (819e9 HBM bytes/s) * 4 bytes,
# network: per-hop ICI latency-ish constant for small collectives.
TPU_CPU_WEIGHT = 1.0 / 197e12
TPU_MEM_WEIGHT = 4.0 / 819e9
TPU_NETWORK_WEIGHT = 1e-6


class CostModel:
    """Mix-in: analytic cost of running this operator."""

    def cost(
        self,
        n: int,
        d: int,
        k: int,
        sparsity: float,
        num_machines: int,
        cpu_weight: float = TPU_CPU_WEIGHT,
        mem_weight: float = TPU_MEM_WEIGHT,
        network_weight: float = TPU_NETWORK_WEIGHT,
    ) -> float:
        raise NotImplementedError
