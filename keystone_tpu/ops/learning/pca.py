"""PCA family: local SVD, distributed TSQR, randomized sketch, and the
cost-model-selected column variant.

Reference: nodes/learning/PCA.scala (PCATransformer:19,
BatchPCATransformer:38, PCAEstimator:163-225 with MATLAB sign convention
:227-248, ColumnPCAEstimator:51-156), DistributedPCA.scala:20 (mlmatrix
TSQR), ApproximatePCA.scala:22 (Halko-Martinsson-Tropp randomized range
finder).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.cost import CostModel
from keystone_tpu.parallel import linalg as plinalg
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, Transformer
from keystone_tpu.workflow.node_optimization import Optimizable


def enforce_matlab_pca_sign_convention(pca: jnp.ndarray) -> jnp.ndarray:
    """Largest-|element| entry of each column gets a positive sign
    (reference: PCA.scala:227-248)."""
    col_maxs = jnp.max(pca, axis=0)
    abs_col_maxs = jnp.max(jnp.abs(pca), axis=0)
    signs = jnp.where(col_maxs == abs_col_maxs, 1.0, -1.0)
    return pca * signs[None, :]


@dataclasses.dataclass(eq=False)
class PCATransformer(Transformer):
    """x -> pca_matᵀ x for vectors (reference: PCA.scala:19)."""

    pca_mat: Any  # (d, dims)

    def apply(self, x):
        return mm(x, self.pca_mat)

    def apply_batch(self, ds: Dataset) -> Dataset:
        return Dataset.from_array(mm(ds.padded(), self.pca_mat), n=ds.n)


@dataclasses.dataclass(eq=False)
class BatchPCATransformer(Transformer):
    """(d, m) descriptor matrix -> (dims, m) (reference: PCA.scala:38 —
    pcaMat.t * in)."""

    pca_mat: Any  # (d, dims)
    vmap_batch = True

    def apply(self, m):
        return mm(self.pca_mat.T, m)

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            x = ds.padded()  # (n, d, m)
            return Dataset.from_array(
                jnp.einsum("dk,ndm->nkm", self.pca_mat, x), n=ds.n
            )
        return ds.map(self.apply)


def _compute_pca(data_mat: jnp.ndarray, dims: int) -> jnp.ndarray:
    """Center, SVD, sign convention, truncate (reference:
    PCA.scala:180-203 computePCA)."""
    means = jnp.mean(data_mat, axis=0)
    centered = data_mat - means
    _, _, vt = jnp.linalg.svd(centered, full_matrices=False)
    pca = enforce_matlab_pca_sign_convention(vt.T)
    return pca[:, :dims]


@dataclasses.dataclass(eq=False)
class PCAEstimator(Estimator, CostModel):
    """Local PCA: materialize the sample, one SVD (reference:
    PCA.scala:163-225 — collect + LAPACK sgesvd; here the SVD runs on
    device)."""

    dims: int

    def fit(self, data: Dataset) -> PCATransformer:
        x = data.array()
        return PCATransformer(_compute_pca(jnp.asarray(x), self.dims))

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        # reference: PCA.scala:205-225 — collect everything to one place
        flops = float(n) * d * d
        bytes_scanned = float(n) * d
        network = float(n) * d
        return (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


@dataclasses.dataclass(eq=False)
class DistributedPCAEstimator(Estimator, CostModel):
    """Distributed PCA via TSQR: R of the sharded centered matrix, then a
    local SVD of R (reference: DistributedPCA.scala:20,34-57 — mlmatrix
    `new TSQR().qrR` + driver-side SVD)."""

    dims: int

    def fit(self, data: Dataset) -> PCATransformer:
        ds = data.to_array_mode()
        x = ds.padded()
        mask = ds.mask()
        mu = jnp.sum(x * mask[:, None], axis=0) / ds.n
        centered = (x - mu) * mask[:, None]
        r = plinalg.tsqr_r(centered)
        _, _, vt = jnp.linalg.svd(r, full_matrices=False)
        pca = enforce_matlab_pca_sign_convention(vt.T)
        return PCATransformer(pca[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        # reference: DistributedPCA.scala:59-73 — n d²/m + d³ log m
        flops = float(n) * d * d / num_machines + float(d) ** 3 * max(
            np.log2(num_machines), 1.0
        )
        bytes_scanned = float(n) * d / num_machines
        network = float(d) * d * max(np.log2(num_machines), 1.0)
        return (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


@dataclasses.dataclass(eq=False)
class ApproximatePCAEstimator(Estimator, CostModel):
    """Randomized sketch PCA (Halko-Martinsson-Tropp algs 4.4 + 5.1;
    reference: ApproximatePCA.scala:22,37,67): range finder with ``q``
    power iterations on an (n, dims+p) sketch, then SVD of the small
    projected matrix."""

    dims: int
    p: int = 10  # oversampling
    q: int = 2  # power iterations
    seed: int = 0

    def fit(self, data: Dataset) -> PCATransformer:
        ds = data.to_array_mode()
        x = ds.padded()
        mask = ds.mask()
        mu = jnp.sum(x * mask[:, None], axis=0) / ds.n
        A = (x - mu) * mask[:, None]
        d = A.shape[1]
        l = min(self.dims + self.p, d)
        key = jax.random.PRNGKey(self.seed)
        omega = jax.random.normal(key, (d, l), jnp.float32)
        Y = mm(A, omega)  # (and B below): policy precision — B feeds the
        # SVD directly, so truncation there lands in the PCA directions
        Q, _ = jnp.linalg.qr(Y)
        for _ in range(self.q):  # power iterations for spectral decay
            Z, _ = jnp.linalg.qr(mm(A.T, Q))
            Q, _ = jnp.linalg.qr(mm(A, Z))
        B = mm(Q.T, A)  # (l, d)
        _, _, vt = jnp.linalg.svd(B, full_matrices=False)
        pca = enforce_matlab_pca_sign_convention(vt.T)
        return PCATransformer(pca[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight,
             network_weight):
        l = self.dims + self.p
        flops = float(n) * d * l * (1 + self.q) / num_machines
        bytes_scanned = float(n) * d / num_machines
        network = float(d) * l
        return (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


def _columns_dataset(data: Dataset) -> Dataset:
    """Flatten a dataset of (d, m) descriptor matrices into one (N, d)
    array of descriptor columns (reference: LocalColumnPCAEstimator —
    flatMap(matrixToColArray))."""
    cols: List[np.ndarray] = []
    for m in data.items():
        cols.append(np.asarray(m).T)
    return Dataset.from_array(jnp.asarray(np.concatenate(cols, axis=0)))


@dataclasses.dataclass(eq=False)
class LocalColumnPCAEstimator(Estimator, CostModel):
    """Column-wise local PCA over matrix items (reference:
    PCA.scala:51-70)."""

    dims: int

    def fit(self, data: Dataset) -> BatchPCATransformer:
        t = PCAEstimator(self.dims).fit(_columns_dataset(data))
        return BatchPCATransformer(t.pca_mat)

    def cost(self, *a, **kw):
        return PCAEstimator(self.dims).cost(*a, **kw)


@dataclasses.dataclass(eq=False)
class DistributedColumnPCAEstimator(Estimator, CostModel):
    """Column-wise distributed PCA (reference: PCA.scala:81-102)."""

    dims: int

    def fit(self, data: Dataset) -> BatchPCATransformer:
        t = DistributedPCAEstimator(self.dims).fit(
            _columns_dataset(data).shard()
        )
        return BatchPCATransformer(t.pca_mat)

    def cost(self, *a, **kw):
        return DistributedPCAEstimator(self.dims).cost(*a, **kw)


@dataclasses.dataclass(eq=False)
class ColumnPCAEstimator(Estimator, Optimizable):
    """Cost-model choice between local and distributed column PCA
    (reference: PCA.scala:118-156 — OptimizableEstimator)."""

    dims: int
    num_machines: Optional[int] = None

    def _options(self):
        return [
            LocalColumnPCAEstimator(self.dims),
            DistributedColumnPCAEstimator(self.dims),
        ]

    def fit(self, data: Dataset):
        # consult the cost model eagerly (reference default is the
        # distributed estimator, PCA.scala:128; the graph-level
        # NodeOptimizationRule replaces this node when sampling is possible)
        return self.optimize([data], data.n).fit(data)

    def fit_datasets(self, datasets):
        return self.fit(datasets[0])

    def optimize(self, samples, n_total: int):
        sample: Dataset = samples[0]
        first = np.asarray(sample.first())
        d = first.shape[0]
        cols_per_item = first.shape[1] if first.ndim > 1 else 1
        n = max(n_total, sample.n) * cols_per_item
        machines = self.num_machines or max(
            len(jax.devices()), 1
        )
        from keystone_tpu.ops.learning.cost import (
            TPU_CPU_WEIGHT,
            TPU_MEM_WEIGHT,
            TPU_NETWORK_WEIGHT,
        )

        return min(
            self._options(),
            key=lambda o: o.cost(
                n, d, self.dims, 1.0, machines,
                TPU_CPU_WEIGHT, TPU_MEM_WEIGHT, TPU_NETWORK_WEIGHT,
            ),
        )
