"""Diagonal-covariance Gaussian mixture model (soft assignment + local EM).

Reference: nodes/learning/GaussianMixtureModel.scala (batch Mahalanobis +
shifted-softmax posterior + aggressive thresholding, :19-97, csv load
:97-110) and GaussianMixtureModelEstimator.scala:25-203 (k-means++ or
random init, variance flooring, incremental log-sum-exp cost, min-cluster
guard). The E/M steps are jitted device matmuls; the reference's
incremental LSE trick is the standard logsumexp here.

Two physical EM implementations exist, like the reference's scala/enceval
pair (nodes/learning/external/GaussianMixtureModelEstimator.scala):
``GaussianMixtureModelEstimator`` steps EM from the host (one small jitted
program per iteration, cost read back each step — easy to introspect),
and ``FusedGMMEstimator`` runs the ENTIRE EM as one ``lax.while_loop``
program that never leaves the device (convergence test, min-cluster
guard, and variance flooring all in-graph) — the enceval-native analogue,
where "native" on TPU means fused XLA. ``OptimizableGMMEstimator`` picks
between them at k >= 32 the way the reference flips to the native
implementation for large vocabularies (nodes/images/FisherVector
.scala:84-94).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.kmeans import KMeansPlusPlusEstimator
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, Transformer
from keystone_tpu.workflow.node_optimization import Optimizable

KMEANS_PLUS_PLUS_INITIALIZATION = "kmeans++"
RANDOM_INITIALIZATION = "random"


@dataclasses.dataclass(eq=False)
class GaussianMixtureModel(Transformer):
    """Thresholded posterior assignments. ``means``/``variances`` are
    (dims, k) — each column one cluster, matching the reference ctor so
    csv fixtures load identically."""

    means: Any  # (d, k)
    variances: Any  # (d, k)
    weights: Any  # (k,)
    weight_threshold: float = 1e-4

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def _posteriors(self, X):
        # (d, k) operands consumed directly — transposing captured
        # constants inside a fused jit program miscompiles on some TPU
        # backends (observed: posteriors computed against wrong means)
        llh = _log_likelihoods_dk(
            X, self.means, self.variances, self.weights
        )
        # shifted softmax (peak at 0) + aggressive thresholding
        llh = llh - jnp.max(llh, axis=1, keepdims=True)
        q = jnp.exp(llh)
        q = q / jnp.sum(q, axis=1, keepdims=True)
        q = jnp.where(q > self.weight_threshold, q, 0.0)
        return q / jnp.sum(q, axis=1, keepdims=True)

    def apply(self, x):
        return self._posteriors(x[None, :])[0]

    def apply_batch(self, ds: Dataset) -> Dataset:
        q = self._posteriors(ds.padded())
        return Dataset.from_array(q * ds.mask()[:, None], n=ds.n)

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str,
             delimiter: str = ",") -> "GaussianMixtureModel":
        """CSV load (reference: GaussianMixtureModel.scala:97-110)."""
        means = np.loadtxt(mean_file, delimiter=delimiter, ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=delimiter, ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=delimiter).reshape(-1)
        return GaussianMixtureModel(
            jnp.asarray(means, jnp.float32),
            jnp.asarray(variances, jnp.float32),
            jnp.asarray(weights, jnp.float32),
        )


@jax.jit
def _log_likelihoods_dk(X, mu_dk, var_dk, weights):
    """(n, k) log p(x, cluster): −½‖x−μ‖²_Λ − ½Σlog var + log w + const
    (reference: GaussianMixtureModel.scala:47-66). ``mu_dk``/``var_dk``
    are (d, k) — the model's native layout; no transposes occur in the
    program (see _posteriors for why)."""
    d = X.shape[1]
    xsq = X * X
    # HIGHEST precision: TPU's default bf16 matmul passes lose ~3 decimal
    # digits here, which the softmax amplifies into materially different
    # posteriors (the reference computes these in f64 on CPU)
    hp = jax.lax.Precision.HIGHEST
    sq_mahl = (
        jnp.matmul(xsq, 0.5 / var_dk, precision=hp)
        - jnp.matmul(X, mu_dk / var_dk, precision=hp)
        + 0.5 * jnp.sum(mu_dk * mu_dk / var_dk, axis=0)[None, :]
    )
    return (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(var_dk), axis=0)[None, :]
        + jnp.log(weights)[None, :]
        - sq_mahl
    )


def _log_likelihoods(X, mu, var, weights):
    """Back-compat wrapper taking (k, d) mu/var."""
    return _log_likelihoods_dk(X, mu.T, var.T, weights)


@dataclasses.dataclass(eq=False)
class GaussianMixtureModelEstimator(Estimator):
    """Local EM over the (collected) sample, mirroring
    GaussianMixtureModelEstimator.scala:25 parameter-for-parameter."""

    k: int
    max_iterations: int = 100
    min_cluster_size: int = 40
    stop_tolerance: float = 1e-4
    weight_threshold: float = 1e-4
    small_variance_threshold: float = 1e-2
    absolute_variance_threshold: float = 1e-9
    initialization_method: str = KMEANS_PLUS_PLUS_INITIALIZATION
    seed: int = 0

    def _initialize(self, X, xsq):
        """Shared init for both physical EMs: k-means++ (or random) seeds
        + variance floor (GaussianMixtureModelEstimator.scala:60-90)."""
        n, d = X.shape
        mean_global = jnp.mean(X, axis=0)
        var_global = jnp.mean(xsq, axis=0) - mean_global * mean_global

        if self.initialization_method == KMEANS_PLUS_PLUS_INITIALIZATION:
            km = KMeansPlusPlusEstimator(self.k, 1, seed=self.seed)
            assign = km.fit(np.asarray(X)).apply_batch(
                Dataset.from_array(X)
            ).padded()
            mass = jnp.sum(assign, axis=0)
            inv = 1.0 / jnp.maximum(mass, 1.0)
            weights = mass / n
            mu = inv[:, None] * mm(assign.T, X)
            var = inv[:, None] * mm(assign.T, xsq) - mu * mu
        else:  # RANDOM_INITIALIZATION
            rng = np.random.default_rng(self.seed)
            col_min = jnp.min(X, axis=0)
            col_range = jnp.max(X, axis=0) - col_min
            mu = (
                jnp.asarray(rng.uniform(size=(self.k, d)), jnp.float32)
                * col_range[None, :]
                + col_min[None, :]
            )
            var = 0.1 * jnp.ones((self.k, d)) * (col_range * col_range)[None, :]
            weights = jnp.full((self.k,), 1.0 / self.k)

        var_lb = jnp.maximum(
            self.small_variance_threshold * var_global,
            self.absolute_variance_threshold,
        )
        var = jnp.maximum(var, var_lb[None, :])
        return mu, var, weights, var_lb

    def fit(self, data) -> GaussianMixtureModel:
        if isinstance(data, Dataset):
            X = np.asarray(data.array(), np.float32)
        else:
            X = np.asarray(data, np.float32)
        X = jnp.asarray(X)
        n = X.shape[0]
        xsq = X * X
        mu, var, weights, var_lb = self._initialize(X, xsq)

        prev_cost = None
        for _ in range(self.max_iterations):
            llh = _log_likelihoods(X, mu, var, weights)
            cost = float(
                jnp.mean(jax.scipy.special.logsumexp(llh, axis=1))
            )
            if prev_cost is not None and (
                cost - prev_cost
            ) < self.stop_tolerance * abs(prev_cost):
                break
            prev_cost = cost
            # E-step: shifted softmax + thresholding
            q = jnp.exp(llh - jnp.max(llh, axis=1, keepdims=True))
            q = q / jnp.sum(q, axis=1, keepdims=True)
            q = jnp.where(q > self.weight_threshold, q, 0.0)
            q = q / jnp.sum(q, axis=1, keepdims=True)
            # M-step with min-cluster guard
            q_sum = jnp.sum(q, axis=0)
            if bool(jnp.any(q_sum < self.min_cluster_size)):
                break  # "Unbalanced clustering, try less centers"
            weights = q_sum / n
            inv = 1.0 / q_sum
            mu = inv[:, None] * mm(q.T, X)
            var = inv[:, None] * mm(q.T, xsq) - mu * mu
            var = jnp.maximum(var, var_lb[None, :])

        return GaussianMixtureModel(
            mu.T, var.T, weights, self.weight_threshold
        )


@partial(
    jax.jit,
    static_argnames=(
        "max_iterations", "min_cluster_size", "stop_tolerance",
        "weight_threshold",
    ),
)
def _fused_em(
    X, mu0, var0, w0, var_lb, *, max_iterations: int,
    min_cluster_size: int, stop_tolerance: float, weight_threshold: float,
):
    """Whole EM as ONE device program: lax.while_loop with the convergence
    test, aggressive posterior thresholding, min-cluster guard, and
    variance flooring all in-graph — zero host syncs until the caller
    reads the result. Semantics identical to the host-stepped loop in
    ``GaussianMixtureModelEstimator.fit`` (both break BEFORE applying an
    update when converged or unbalanced)."""
    n = X.shape[0]
    xsq = X * X

    def cond(state):
        i, mu, var, w, prev_cost, done = state
        return (i < max_iterations) & ~done

    def body(state):
        i, mu, var, w, prev_cost, done = state
        llh = _log_likelihoods_dk(X, mu.T, var.T, w)
        cost = jnp.mean(jax.scipy.special.logsumexp(llh, axis=1))
        converged = (cost - prev_cost) < stop_tolerance * jnp.abs(prev_cost)

        q = jnp.exp(llh - jnp.max(llh, axis=1, keepdims=True))
        q = q / jnp.sum(q, axis=1, keepdims=True)
        q = jnp.where(q > weight_threshold, q, 0.0)
        q = q / jnp.sum(q, axis=1, keepdims=True)
        q_sum = jnp.sum(q, axis=0)
        unbalanced = jnp.any(q_sum < min_cluster_size)

        stop = converged | unbalanced
        inv = 1.0 / jnp.maximum(q_sum, 1e-30)
        hp = jax.lax.Precision.HIGHEST
        mu_new = inv[:, None] * jnp.matmul(q.T, X, precision=hp)
        var_new = (
            inv[:, None] * jnp.matmul(q.T, xsq, precision=hp)
            - mu_new * mu_new
        )
        var_new = jnp.maximum(var_new, var_lb[None, :])
        w_new = q_sum / n

        keep = lambda new, old: jnp.where(stop, old, new)
        return (
            i + 1,
            keep(mu_new, mu),
            keep(var_new, var),
            keep(w_new, w),
            jnp.where(stop, prev_cost, cost),
            stop,
        )

    _, mu, var, w, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), mu0, var0, w0, jnp.float32(-jnp.inf),
         jnp.bool_(False)),
    )
    return mu, var, w


@dataclasses.dataclass(eq=False)
class FusedGMMEstimator(GaussianMixtureModelEstimator):
    """Second physical EM implementation — the enceval-native analogue
    (reference: nodes/learning/external/GaussianMixtureModelEstimator
    .scala): the full EM runs as one fused device program. Same init,
    same parameters, same stopping semantics as the host-stepped EM."""

    def fit(self, data) -> GaussianMixtureModel:
        if isinstance(data, Dataset):
            X = np.asarray(data.array(), np.float32)
        else:
            X = np.asarray(data, np.float32)
        X = jnp.asarray(X)
        mu, var, weights, var_lb = self._initialize(X, X * X)
        mu, var, weights = _fused_em(
            X, mu, var, weights, var_lb,
            max_iterations=self.max_iterations,
            min_cluster_size=self.min_cluster_size,
            stop_tolerance=self.stop_tolerance,
            weight_threshold=self.weight_threshold,
        )
        return GaussianMixtureModel(
            mu.T, var.T, weights, self.weight_threshold
        )


@dataclasses.dataclass(eq=False)
class OptimizableGMMEstimator(GaussianMixtureModelEstimator, Optimizable):
    """Physical-choice wrapper: the fused device EM at k >= 32, the
    host-stepped EM below — mirroring the reference's switch to the
    native implementation for large vocabularies
    (nodes/images/FisherVector.scala:84-94)."""

    native_k_threshold: int = 32

    def _chosen(self) -> GaussianMixtureModelEstimator:
        cls = (
            FusedGMMEstimator
            if self.k >= self.native_k_threshold
            else GaussianMixtureModelEstimator
        )
        fields = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(GaussianMixtureModelEstimator)
        }
        return cls(**fields)

    @property
    def default(self) -> Estimator:
        return self._chosen()

    def optimize(self, samples, n_total: int) -> Estimator:
        return self._chosen()

    def fit(self, data) -> GaussianMixtureModel:
        return self._chosen().fit(data)
