"""Fixed-width sparse rows (ELL format) + a streamed one-pass solver.

Reference: the Amazon reviews workload — hashed-TF features (65M rows x
1024 hashed dims, ~0.5% dense; scripts/constantEstimator.R:34-36) solved
by LeastSquaresSparseGradient LBFGS (nodes/learning/LBFGS.scala:208) or
the Exact normal-equations solver (nodes/learning/LinearMapper.scala) over
Spark-partitioned breeze SparseVectors.

TPU-native redesign: scatter/gather-based CSR math is the wrong shape for
a systolic array. Hashed-TF rows have a *bounded* number of nonzeros, so
the natural device format is ELL — ``(n, nnz)`` column indices + values —
and the natural compute is *tile-densify then ride the MXU*: a scan
streams fixed-size row tiles, expands each to a dense ``(chunk, d)``
bfloat16 block via fused iota-compare one-hots (no scatter), and feeds
MXU contractions. One pass accumulates the full normal equations
(G = AᵀA, AᵀY), so the least-squares fit needs ZERO further passes —
where the reference's LBFGS re-streams all 65M rows per iteration, the
quadratic objective collapses into the (d, d) Gram once d fits in HBM.
Multi-device: rows shard over the mesh's example axes; each shard scans
its local tiles and the (d, d)/(d, k) partials meet in one psum.

Measured (1 TPU v5e chip, 65M x 1024 @ nnz=5): full fit ~2.1 s vs the
reference cluster's 186.1 s Exact / 33.7 s LS-LBFGS (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from keystone_tpu.ops.learning.block_ls import _psd_solve_device
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


def ell_dataset(idx, vals, n: Optional[int] = None) -> Dataset:
    """Wrap ``(n, nnz)`` int32 column indices + values as a Dataset whose
    element tree is the ELL pair. Pad rows must have ``vals == 0`` (their
    contributions then vanish identically — no masking needed)."""
    return Dataset.from_array((jnp.asarray(idx), jnp.asarray(vals)), n=n)


def ell_to_dense(idx, vals, d: int) -> jnp.ndarray:
    """Dense (rows, d) bf16 tile from ELL rows via fused iota-compare
    one-hots — the scatter-free densify (duplicate column ids sum)."""
    cols = jnp.arange(d, dtype=jnp.int32)
    out = jnp.zeros((idx.shape[0], d), jnp.bfloat16)
    for j in range(idx.shape[1]):
        out = out + jnp.where(
            idx[:, j : j + 1] == cols[None, :],
            vals[:, j : j + 1].astype(jnp.bfloat16),
            0,
        )
    return out


def _chunked(a, chunk: int):
    n = a.shape[0]
    pad = (-n) % chunk
    if pad:
        a = jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
        )
    return a.reshape((a.shape[0] // chunk, chunk) + a.shape[1:])


@partial(jax.jit, static_argnames=("d", "chunk"))
def _normal_eq_pass(idx, vals, Y, *, d: int, chunk: int):
    """Single-shard streamed accumulation of (AᵀA, AᵀY) over row tiles."""

    def body(carry, inp):
        i, v, y = inp
        dense = ell_to_dense(i, v, d)
        G, AY = carry
        G = G + jax.lax.dot_general(
            dense.T, dense, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        AY = AY + jax.lax.dot_general(
            dense.T, y, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # f32 labels keep f32 passes on the MXU — DEFAULT precision
            # would truncate the f32 operand to bf16 (repo precision
            # policy, block_ls._f32_mm); bf16 labels ride the native path
            precision=(
                jax.lax.Precision.HIGHEST
                if y.dtype == jnp.float32 else None
            ),
        )
        return (G, AY), None

    k = Y.shape[1]
    (G, AY), _ = jax.lax.scan(
        body,
        (jnp.zeros((d, d), jnp.float32), jnp.zeros((d, k), jnp.float32)),
        (_chunked(idx, chunk), _chunked(vals, chunk),
         _chunked(Y, chunk)),  # Y keeps its dtype: bf16×f32→f32 accumulates
        # without quantizing user-supplied f32 labels
    )
    return G, AY


_jit_psd_solve = jax.jit(_psd_solve_device)

_SHARDED_CACHE = {}


def _sharded_normal_eq(mesh, d: int, chunk: int):
    """shard_map'd normal-equations pass, cached per (mesh topology, d,
    chunk) so repeated fits — including on distinct but equivalent mesh
    objects — reuse one compiled program (keying on id(mesh) would grow
    an entry per mesh object for the life of the process)."""
    key = (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(dev.id for dev in mesh.devices.flat),
        d, chunk,
    )
    if key not in _SHARDED_CACHE:
        axes = mesh_lib._example_axes(mesh)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(axes, None), P(axes, None), P(axes, None)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def sharded_pass(i, v, y):
            G, AY = _normal_eq_pass(i, v, y, d=d, chunk=chunk)
            return jax.lax.psum(G, axes), jax.lax.psum(AY, axes)

        _SHARDED_CACHE[key] = sharded_pass
    return _SHARDED_CACHE[key]


@dataclasses.dataclass(eq=False)
class EllLeastSquaresEstimator(LabelEstimator):
    """One-pass L2-regularized least squares on ELL sparse features:
    stream-accumulate the normal equations, solve the (d, d) system on
    device. Replaces both reference solvers for this workload — the
    Exact solver's shuffle-heavy AᵀA (LinearMapper.scala) and the
    per-iteration re-streaming of sparse LBFGS (LBFGS.scala:208)."""

    d: int  # feature dimension (hash space size)
    lam: float = 0.0
    chunk: int = 1_000_000
    segment_flops: float = 2.5e15  # Gram work per DISPATCH (~40 s at
    # the measured 68 TF/s): one monolithic scan over 65M rows at
    # d=16384 is a single ~9-minute XLA execution, which the remote
    # worker killed twice (worker crash/restart) where shorter
    # dispatches of the same total work complete. The bound scales
    # with d² so small-d fits (Amazon-1024: ~2 s total) stay one
    # dispatch — segmentation adds one ~100 ms sync per segment, noise
    # against minutes of Gram work but 20% on a 2 s fit. G/AY
    # accumulate across segments on device.

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        idx, vals = data.padded()
        Y = labels.padded()
        n = data.n
        mesh = mesh_lib.current_mesh()
        n_shards = mesh_lib.n_data_shards(mesh)

        if n_shards > 1:
            # zero-val rows contribute nothing, so padding to a shard
            # multiple is free (same invariant as chunk padding)
            pad = (-idx.shape[0]) % n_shards
            if pad:
                z = lambda a: jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
                )
                idx, vals, Y = z(idx), z(vals), z(Y)
            chunk = min(self.chunk, max(idx.shape[0] // n_shards, 1))
            G, AY = _sharded_normal_eq(mesh, self.d, chunk)(idx, vals, Y)
        elif (
            2.0 * idx.shape[0] * self.d * self.d <= self.segment_flops
        ):
            chunk = min(self.chunk, idx.shape[0])
            G, AY = _normal_eq_pass(
                idx, vals, Y, d=self.d, chunk=chunk
            )
        else:
            chunk = min(self.chunk, idx.shape[0])
            seg_rows = int(self.segment_flops / (2.0 * self.d * self.d))
            # a whole number of chunks per segment, at least one
            seg = max(seg_rows // chunk, 1) * chunk
            chunk = min(chunk, seg)
            # pad rows to a segment multiple (zero-val rows vanish
            # identically) so every dispatch shares one compilation
            pad = (-idx.shape[0]) % seg
            if pad:
                z = lambda a: jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
                )
                idx, vals, Y = z(idx), z(vals), z(Y)
            G = jnp.zeros((self.d, self.d), jnp.float32)
            AY = jnp.zeros((self.d, Y.shape[1]), jnp.float32)
            for s in range(0, idx.shape[0], seg):
                Gp, AYp = _normal_eq_pass(
                    idx[s : s + seg], vals[s : s + seg], Y[s : s + seg],
                    d=self.d, chunk=chunk,
                )
                G = G + Gp
                AY = AY + AYp
                np.asarray(G[0, 0])  # bound the dispatch queue (one
                # RT per segment; block_until_ready does not drain the
                # remote stream)

        # f32 Cholesky + iterative refinement, eigh-clamp fallback for
        # the rank-deficient lam=0 case (hash bins never hit / n < d) —
        # same solver discipline as BlockLS. MUST be jitted: eagerly the
        # lax.cond dispatches op-by-op through the remote link (~90 s for
        # a (1024, 1024) solve measured vs 73 ms jitted).
        W = _jit_psd_solve(G, AY, jnp.float32(self.lam * n))
        return EllLinearMapper(W)

    @property
    def weight(self) -> int:
        return 2


@dataclasses.dataclass(eq=False)
class EllLinearMapper(LinearMapper):
    """LinearMapper whose batch apply accepts ELL Datasets directly:
    predictions via row-gather of W (no densify needed test-side)."""

    def apply_batch(self, ds: Dataset) -> Dataset:
        ds = ds.to_array_mode()
        x = ds.padded()
        if isinstance(x, tuple):
            if self.feature_scaler is not None:
                raise NotImplementedError(
                    "feature_scaler on ELL input would densify; scale "
                    "before ELL conversion instead"
                )
            idx, vals = x
            out = jnp.einsum(
                "rj,rjk->rk",
                vals.astype(jnp.float32),
                self.W.astype(jnp.float32)[idx],
            )
            if self.intercept is not None:
                out = (out + self.intercept) * ds.mask()[:, None]
            return Dataset.from_array(out, n=ds.n)
        return super().apply_batch(ds)
