"""Probabilistic classifiers: Naive Bayes, logistic regression, LDA.

Reference: nodes/learning/NaiveBayesModel.scala:21,62 (wraps MLlib
NaiveBayes; model emits log-posteriors π + θx),
LogisticRegressionModel.scala:19,42 (MLlib LBFGS LogisticGradient +
SquaredL2Updater, multinomial support),
LinearDiscriminantAnalysis.scala:17,39 (local multi-class LDA via
eig(S_w⁻¹ S_b)). All are small models: the sufficient statistics are
sharded-reduction matmuls; the solve/driver part is host/local.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import scipy.linalg
from jax.experimental import sparse as jsparse

from keystone_tpu.ops.learning.lbfgs import run_lbfgs, run_lbfgs_device
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import LabelEstimator, Transformer


@dataclasses.dataclass(eq=False)
class NaiveBayesModel(Transformer):
    """x -> log-posterior scores π + θ·x (reference:
    NaiveBayesModel.scala:21 — argmax downstream picks the class)."""

    pi: Any  # (k,) log class priors
    theta: Any  # (k, d) log feature likelihoods

    def apply(self, x):
        if isinstance(x, jsparse.BCOO):
            return self.pi + x @ self.theta.T
        return self.pi + mm(x, self.theta.T)

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        if isinstance(x, jsparse.BCOO):
            scores = self.pi + jsparse.bcoo_dot_general(
                x, self.theta.T, dimension_numbers=(([1], [0]), ([], []))
            )
        else:
            scores = self.pi + mm(x, self.theta.T)
        return Dataset.from_array(scores * ds.mask()[:, None], n=ds.n)


@dataclasses.dataclass(eq=False)
class NaiveBayesEstimator(LabelEstimator):
    """Multinomial NB with Laplace smoothing (reference:
    NaiveBayesModel.scala:62 — MLlib NaiveBayes.train(lambda))."""

    num_classes: int
    lam: float = 1.0

    def fit(self, data: Dataset, labels: Dataset) -> NaiveBayesModel:
        # whole fit stays in the dispatch stream: pulling the labels to
        # the host costs a full tunnel round-trip (~100 ms) on remote
        # devices and forces the async pipeline to drain
        # int cast keeps the old np.eye semantics for float labels
        # (1.5 trains as 1); the range guard below then sees the same
        # values one_hot does
        y = jnp.asarray(labels.array()).reshape(-1).astype(jnp.int32)
        x = data.padded()
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        # one_hot maps out-of-range labels to a zero row, which would
        # silently drop those samples (np.eye indexing used to raise);
        # poison the model with NaN instead — loud, but still sync-free
        bad = jnp.any((y < 0) | (y >= self.num_classes))
        onehot = jnp.where(bad, jnp.nan, onehot)
        # pad rows of x are zero so the (k, d) count matmul is exact
        if isinstance(x, jsparse.BCOO):
            counts = jsparse.bcoo_dot_general(
                x, _pad_rows(onehot, x.shape[0]),
                dimension_numbers=(([0], [0]), ([], [])),
            ).T
        else:
            counts = mm(_pad_rows(onehot, x.shape[0]).T, x)
        class_counts = onehot.sum(axis=0)
        pi = jnp.log(class_counts + self.lam) - np.log(
            y.shape[0] + self.num_classes * self.lam
        )
        totals = jnp.sum(counts, axis=1, keepdims=True)
        theta = jnp.log(counts + self.lam) - jnp.log(
            totals + self.lam * counts.shape[1]
        )
        return NaiveBayesModel(pi, theta)


def _pad_rows(a: jnp.ndarray, n: int) -> jnp.ndarray:
    if a.shape[0] == n:
        return a
    return jnp.concatenate(
        [a, jnp.zeros((n - a.shape[0],) + a.shape[1:], a.dtype)]
    )


def _logistic_vg(W, x, onehot, mask, n, reg):
    """Softmax cross-entropy mean loss + L2 and its gradient — the
    traceable ``vg(W, *data)`` the fused device L-BFGS consumes (module
    level so the compiled optimizer is cached across fits)."""
    # HIGHEST for f32 (TPU DEFAULT truncates operands to bf16 —
    # block_ls._f32_mm); bf16 data keeps the native MXU path
    hp = (
        jax.lax.Precision.HIGHEST
        if not isinstance(x, jsparse.BCOO) and x.dtype == jnp.float32
        else None
    )
    if isinstance(x, jsparse.BCOO):
        logits = jsparse.bcoo_dot_general(
            x, W, dimension_numbers=(([1], [0]), ([], []))
        )
    else:
        logits = jnp.matmul(x, W, precision=hp)
    logz = jax.scipy.special.logsumexp(logits, axis=1)
    ll = jnp.sum((logz - jnp.sum(logits * onehot, axis=1)) * mask)
    p = jnp.exp(logits - logz[:, None]) * mask[:, None]
    if isinstance(x, jsparse.BCOO):
        g = jsparse.bcoo_dot_general(
            x, p - onehot, dimension_numbers=(([0], [0]), ([], []))
        )
    else:
        g = jnp.matmul(x.T, p - onehot, precision=hp)
    return ll / n + 0.5 * reg * jnp.sum(W * W), g / n + reg * W


_jit_logistic_vg = jax.jit(_logistic_vg)


@dataclasses.dataclass(eq=False)
class LogisticRegressionModel(Transformer):
    """argmax-of-logits classifier (reference:
    LogisticRegressionModel.scala:19 — MLlib model.predict)."""

    W: Any  # (d, k)

    def apply(self, x):
        if isinstance(x, jsparse.BCOO):
            return jnp.argmax(x @ self.W, axis=-1)
        return jnp.argmax(mm(x, self.W), axis=-1)

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        if isinstance(x, jsparse.BCOO):
            scores = jsparse.bcoo_dot_general(
                x, self.W, dimension_numbers=(([1], [0]), ([], []))
            )
        else:
            scores = mm(x, self.W)
        return Dataset.from_array(jnp.argmax(scores, axis=-1), n=ds.n)


@dataclasses.dataclass(eq=False)
class LogisticRegressionEstimator(LabelEstimator):
    """Multinomial logistic regression by full-batch L-BFGS (reference:
    LogisticRegressionModel.scala:42 — MLlib LogisticRegressionWithLBFGS +
    SquaredL2Updater). Softmax cross-entropy gradient is one jitted sharded
    program; the optimizer is the fused device L-BFGS by default
    (run_lbfgs_device — zero host syncs), or the f64 host driver."""

    num_classes: int
    num_iters: int = 20
    reg_param: float = 0.0
    convergence_tol: float = 1e-4
    driver: str = "device"

    def fit(self, data: Dataset, labels: Dataset) -> LogisticRegressionModel:
        if self.driver not in ("device", "host"):
            raise ValueError(f"driver must be 'device' or 'host', got {self.driver!r}")
        y = np.asarray(labels.array()).reshape(-1).astype(np.int64)
        if y.size and (y.min() < 0 or y.max() >= self.num_classes):
            # np.eye(k)[y] would silently wrap negatives (e.g. -1/+1
            # binary labels) into valid classes and corrupt the fit
            raise ValueError(
                f"labels must be class ids in [0, {self.num_classes}); "
                f"got range [{y.min()}, {y.max()}]"
            )
        data = data.to_array_mode()
        x = data.padded()
        n = data.n
        d = x.shape[1]
        k = self.num_classes
        onehot = jnp.asarray(_pad_rows(
            jnp.asarray(np.eye(k, dtype=np.float32)[y]), x.shape[0]
        ))
        mask = data.mask()

        if self.driver == "device":
            W = run_lbfgs_device(
                _logistic_vg,  # module-level: jit cache shared across fits
                jnp.zeros((d, k), jnp.float32),
                self.num_iters, convergence_tol=self.convergence_tol,
                data=(x, onehot, mask, jnp.float32(n),
                      jnp.float32(self.reg_param)),
            )
            return LogisticRegressionModel(W)

        def vg(w_flat):
            W = jnp.asarray(w_flat.reshape(d, k).astype(np.float32))
            f, g = _jit_logistic_vg(
                W, x, onehot, mask, jnp.float32(n),
                jnp.float32(self.reg_param),
            )
            return float(f), np.asarray(g, np.float64).ravel()

        w = run_lbfgs(
            vg, np.zeros((d, k)), self.num_iters,
            convergence_tol=self.convergence_tol,
        )
        return LogisticRegressionModel(
            jnp.asarray(w.reshape(d, k).astype(np.float32))
        )


@dataclasses.dataclass(eq=False)
class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA: project onto the top eigenvectors of S_w⁻¹ S_b
    (reference: LinearDiscriminantAnalysis.scala:17,39 — local eig)."""

    num_dimensions: int

    def fit(self, data: Dataset, labels: Dataset):
        from keystone_tpu.ops.learning.linear import LinearMapper

        X = np.asarray(data.array(), np.float64)
        y = np.asarray(labels.array()).reshape(-1).astype(np.int64)
        classes = np.unique(y)
        d = X.shape[1]
        overall_mean = X.mean(axis=0)
        Sw = np.zeros((d, d))
        Sb = np.zeros((d, d))
        for c in classes:
            Xc = X[y == c]
            mu_c = Xc.mean(axis=0)
            centered = Xc - mu_c
            Sw += centered.T @ centered
            diff = (mu_c - overall_mean)[:, None]
            Sb += Xc.shape[0] * (diff @ diff.T)
        evals, evecs = scipy.linalg.eig(Sb, Sw)
        order = np.argsort(-evals.real)
        W = evecs[:, order[: self.num_dimensions]].real
        return LinearMapper(jnp.asarray(W, jnp.float32))
