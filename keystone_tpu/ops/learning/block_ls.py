"""Block coordinate descent least squares — the workhorse solver.

Reference: nodes/learning/BlockLinearMapper.scala — BlockLinearMapper
(:22,50-73) applies a block-split linear model; BlockLeastSquaresEstimator
(:199-283) mean-centers features/labels per block and runs mlmatrix
BlockCoordinateDescent.solveLeastSquaresWithL2 (Gauss-Seidel sweeps: per
block, executors compute AᵀA / AᵀR Grams, tree-reduce to the driver, driver
solves the (b×b) system, broadcasts the block model, executors update the
residual).

TPU-native redesign: the feature matrix is ONE sharded (n, D) array (rows
over the mesh's data axis) instead of a Seq of per-block RDDs; a block is a
static column slice. Each block update is a single jitted program:

    R⁺   = R + X_b W_b            (undo this block's contribution)
    G    = X_bᵀ X_b               (per-shard MXU matmul + psum over "data")
    W_b' = (G + λI)⁻¹ X_bᵀ R⁺      (f64 host solve — see hostsolve.py)
    R    = R⁺ − X_b W_b'

so the reference's executor-GEMM → treeReduce → driver-solve → broadcast →
residual-update round trip collapses into two XLA programs around one small
host solve; the O(n·b·(b+k)) work never leaves the device, and the residual
buffer is donated to avoid an HBM copy per block.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator, Transformer
from keystone_tpu.ops.learning.hostsolve import psd_solve_host
from keystone_tpu.utils.checkpoint import (
    LoopCheckpointer,
    data_probe,
    two_level_schedule,
)


def _f32_mm(a, b):
    """Matmul with f32 accumulation. bf16 inputs ride the MXU's native
    bf16xbf16->f32 path; f32 inputs request HIGHEST precision — on TPU
    the DEFAULT precision truncates f32 operands to bf16 passes, and the
    centered-Gram algebra (G − n·μμᵀ) cancels ~3 orders of magnitude, so
    default-precision f32 Grams come out with O(1) relative error
    (measured: 789 abs err vs 0.09 at HIGHEST on a 256x1024 relu-FFT
    feature Gram, which silently destroyed the MNIST app's model). Users
    choose speed by passing bf16 data, not by losing f32 semantics."""
    f32_in = a.dtype == jnp.float32 or b.dtype == jnp.float32
    return jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST if f32_in else None,
    )


def _psd_solve_with_factor(A, L, rhs, refine=2):
    """A X = rhs given A's (already-ridged) Cholesky factor ``L``, f32
    + ``refine`` iterative-refinement steps. Refinement recovers most
    of the f64 accuracy the reference's driver-side LAPACK solve had
    (mlmatrix NormalEquations; BlockLinearMapper.scala:234-240) without
    a host round-trip — through a remote-dispatch link every host sync
    costs ~100 ms, so the solve must stay inside the async dispatch
    stream. Falls back to eigendecomposition with eigenvalue clamping
    when Cholesky breaks down (indefiniteness from f32 rounding),
    mirroring hostsolve.py. Shared by the fresh-factor path below and
    the cached-KRR factor bank (kernel.py _krr_cached_epoch_scan)."""
    # full-f32 matmuls: refinement converges to the residual's noise
    # floor, so the default bf16 matmul passes would cap the recovered
    # accuracy ~3 digits short
    hp = jax.lax.Precision.HIGHEST

    def chol_path(L):
        def solve(b):
            return jax.scipy.linalg.cho_solve((L, True), b)

        W = solve(rhs)
        for _ in range(refine):
            W = W + solve(rhs - jnp.matmul(A, W, precision=hp))
        return W

    if A.shape[0] > 8192:
        # No eigh fallback at large d: lax.cond compiles BOTH branches,
        # and eigh's QR workspace at (16384,16384) is several extra
        # ~1 GB f32 buffers — it OOMed the 16 GiB chip alongside the
        # Gram/data the Amazon-16384 solve holds. Cholesky breakdown
        # (f32-rounding indefiniteness at lam≈0) then surfaces as
        # non-finite W, which every large-d caller already asserts on;
        # regularized fits at this scale are well inside chol's range.
        return chol_path(L)

    def eigh_path(L):
        del L
        w, V = jnp.linalg.eigh(A)
        w = jnp.maximum(w, 1e-12 * jnp.maximum(w[-1], 1.0))
        return jnp.matmul(
            V, jnp.matmul(V.T, rhs, precision=hp) / w[:, None],
            precision=hp,
        )

    return jax.lax.cond(jnp.all(jnp.isfinite(L)), chol_path, eigh_path, L)


def _psd_solve_device(gram, rhs, lam, refine=2):
    """(gram + lam·I) X = rhs on device: factor, then the shared
    refined solve (see _psd_solve_with_factor)."""
    A = gram + lam * jnp.eye(gram.shape[0], dtype=gram.dtype)
    L = jax.scipy.linalg.cholesky(A, lower=True)
    return _psd_solve_with_factor(A, L, rhs, refine)


@partial(
    jax.jit, static_argnames=("width", "n", "first_pass", "last_pass"),
    donate_argnums=(1,),
)
def _block_step(X, R, Wb, mu, mask, start, lam, *, width: int, n: int,
                first_pass: bool = False, last_pass: bool = False):
    """One whole BCD block update — stats, solve, and residual update —
    as a single XLA program with no host synchronization. The reference's
    executor-GEMM → treeReduce → driver-LAPACK → broadcast → residual
    round trip (BlockLinearMapper.scala:234-240) becomes one dispatch.

    ``first_pass``: on sweep 0 the current block's model is exactly zero
    (fresh fit, or a resumed fit that never completed this block), so the
    old-contribution matmul is skipped — one fewer N·b·k matmul and one
    fewer full read of X per block on the first sweep.

    ``last_pass``: after the final block of the final sweep the residual
    is never read again, so its update (another N·b·k matmul + a full
    residual write) is elided; the returned residual is then stale and
    the caller must not use it.
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    mu_b = jax.lax.dynamic_slice_in_dim(mu, start, width)
    if first_pass:
        R_plus = R
    else:
        contrib = _f32_mm(Xb, Wb) - mask[:, None] * _f32_mm(mu_b, Wb)
        R_plus = R + contrib
    gram = _f32_mm(Xb.T, Xb) - n * jnp.outer(mu_b, mu_b)
    rhs = _f32_mm(Xb.T, R_plus) - jnp.outer(mu_b, jnp.sum(R_plus, axis=0))
    Wb_new = _psd_solve_device(gram, rhs, lam)
    if last_pass:
        return Wb_new, R_plus
    contrib_new = _f32_mm(Xb, Wb_new) - mask[:, None] * _f32_mm(mu_b, Wb_new)
    return Wb_new, R_plus - contrib_new


@partial(jax.jit, static_argnames=("width", "n"), donate_argnums=(1,))
def _block_stats(X, R, Wb, mu, mask, start, *, width: int, n: int):
    """Per-block Gram pass on the RAW (possibly bf16) feature matrix.

    Centering is algebraic — the centered block is never materialized:
        G_c   = X_bᵀX_b − n·μ_bμ_bᵀ
        rhs_c = X_bᵀR⁺ − μ_b·(1ᵀR⁺)
    (pad rows of X and R are zero, so sums over all rows equal sums over
    valid rows). One XLA program; the contractions over the sharded example
    axis lower to per-shard MXU matmuls + a psum over the "data" axis.
    ``start`` is traced so every equal-width block shares this compilation.
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    mu_b = jax.lax.dynamic_slice_in_dim(mu, start, width)
    contrib = _f32_mm(Xb, Wb) - mask[:, None] * _f32_mm(mu_b, Wb)
    R_plus = R + contrib
    gram = _f32_mm(Xb.T, Xb) - n * jnp.outer(mu_b, mu_b)
    rhs = _f32_mm(Xb.T, R_plus) - jnp.outer(mu_b, jnp.sum(R_plus, axis=0))
    return gram, rhs, R_plus


@partial(jax.jit, static_argnames=("width",), donate_argnums=(1,))
def _residual_update(X, R_plus, Wb_new, mu, mask, start, *, width: int):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    mu_b = jax.lax.dynamic_slice_in_dim(mu, start, width)
    contrib = _f32_mm(Xb, Wb_new) - mask[:, None] * _f32_mm(mu_b, Wb_new)
    return R_plus - contrib


@jax.jit
def _column_means(X, Y, mask, n):
    """Feature/label means over valid rows, f32 accumulation, one pass.
    Masked: upstream transformers (e.g. ClassLabelIndicators one-hotting)
    may map zero pad rows to nonzero values."""
    m = mask[:, None]
    s1 = jnp.sum(X.astype(jnp.float32) * m, axis=0)
    sY = jnp.sum(Y.astype(jnp.float32) * m, axis=0)
    return s1 / n, sY / n


@jax.jit
def _centered_labels(Y, mu_y, mask):
    return (Y.astype(jnp.float32) - mu_y) * mask[:, None]


@jax.jit
def _prep(X, Y, mask, n):
    """Means + centered residual in ONE dispatch (each eager/extra
    dispatch costs real latency through a remote-tunnel device; the Y
    pass for mu_y and the centering write share one program so XLA can
    fuse them)."""
    mu, mu_y = _column_means.__wrapped__(X, Y, mask, n)
    return mu, mu_y, _centered_labels.__wrapped__(Y, mu_y, mask)


@jax.jit
def _prep_labels(Y, mask, n):
    """Label mean + centered residual only — the host-blocks path has no
    device-resident X to fold into the same program; feature means ride
    each slab's first visit instead (_host_block_step first_pass)."""
    m = mask[:, None]
    mu_y = jnp.sum(Y.astype(jnp.float32) * m, axis=0) / n
    return mu_y, (Y.astype(jnp.float32) - mu_y) * m


@partial(
    jax.jit, static_argnames=("n", "first_pass", "last_pass"),
    donate_argnums=(1,),
)
def _host_block_step(Xb, R, Wb, mu_b, mask, lam, *, n: int,
                     first_pass: bool = False, last_pass: bool = False):
    """One BCD block update on a HOST-STREAMED slab — the same algebra
    as ``_block_step`` operating on a whole (padded_n, w) slab instead
    of a dynamic column slice of a device-resident X (reference:
    BlockLinearMapper.scala:50-73 iterates feature blocks cached in
    cluster RAM; here the slab arrived via an async ``device_put`` the
    caller double-buffers against this program).

    ``first_pass`` additionally computes the block's feature mean from
    the slab (the in-HBM path gets all means from one ``_prep`` pass;
    with X living on host, the mean pass rides the slab's first visit
    — no extra transfer, one extra fused reduction)."""
    if first_pass:
        mu_b = (
            jnp.sum(Xb.astype(jnp.float32) * mask[:, None], axis=0) / n
        )
        R_plus = R  # this block's model is exactly zero on sweep 0
    else:
        contrib = _f32_mm(Xb, Wb) - mask[:, None] * _f32_mm(mu_b, Wb)
        R_plus = R + contrib
    gram = _f32_mm(Xb.T, Xb) - n * jnp.outer(mu_b, mu_b)
    rhs = _f32_mm(Xb.T, R_plus) - jnp.outer(mu_b, jnp.sum(R_plus, axis=0))
    Wb_new = _psd_solve_device(gram, rhs, lam)
    if last_pass:
        return Wb_new, R_plus, mu_b
    contrib_new = _f32_mm(Xb, Wb_new) - mask[:, None] * _f32_mm(mu_b, Wb_new)
    return Wb_new, R_plus - contrib_new, mu_b


@partial(jax.jit, static_argnames=("n",), donate_argnums=(1,))
def _host_block_rebuild(Xb, R, Wb, mask, *, n: int):
    """Checkpoint-resume residual rebuild for one host slab: recompute
    the block's mean and subtract its restored model's contribution
    (the standard path's ``_residual_update`` + the mean it would have
    had from ``_prep``)."""
    mu_b = jnp.sum(Xb.astype(jnp.float32) * mask[:, None], axis=0) / n
    contrib = _f32_mm(Xb, Wb) - mask[:, None] * _f32_mm(mu_b, Wb)
    return R - contrib, mu_b


def _force_sync(x) -> None:
    """Synchronously force a queued computation by pulling one element
    to host. ``jax.block_until_ready`` does NOT drain the remote
    dispatch stream on tunneled devices (the repo's timing discipline —
    bench.py:24, bin/profile-solvers ``sync()``), so a throttle built on
    it is a no-op exactly where run-ahead hurts."""
    np.asarray(jnp.reshape(x, (-1,))[0])


class _RunAheadLimiter:
    """Caps dispatched-but-unforced pipeline steps at ``window``.

    ``device_put`` allocates its destination buffer at ENQUEUE time, so
    an unthrottled host-blocks loop queues every remaining slab at once
    — peak HBM becomes the sum of ALL slabs instead of the documented
    2-slab bound, and the transfer client retains the matching host
    upload buffers (measured +60 GB transient on the 32 GiB XL fit).
    Forcing the step output from ``window`` steps back keeps at most
    ``window + 1`` slabs in flight while H2D still rides under compute;
    the forced sync costs one ~100 ms tunnel round trip per step, noise
    against the multi-second slab transfers the host path exists for."""

    def __init__(self, window: int = 2):
        self._window = window
        self._q: deque = deque()

    def add(self, step_output) -> None:
        self._q.append(step_output)
        if len(self._q) > self._window:
            _force_sync(self._q.popleft())


def _host_blocks_probe(blocks: Sequence[np.ndarray], Y) -> str:
    """Cheap order-sensitive digest of a host-blocks dataset for
    checkpoint fingerprints — strided row/column samples per block (a
    full ``data_probe`` scan of a host-RAM-scale X would read the whole
    array just to stamp a snapshot)."""
    parts = []
    for b in blocks:
        rows = [0, b.shape[0] // 3, (2 * b.shape[0]) // 3, b.shape[0] - 1]
        cols = slice(0, min(8, b.shape[1]))
        sample = np.asarray(b[rows, cols], np.float64)
        parts.append(
            f"{b.shape}:{b.dtype}:"
            + ",".join(f"{v:.6e}" for v in sample.ravel())
        )
    ysum = float(np.asarray(jnp.sum(Y.astype(jnp.float32))))
    return ";".join(parts) + f"|Y={ysum:.6e}"


@dataclasses.dataclass(eq=False)
class BlockLinearMapper(Transformer):
    """Applies the block-solved linear model. Weights are stored as one
    (D, k) matrix (the concatenation of the reference's per-block models,
    BlockLinearMapper.scala:22) so test-time apply is one MXU matmul."""

    W: Any  # (D, k)
    block_size: int
    feature_mean: Optional[Any] = None  # (D,)
    label_mean: Optional[Any] = None  # (k,)
    explicit_intercept: Optional[Any] = None  # (k,); weighted solver sets it
    solver_info: Optional[dict] = None  # lazy solver diagnostics (e.g.
    # the weighted solver's PCG exit residual); values may be device
    # scalars — reading them forces a host sync

    @property
    def intercept(self):
        if self.explicit_intercept is not None:
            return self.explicit_intercept
        if self.label_mean is None:
            return None
        if self.feature_mean is None:
            return self.label_mean
        return self.label_mean - _f32_mm(self.feature_mean, self.W)

    def apply(self, x):
        out = _f32_mm(x, self.W)
        icpt = self.intercept
        return out if icpt is None else out + icpt

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_host:
            return self._apply_host_blocks(ds)
        out = _f32_mm(ds.padded(), self.W)
        icpt = self.intercept
        if icpt is not None:
            out = (out + icpt) * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)

    def _apply_host_blocks(self, ds: Dataset) -> Dataset:
        """Predict from a host-blocked feature matrix: stream each slab
        (double-buffered, like the fit) and accumulate X_b W_b on
        device — HBM holds 2 slabs + the (n, k) output, never X."""
        blocks = ds.host_blocks
        out = None
        s = 0
        limiter = _RunAheadLimiter()
        nxt = jax.device_put(blocks[0])
        for i, b in enumerate(blocks):
            cur = nxt
            if i + 1 < len(blocks):
                nxt = jax.device_put(blocks[i + 1])
            w = b.shape[1]
            part = _f32_mm(cur, self.W[s : s + w])
            out = part if out is None else out + part
            limiter.add(out)
            s += w
            del cur
        if s != self.W.shape[0]:
            raise ValueError(
                f"host blocks cover {s} features but the model has "
                f"{self.W.shape[0]}"
            )
        icpt = self.intercept
        if icpt is not None:
            out = (out + icpt) * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)

    def apply_and_evaluate(
        self, ds: Dataset, evaluator: Callable[[jnp.ndarray], None]
    ) -> None:
        """Stream per-block partial prediction sums to ``evaluator`` after
        each block (reference: BlockLinearMapper.applyAndEvaluate:95-137) —
        lets callers watch train error improve block by block."""
        X = ds.padded()
        D = X.shape[1]
        icpt = self.intercept
        acc = jnp.zeros((X.shape[0], self.W.shape[1]), X.dtype)
        for start in range(0, D, self.block_size):
            end = min(start + self.block_size, D)
            acc = acc + _f32_mm(X[:, start:end], self.W[start:end])
            out = acc if icpt is None else (acc + icpt) * ds.mask()[:, None]
            evaluator(out)

    @property
    def weight(self) -> int:
        return 2


@dataclasses.dataclass(eq=False)
class BlockLeastSquaresEstimator(LabelEstimator):
    """Gauss-Seidel block coordinate descent for L2-regularized least
    squares (reference: BlockLinearMapper.scala:199-283). ``num_iter``
    sweeps over ``ceil(D / block_size)`` blocks; one sweep reproduces the
    reference's single-pass path (solveOnePassL2)."""

    block_size: int
    num_iter: int = 1
    lam: float = 0.0
    num_features: Optional[int] = None  # pad/truncate hint, parity only
    solve: str = "device"  # "device" (f32 chol + refinement, zero host
    # syncs — the fast path) | "host" (f64 LAPACK per block, for
    # pathologically conditioned systems; costs a dispatch round-trip
    # per block)
    checkpoint_path: Optional[str] = None  # periodic loop-state snapshot;
    # a re-run with the same path resumes at the last completed block
    # (reference: lineage checkpoint every 25 blocks,
    # KernelRidgeRegression.scala:200-210 — see utils/checkpoint.py)
    checkpoint_every: int = 25
    block_callback: Optional[Callable[[int], None]] = None  # called with a
    # running count after each completed block update (per-block progress
    # logging in the reference driver loop)

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        if self.solve not in ("device", "host"):
            raise ValueError(f"solve must be 'device' or 'host', got {self.solve!r}")
        if data.is_host:
            return self._fit_host_blocks(data, labels)
        # Mean-centering of features and labels (reference fits
        # StandardScaler(normalizeStdDev=false) per block + labels:
        # BlockLinearMapper.scala:209-215; full-width centering is
        # mathematically identical) happens algebraically inside the Gram
        # math — X is never copied, so bf16 feature matrices of HBM scale
        # pass through untouched.
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded()
        n = data.n
        D = X.shape[1]
        k = Y.shape[1]
        mask = data.mask()
        mu, mu_y, R = _prep(X, Y, mask, n)

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        Wb = {s: jnp.zeros((w, k), jnp.float32) for s, w in blocks}

        ckpt = None
        start_it, start_pos = 0, 0
        if self.checkpoint_path is not None:
            # stamp config + problem shape + a cheap data probe so a
            # snapshot from a different fit is discarded, not resumed
            fp = (
                f"bls bs={self.block_size} it={self.num_iter} "
                f"lam={self.lam} solve={self.solve} n={n} D={D} k={k} "
                f"probe={data_probe(X, Y)}"
            )
            ckpt = LoopCheckpointer(self.checkpoint_path,
                                    self.checkpoint_every, fingerprint=fp)
            state = ckpt.load()
            if state is not None:
                start_it = int(state["it"])
                start_pos = int(state["pos"])
                for s, w in blocks:
                    if not np.any(state[f"Wb_{s}"]):
                        continue  # untouched block: zero contribution
                    Wb[s] = jnp.asarray(state[f"Wb_{s}"], jnp.float32)
                    # Rebuild the residual from the compact snapshot —
                    # the lineage-truncation analogue: recompute the big
                    # intermediate instead of persisting it.
                    R = _residual_update(X, R, Wb[s], mu, mask, s, width=w)

        def snapshot(next_it: int, next_pos: int):
            st = {"it": next_it, "pos": next_pos}
            for s, _ in blocks:
                st[f"Wb_{s}"] = np.asarray(Wb[s])
            return st

        done = 0
        for it, pos, nxt in two_level_schedule(
            self.num_iter, len(blocks), (start_it, start_pos)
        ):
            s, w = blocks[pos]
            if self.solve == "device":
                # whole block update in one dispatch; the entire fit
                # stays in the async stream — no host sync until the
                # caller consumes W. On sweep 0 this block's model is
                # zero in every path (including checkpoint resume: only
                # never-completed blocks are revisited in sweep 0), so
                # the old-contribution matmul is elided.
                Wb[s], R = _block_step(
                    X, R, Wb[s], mu, mask, s, self.lam,
                    width=w, n=n, first_pass=(it == 0),
                    last_pass=(
                        it == self.num_iter - 1 and pos == len(blocks) - 1
                    ),
                )
            else:
                gram, rhs, R_plus = _block_stats(
                    X, R, Wb[s], mu, mask, s, width=w, n=n
                )
                # (b,b) solve on host in f64 (reference: driver-side
                # NormalEquations solve) — see hostsolve.py.
                Wb[s] = jnp.asarray(psd_solve_host(gram, rhs, self.lam))
                R = _residual_update(
                    X, R_plus, Wb[s], mu, mask, s, width=w
                )
            done += 1
            if ckpt is not None:
                ckpt.tick(lambda: snapshot(*nxt))
            if self.block_callback is not None:
                self.block_callback(done)
        if ckpt is not None:
            ckpt.clear()  # fit completed; stale state must not leak into
            # a later fit at the same path
        W = jnp.concatenate([Wb[s] for s, _ in blocks], axis=0)
        return BlockLinearMapper(
            W,
            self.block_size,
            feature_mean=mu,
            label_mean=mu_y,
        )

    def _fit_host_blocks(self, data: Dataset, labels: Dataset
                         ) -> BlockLinearMapper:
        """Out-of-aggregate-HBM fit: X lives in host RAM as column
        blocks (Dataset.from_host_blocks — the cluster-RAM feature
        cache of BlockLinearMapper.scala:50-73 / the 75%-of-memory
        budget of AutoCacheRule.scala:559-602); each (padded_n, w) slab
        is transferred per pass with the NEXT slab's async ``device_put``
        double-buffered against the current block's Gram/solve/update
        program, so H2D rides under compute. HBM holds 2 slabs + the
        residual, independent of D — the fit is bounded by host RAM.

        The data-blocking ignores ``self.block_size``: the dataset's own
        block layout IS the coordinate-descent blocking (matching the
        reference, where the Seq of feature RDDs defines the blocks)."""
        blocks = data.host_blocks
        widths = data.block_widths
        n = data.n
        pn = data.padded_n
        mask = data.mask()
        lab = labels.to_array_mode()
        if lab.padded_n != pn:
            lab = lab._pad_to(pn)
        Y = lab.padded()
        mu_y, R = _prep_labels(Y, mask, n)
        k = Y.shape[1]
        nb = len(blocks)
        Wb: List[Any] = [jnp.zeros((w, k), jnp.float32) for w in widths]
        mu_bs: List[Any] = [None] * nb

        from keystone_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.current_mesh()
        nshards = mesh.shape[mesh_lib.DATA_AXIS]
        # rows over the mesh's data axis when they divide evenly (the
        # multichip layout); otherwise default single-device placement
        sharding = (
            mesh_lib.data_sharding(mesh) if pn % nshards == 0 else None
        )

        def put(bi: int):
            # async H2D; jax returns immediately and the copy streams
            # while the previous block's program occupies the chip
            if sharding is not None:
                return jax.device_put(blocks[bi], sharding)
            return jax.device_put(blocks[bi])

        ckpt = None
        start_it, start_pos = 0, 0
        if self.checkpoint_path is not None:
            fp = (
                f"bls-host nb={nb} widths={widths} it={self.num_iter} "
                f"lam={self.lam} n={n} k={k} "
                f"probe={_host_blocks_probe(blocks, Y)}"
            )
            ckpt = LoopCheckpointer(self.checkpoint_path,
                                    self.checkpoint_every, fingerprint=fp)
            state = ckpt.load()
            if state is not None:
                start_it = int(state["it"])
                start_pos = int(state["pos"])
                for bi in range(nb):
                    if not np.any(state[f"Wb_{bi}"]):
                        continue
                    Wb[bi] = jnp.asarray(state[f"Wb_{bi}"], jnp.float32)
                    R, mu_bs[bi] = _host_block_rebuild(
                        put(bi), R, Wb[bi], mask, n=n
                    )
                    # serialize rebuild transfers (bounded HBM; resume
                    # is rare so the lost overlap is irrelevant)
                    _force_sync(mu_bs[bi])

        def snapshot(next_it: int, next_pos: int):
            st = {"it": next_it, "pos": next_pos}
            for bi in range(nb):
                st[f"Wb_{bi}"] = np.asarray(Wb[bi])
            return st

        schedule = list(two_level_schedule(
            self.num_iter, nb, (start_it, start_pos)
        ))
        done = 0
        nxt = put(schedule[0][1]) if schedule else None
        limiter = _RunAheadLimiter()
        for j, (it, bi, nxt_state) in enumerate(schedule):
            Xb = nxt
            if j + 1 < len(schedule):
                nxt = put(schedule[j + 1][1])  # prefetch: double buffer
            first = it == 0
            mu_arg = (
                mu_bs[bi]
                if mu_bs[bi] is not None
                else jnp.zeros((widths[bi],), jnp.float32)
            )
            Wb[bi], R, mu_bs[bi] = _host_block_step(
                Xb, R, Wb[bi], mu_arg, mask, self.lam, n=n,
                first_pass=first,
                last_pass=(
                    it == self.num_iter - 1 and bi == nb - 1
                ),
            )
            del Xb  # release this slab's HBM as soon as XLA is done
            limiter.add(Wb[bi])
            done += 1
            if ckpt is not None:
                ckpt.tick(lambda: snapshot(*nxt_state))
            if self.block_callback is not None:
                self.block_callback(done)
        if ckpt is not None:
            ckpt.clear()
        W = jnp.concatenate([jnp.asarray(w) for w in Wb], axis=0)
        mu = jnp.concatenate(mu_bs, axis=0)
        return BlockLinearMapper(
            W,
            max(widths),
            feature_mean=mu,
            label_mean=mu_y,
        )

    @property
    def weight(self) -> int:
        # reference: BlockLinearMapper.scala:204
        return 3 * self.num_iter + 1

    def cost(
        self,
        n: int,
        d: int,
        k: int,
        sparsity: float,
        num_machines: int,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
    ) -> float:
        """Analytic flops/mem/net cost (reference:
        BlockLinearMapper.scala:268-282)."""
        import math

        flops = n * float(d) * (self.block_size + k) / num_machines
        bytes_scanned = n * float(d) / num_machines + float(d) * k
        network = (
            2.0
            * (float(d) * (self.block_size + k))
            * max(math.log2(num_machines), 1.0)
        )
        return self.num_iter * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )
