"""Host-side f64 solves for small regularized PSD systems.

The reference's block solvers compute Gram matrices on executors but solve
the (b, b) systems on the driver in double precision (mlmatrix
NormalEquations / BlockCoordinateDescent; nodes/learning/
BlockLinearMapper.scala:234-240). TPUs have no native f64, and these
systems are genuinely ill-conditioned (n < b blocks with tiny λ), beyond
f32 Cholesky's eps. Same split here: the O(n·b²) Gram work stays on device
in f32; the O(b³) solve of a matrix that already fits on one host runs in
numpy f64. Transfers are (b,b)+(b,k) — negligible next to the Gram pass.
"""

from __future__ import annotations

import numpy as np
import scipy.linalg


def psd_solve_host(gram, rhs, lam: float = 0.0) -> np.ndarray:
    """Solve (gram + lam·I) X = rhs in f64 on host; robust to indefiniteness
    from f32 rounding (falls back to eigh with eigenvalue clamping)."""
    G = np.asarray(gram, dtype=np.float64)
    R = np.asarray(rhs, dtype=np.float64)
    if lam:
        G = G + lam * np.eye(G.shape[0])
    try:
        c, low = scipy.linalg.cho_factor(G, check_finite=False)
        return scipy.linalg.cho_solve((c, low), R, check_finite=False)
    except np.linalg.LinAlgError:
        w, V = np.linalg.eigh(G)
        w = np.maximum(w, 1e-12 * max(w.max(), 1.0))
        return V @ ((V.T @ R) / w[:, None])
