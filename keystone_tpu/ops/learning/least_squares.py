"""Cost-model-driven least-squares solver auto-selection.

Reference: nodes/learning/LeastSquaresEstimator.scala:26-87 — an
OptimizableLabelEstimator whose physical options are Dense LBFGS,
Sparsify→Sparse LBFGS, Densify→BlockLS(1000, 3), and Densify→Exact
NormalEquations; picks minBy(cost(n, d, k, sparsity, numMachines, ...)).
The TPU cost weights live in cost.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.ops.learning.block_ls import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.cost import (
    TPU_CPU_WEIGHT,
    TPU_MEM_WEIGHT,
    TPU_NETWORK_WEIGHT,
)
from keystone_tpu.ops.learning.lbfgs import (
    DenseLBFGSwithL2,
    SparseLBFGSwithL2,
)
from keystone_tpu.ops.learning.linear import LinearMapEstimator
from keystone_tpu.ops.util.nodes import Densify, Sparsify
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator
from keystone_tpu.workflow.chain_utils import TransformerLabelEstimatorChain
from keystone_tpu.workflow.node_optimization import Optimizable


@dataclasses.dataclass(eq=False)
class LeastSquaresEstimator(LabelEstimator, Optimizable):
    lam: float = 0.0
    num_machines: Optional[int] = None
    cpu_weight: float = TPU_CPU_WEIGHT
    mem_weight: float = TPU_MEM_WEIGHT
    network_weight: float = TPU_NETWORK_WEIGHT

    def _options(self):
        dense_lbfgs = DenseLBFGSwithL2(
            reg_param=self.lam, num_iterations=20
        )
        sparse_lbfgs = SparseLBFGSwithL2(
            reg_param=self.lam, num_iterations=20
        )
        block = BlockLeastSquaresEstimator(1000, 3, lam=self.lam)
        exact = LinearMapEstimator(lam=self.lam)
        return [
            (dense_lbfgs, dense_lbfgs),
            (
                sparse_lbfgs,
                TransformerLabelEstimatorChain(Sparsify(), sparse_lbfgs),
            ),
            (block, TransformerLabelEstimatorChain(Densify(), block)),
            (exact, TransformerLabelEstimatorChain(Densify(), exact)),
        ]

    @property
    def default(self) -> LabelEstimator:
        return DenseLBFGSwithL2(reg_param=self.lam, num_iterations=20)

    def fit(self, data: Dataset, labels: Dataset):
        chosen = self.optimize([data, labels], data.n)
        return chosen.fit(data, labels)

    def fit_datasets(self, datasets):
        return self.fit(datasets[0], datasets[1])

    def optimize(self, samples, n_total: int) -> LabelEstimator:
        sample: Dataset = Dataset.of(samples[0])
        sample_labels: Dataset = Dataset.of(samples[1])
        first = sample.first()
        n = max(n_total, sample.n)
        if isinstance(first, jsparse.BCOO):
            d = int(np.prod(first.shape))
            sparsity = float(first.nse) / max(d, 1)
        else:
            arr = np.asarray(first)
            d = int(arr.reshape(-1).shape[0])
            nz = float(np.count_nonzero(arr))
            sparsity = nz / max(d, 1)
        k = int(np.asarray(sample_labels.first()).reshape(-1).shape[0])
        machines = self.num_machines or max(len(jax.devices()), 1)
        return min(
            self._options(),
            key=lambda o: o[0].cost(
                n, d, k, sparsity, machines,
                self.cpu_weight, self.mem_weight, self.network_weight,
            ),
        )[1]

    @property
    def weight(self) -> int:
        return self.default.weight
