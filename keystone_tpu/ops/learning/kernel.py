"""Kernel ridge regression by block Gauss-Seidel on the dual
(arXiv:1602.05310), with RBF kernel generation.

Reference: nodes/learning/KernelGenerator.scala:18-206 (GaussianKernel
column blocks via broadcast + per-partition matmul),
KernelMatrix.scala:17,50 (lazy column-block view w/ caching),
KernelRidgeRegression.scala:37,86-235 (per epoch & column block:
materialize K(:,B), treeReduce K_Bᵀ·W, driver solve of
(K_BB + λI) W_B = Y_B − K_BᵀW + K_BBᵀW_B_old, broadcast + scatter model
update, lineage checkpoint every 25 blocks),
KernelBlockLinearMapper.scala:28 (test-time blockwise K_test(:,B)·W_B
accumulation).

TPU-native: the kernel column block is one fused jitted expression
(‖x‖² + ‖x_B‖² − 2·X X_Bᵀ → exp), the b×k residual contraction psums over
the sharded example axis, the small (b, b) solve goes to the host in f64
(hostsolve.py), and the model update is a dynamic_update_slice — no
broadcast variables. The reference's every-25-blocks lineage checkpoint
becomes a cadenced atomic host snapshot of the model that ``fit`` resumes
from after preemption (``checkpoint_path``; utils/checkpoint.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.block_ls import (
    _f32_mm,
    _psd_solve_device,
    _psd_solve_with_factor,
)
from keystone_tpu.ops.learning.hostsolve import psd_solve_host
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.checkpoint import (
    LoopCheckpointer,
    data_probe,
    two_level_schedule,
)
from keystone_tpu.workflow.api import Estimator, LabelEstimator, Transformer


def _cross_mm_x3(A, B):
    """A·Bᵀ for f32 operands with XLA's 3-pass bf16 algorithm — ~2×
    faster than the 6-pass HIGHEST decomposition at ~1.5e-5 relative
    error, which the RBF distance tolerates: the kernel's sensitivity is
    γ·|d² error| and γ·1.5e-5·‖x‖² ≪ any solver tolerance here."""
    return jax.lax.dot_general(
        A, B, (((1,), (1,)), ((), ())),
        precision=jax.lax.DotAlgorithmPreset.BF16_BF16_F32_X3,
    )


def _rbf_block_body(X, X_norms, gamma, mask, start, width):
    """K(:, B) for a contiguous train block: exp(−γ(‖x‖²+‖x_B‖²−2x·x_B)).
    Pad rows AND pad columns are zeroed — exp(·) of a zero pad vector is
    nonzero and would pollute the Gauss-Seidel solves."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=0)
    nb = jax.lax.dynamic_slice_in_dim(X_norms, start, width, axis=0)
    mask_b = jax.lax.dynamic_slice_in_dim(mask, start, width, axis=0)
    d2 = X_norms[:, None] + nb[None, :] - 2.0 * _cross_mm_x3(X, Xb)
    K = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
    return K * mask[:, None] * mask_b[None, :]


@partial(jax.jit, static_argnames=("width",))
def _rbf_block(X, X_norms, gamma, mask, start, *, width):
    return _rbf_block_body(X, X_norms, gamma, mask, start, width)


@dataclasses.dataclass(eq=False)
class GaussianKernelTransformer(Transformer):
    """Holds the train set; produces kernel blocks against it (reference:
    KernelGenerator.scala:49).

    Precision note (ADVICE r4): the blocked cross term uses XLA's
    3-pass bf16 GEMM (``_cross_mm_x3``, ~1.5e-5 relative error), so the
    absolute kernel error scales as γ·1.5e-5·‖x‖². With normalized
    features and the small γ the apps use (γ·‖x‖² ≲ 10) that is ≤1e-4
    on kernel entries — far below solver tolerance; with LARGE
    γ·‖x‖² (unnormalized features) kernel entries lose accuracy
    proportionally. Normalize features (NormalizeRows) or scale γ
    down accordingly."""

    train_X: Any  # (n_pad, d) device array, pad rows zero
    n_train: int
    gamma: float
    train_mask: Any = None

    def __post_init__(self):
        if self.train_mask is None:
            self.train_mask = (
                jnp.arange(self.train_X.shape[0]) < self.n_train
            ).astype(jnp.float32)
        self._norms = jnp.sum(
            self.train_X.astype(jnp.float32) ** 2, axis=1
        )

    def apply(self, x):
        """kernel row of a single test point vs the whole train set."""
        d2 = (
            jnp.sum(x * x)
            + self._norms
            - 2.0 * (self.train_X @ x).astype(jnp.float32)
        )
        return jnp.exp(-self.gamma * jnp.maximum(d2, 0.0)) * self.train_mask

    def apply_batch(self, ds: Dataset) -> Dataset:
        """Kernel rows vs the train set as a Dataset (pipeline contract);
        KRR uses ``kernel_matrix`` for the lazy block view instead."""
        ds = ds.to_array_mode()
        km = self.kernel_matrix(ds)
        n_pad = self.train_X.shape[0]
        return Dataset.from_array(km.block(0, n_pad), n=ds.n)

    def kernel_matrix(self, ds: Dataset) -> "KernelMatrix":
        ds = ds.to_array_mode()
        return KernelMatrix(self, ds)

    def train_block(self, start: int, width: int) -> jnp.ndarray:
        return _rbf_block(
            self.train_X, self._norms, self.gamma, self.train_mask,
            start, width=width,
        )


@partial(jax.jit, static_argnames=("width",))
def _rbf_cross_block(Xt, Xt_norms, train_X, train_norms, gamma, mask_t,
                     train_mask, start, *, width):
    Xb = jax.lax.dynamic_slice_in_dim(train_X, start, width, axis=0)
    nb = jax.lax.dynamic_slice_in_dim(train_norms, start, width, axis=0)
    mask_b = jax.lax.dynamic_slice_in_dim(train_mask, start, width, axis=0)
    d2 = Xt_norms[:, None] + nb[None, :] - 2.0 * _cross_mm_x3(Xt, Xb)
    K = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
    return K * mask_t[:, None] * mask_b[None, :]


class KernelMatrix:
    """Lazy column-block view of K(test, train) with optional block cache
    (reference: KernelMatrix.scala:17 / BlockKernelMatrix:50)."""

    def __init__(self, transformer: GaussianKernelTransformer, ds: Dataset,
                 cache_blocks: bool = False):
        self.transformer = transformer
        self.ds = ds
        self._X = ds.padded().astype(jnp.float32)
        self._norms = jnp.sum(self._X * self._X, axis=1)
        self._mask = ds.mask()
        self.cache_blocks = cache_blocks
        self._cache: Dict[tuple, jnp.ndarray] = {}

    def block(self, start: int, width: int) -> jnp.ndarray:
        key = (start, width)
        if key in self._cache:
            return self._cache[key]
        out = _rbf_cross_block(
            self._X, self._norms, self.transformer.train_X,
            self.transformer._norms, self.transformer.gamma, self._mask,
            self.transformer.train_mask, start, width=width,
        )
        if self.cache_blocks:
            self._cache[key] = out
        return out

    def diag_block(self, start: int, width: int) -> jnp.ndarray:
        """K_BB for a train-set kernel matrix (square view only —
        dynamic_slice would silently clamp on a rectangular test-vs-train
        matrix)."""
        if self._X.shape[0] < start + width:
            raise ValueError(
                "diag_block requires a square (train) kernel matrix"
            )
        K = self.block(start, width)
        return jax.lax.dynamic_slice_in_dim(K, start, width, axis=0)

    def unpersist(self, start: int, width: int) -> None:
        self._cache.pop((start, width), None)


@dataclasses.dataclass(eq=False)
class GaussianKernelGenerator(Estimator):
    """fit(data) -> GaussianKernelTransformer (reference:
    KernelGenerator.scala:18)."""

    gamma: float

    def fit(self, data: Dataset) -> GaussianKernelTransformer:
        ds = data.to_array_mode()
        X = ds.padded().astype(jnp.float32) * ds.mask()[:, None]
        return GaussianKernelTransformer(X, ds.n, self.gamma, ds.mask())


@partial(jax.jit, static_argnames=("width",))
def _krr_residual(K_block, W, start, *, width):
    """K_Bᵀ W and K_BB from the materialized column block."""
    resid = _f32_mm(K_block.T, W)
    K_bb = jax.lax.dynamic_slice_in_dim(K_block, start, width, axis=0)
    return resid, K_bb


@partial(jax.jit, static_argnames=("width",), donate_argnums=(0,))
def _krr_update_model(W, Wb_new, start, *, width):
    return jax.lax.dynamic_update_slice_in_dim(W, Wb_new, start, axis=0)


def _krr_block_body(X, X_norms, gamma, mask, W, Y, start, lam, width):
    """One whole Gauss-Seidel block update as a single device program:
    materialize K(:, B), form the residual rhs, solve (K_BB + λI) on
    device (f32 Cholesky + refinement, block_ls._psd_solve_device), and
    scatter the block model — the reference's materialize → treeReduce →
    driver-solve → broadcast round trip (KernelRidgeRegression.scala:
    86-235) with zero host synchronization."""
    K_block = _rbf_block_body(X, X_norms, gamma, mask, start, width)
    # contract the example axis without a .T relayout of the n×b block
    resid = jax.lax.dot_general(
        K_block, W, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    K_bb = jax.lax.dynamic_slice_in_dim(K_block, start, width, axis=0)
    Wb_old = jax.lax.dynamic_slice_in_dim(W, start, width, axis=0)
    y_b = jax.lax.dynamic_slice_in_dim(Y, start, width, axis=0)
    rhs = y_b - (resid - _f32_mm(K_bb.T, Wb_old))
    # one refinement step: each extra step is a triangular-solve pair
    # (~3 ms at b=4096), and Gauss-Seidel tolerates per-block solves at
    # f32+1-refine accuracy (validated against the host-f64 path by
    # tests/ops/test_kernel.py)
    Wb_new = _psd_solve_device(K_bb, rhs, lam, refine=1)
    return jax.lax.dynamic_update_slice_in_dim(W, Wb_new, start, axis=0)


@partial(jax.jit, static_argnames=("width",), donate_argnums=(4,))
def _krr_block_step(X, X_norms, gamma, mask, W, Y, start, lam, *, width):
    return _krr_block_body(X, X_norms, gamma, mask, W, Y, start, lam,
                           width)


@partial(jax.jit, static_argnames=("width",), donate_argnums=(4,))
def _krr_cached_epoch_scan(X, X_norms, gamma, mask, W, Y,
                           block_idx, lam, *, width):
    """Gauss-Seidel with the kernel matrix CACHED in HBM — the
    reference's ``cacheKernel`` mode (KernelMatrix.scala:50,
    BlockKernelMatrix). Three stages, one dispatch:

    1. build all column blocks once (scan, stacked ys) — multi-epoch
       fits stop regenerating K(:, B) every sweep (the regeneration
       GEMM is ~70 ms/epoch at the bench shape, the dominant per-epoch
       cost);
    2. factorize ALL diagonal blocks as one batched Cholesky — the 12
       sequential 4096² factorizations (~26 ms measured) become one
       batched kernel (~10 ms): across-batch panels run in parallel on
       the MXU, and the factor bank is reused by every later epoch;
    3. sweep: per block, residual contraction + two triangular-solve
       pairs (solve + 1 refinement) against the prebuilt factor.

    Memory: the cache holds n_pad² + nb·b² f32 — ``fit`` gates this
    path on the measured device budget and falls back to the
    regenerate-per-block scan (``_krr_epoch_scan``)."""
    n_pad = X.shape[0]
    nb = n_pad // width
    eye = jnp.eye(width, dtype=jnp.float32)
    hp = jax.lax.Precision.HIGHEST

    def build(c, i):
        s = i * width
        Kb = _rbf_block_body(X, X_norms, gamma, mask, s, width)
        Ab = jax.lax.dynamic_slice_in_dim(Kb, s, width, axis=0) + lam * eye
        return c, (Kb, Ab)

    _, (Kcols, Ab) = jax.lax.scan(build, jnp.float32(0), jnp.arange(nb))
    Lb = jnp.linalg.cholesky(Ab)

    def step(W, bi):
        s = bi * width
        Kcol = jax.lax.dynamic_index_in_dim(Kcols, bi, 0, keepdims=False)
        resid = jax.lax.dot_general(
            Kcol, W, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=hp,
        )
        K_bb = jax.lax.dynamic_slice_in_dim(Kcol, s, width, axis=0)
        Wb_old = jax.lax.dynamic_slice_in_dim(W, s, width, axis=0)
        y_b = jax.lax.dynamic_slice_in_dim(Y, s, width, axis=0)
        rhs = y_b - (resid - _f32_mm(K_bb.T, Wb_old))
        L = jax.lax.dynamic_index_in_dim(Lb, bi, 0, keepdims=False)
        # refine=1 matches the uncached scan's _psd_solve_device call
        # (validated by the same f64-parity tests); the helper carries
        # the eigh-breakdown fallback and its >8192 gating
        Wb_new = _psd_solve_with_factor(K_bb + lam * eye, L, rhs, refine=1)
        return jax.lax.dynamic_update_slice_in_dim(W, Wb_new, s, axis=0), None

    W, _ = jax.lax.scan(step, W, block_idx)
    return W


@partial(jax.jit, static_argnames=("width",), donate_argnums=(4,))
def _krr_epoch_scan(X, X_norms, gamma, mask, W, Y, starts, lam, *, width):
    """A whole epoch (or several) of Gauss-Seidel block updates as ONE
    scanned device program — per-block dispatches each cost ~15-30 ms of
    queue latency through a remote tunnel, which at 12 blocks dominated
    the r3 krr_block_solve row (PROFILE_r04)."""

    def step(W, start):
        return _krr_block_body(
            X, X_norms, gamma, mask, W, Y, start, lam, width
        ), None

    W, _ = jax.lax.scan(step, W, starts)
    return W


@dataclasses.dataclass(eq=False)
class KernelBlockLinearMapper(Transformer):
    """Test-time apply: accumulate K_test(:, B) · W_B over blocks
    (reference: KernelBlockLinearMapper.scala:28)."""

    model: Any  # (n_train_pad, k)
    block_size: int
    kernel_transformer: GaussianKernelTransformer
    n_train: int

    def apply(self, x):
        k_row = self.kernel_transformer.apply(x)
        return k_row @ self.model

    def apply_batch(self, ds: Dataset) -> Dataset:
        ds = ds.to_array_mode()
        km = self.kernel_transformer.kernel_matrix(ds)
        n_pad = self.kernel_transformer.train_X.shape[0]
        out = jnp.zeros(
            (ds.padded_n, self.model.shape[1]), jnp.float32
        )
        for start in range(0, n_pad, self.block_size):
            width = min(self.block_size, n_pad - start)
            Kb = km.block(start, width)
            Wb = jax.lax.dynamic_slice_in_dim(
                self.model, start, width, axis=0
            )
            out = out + _f32_mm(Kb, Wb)
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class KernelRidgeRegression(LabelEstimator):
    """(K + λI) W = Y via column-block Gauss-Seidel (reference:
    KernelRidgeRegression.scala:37)."""

    kernel_generator: GaussianKernelGenerator
    lam: float
    block_size: int
    num_epochs: int
    block_permuter: Optional[int] = None
    solve: str = "device"  # "device": f32 Cholesky + iterative refinement
    # in the dispatch stream (same discipline as BlockLS — a host solve
    # costs a ~100 ms sync per block through a remote-dispatch link) |
    # "host": f64 LAPACK per block for pathological conditioning
    checkpoint_path: Optional[str] = None  # periodic model snapshot every
    # ``checkpoint_every`` block solves; a re-run with the same path
    # resumes at the last completed block (reference checkpoints lineage
    # every 25 blocks: KernelRidgeRegression.scala:200-210)
    checkpoint_every: int = 25
    block_callback: Optional[Any] = None  # called with a running count
    # after each completed block solve
    cache_kernel: Optional[bool] = None  # cache the whole train kernel
    # matrix in HBM + batch-factorize the diagonal blocks (the
    # reference's cacheKernel mode, KernelMatrix.scala:50). None = auto:
    # on when the cache fits the device budget AND num_epochs > 1 —
    # measured on the v5e at the bench shape (49k × 1024, b=4096):
    # marginal epoch cost drops 142 → 40 ms device (epoch 2+ skips
    # kernel regeneration; diagonal factors come from one batched
    # Cholesky bank), 1.79× at 3 epochs, but the one-epoch fit pays
    # ~+14 ms of cache-build overhead. Same math (refine=1 Cholesky,
    # eigh fallback; rel diff 6e-6), validated by the same parity tests.

    def _epoch_order(self, epoch: int, n_blocks: int) -> List[int]:
        """Block order for an epoch, seeded per (permuter, epoch) so a
        resumed fit replays the identical schedule.

        NOTE: this changed the schedule for a given ``block_permuter``
        relative to the pre-checkpointing implementation (one RNG stream
        across epochs); models fit with the same seed before/after differ
        numerically (both are valid Gauss-Seidel orders)."""
        order = list(range(n_blocks))
        if self.block_permuter is not None:
            np.random.default_rng(
                (self.block_permuter, epoch)
            ).shuffle(order)
        return order

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        from keystone_tpu.utils.profiling import PhaseTimer

        if self.solve not in ("device", "host"):
            raise ValueError(f"solve must be 'device' or 'host', got {self.solve!r}")
        # per-phase wall clock, published as registry metrics
        # (keystone_phase_seconds_total{timer="krr_fit"}) — the
        # scrapeable version of the reference's kernelGen/residual/
        # localSolve/modelUpdate log lines (KernelRidgeRegression.scala:
        # 213-221); device-path phases are enqueue time (dispatch is
        # async), host-path phases include the blocking f64 solve
        timer = PhaseTimer("krr_fit")
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        transformer = self.kernel_generator.fit(data)
        X = transformer.train_X
        n = data.n
        n_pad = X.shape[0]
        Y = labels.padded().astype(jnp.float32)
        k = Y.shape[1]

        blocks = [
            (s, min(s + self.block_size, n_pad) - s)
            for s in range(0, n_pad, self.block_size)
        ]
        W = jnp.zeros((n_pad, k), jnp.float32)

        ckpt = None
        start_epoch, start_pos = 0, 0
        if self.checkpoint_path is not None:
            # n_pad is stamped too: the snapshot W and block layout are
            # n_pad-shaped, and n_pad varies with mesh shard count
            fp = (
                f"krr bs={self.block_size} ep={self.num_epochs} "
                f"lam={self.lam} gamma={self.kernel_generator.gamma} "
                f"perm={self.block_permuter} n={n} n_pad={n_pad} k={k} "
                f"solve={self.solve} "
                f"probe={data_probe(X, Y)}"
            )
            ckpt = LoopCheckpointer(self.checkpoint_path,
                                    self.checkpoint_every, fingerprint=fp)
            state = ckpt.load()
            if state is not None:
                W = jnp.asarray(state["W"], jnp.float32)
                start_epoch = int(state["epoch"])
                start_pos = int(state["pos"])

        if (
            self.solve == "device"
            and ckpt is None
            and self.block_callback is None
            and len({wd for _, wd in blocks}) == 1
        ):
            # fast path: every epoch's whole block schedule as one
            # scanned program, one dispatch for the entire fit
            order = [
                i
                for epoch in range(self.num_epochs)
                for i in self._epoch_order(epoch, len(blocks))
            ]
            width = blocks[0][1]
            use_cached = self.cache_kernel
            if use_cached is None:
                from keystone_tpu.ops.learning.weighted_ls import (
                    _device_memory_limit,
                )
                # cache bytes: stacked column blocks + factor bank +
                # one (n_pad, b) transient; leave room for X/W/Y and
                # the eigh fallback workspace
                cache_bytes = 4 * (
                    n_pad * n_pad
                    + len(blocks) * width * width
                    + n_pad * width
                )
                use_cached = (
                    self.num_epochs > 1
                    and cache_bytes <= 0.6 * _device_memory_limit()
                )
            with timer.phase("epoch_scan"):
                if use_cached:
                    W = _krr_cached_epoch_scan(
                        transformer.train_X, transformer._norms,
                        transformer.gamma, transformer.train_mask,
                        W, Y, jnp.asarray(order, jnp.int32), self.lam,
                        width=width,
                    )
                else:
                    all_starts = jnp.asarray(
                        [blocks[i][0] for i in order], jnp.int32
                    )
                    W = _krr_epoch_scan(
                        transformer.train_X, transformer._norms,
                        transformer.gamma, transformer.train_mask,
                        W, Y, all_starts, self.lam, width=width,
                    )
            timer.publish()
            return KernelBlockLinearMapper(
                W, self.block_size, transformer, n
            )

        if self.cache_kernel:
            # the cached program is the single-dispatch scan; the
            # per-block loop below (host solves, checkpoint ticks,
            # callbacks, ragged widths) regenerates K(:, B) each visit
            import warnings

            warnings.warn(
                "cache_kernel=True has no effect with solve='host', "
                "checkpoint_path, block_callback, or non-uniform block "
                "widths — falling back to per-block kernel regeneration",
                stacklevel=2,
            )

        done = 0
        order, order_epoch = [], -1
        for epoch, pos, nxt in two_level_schedule(
            self.num_epochs, len(blocks), (start_epoch, start_pos)
        ):
            if epoch != order_epoch:
                order = self._epoch_order(epoch, len(blocks))
                order_epoch = epoch
            s, wd = blocks[order[pos]]
            if self.solve == "device":
                # whole block update — kernel block, residual, solve,
                # model scatter — stays in the async dispatch stream
                with timer.phase("block_step"):
                    W = _krr_block_step(
                        transformer.train_X, transformer._norms,
                        transformer.gamma, transformer.train_mask,
                        W, Y, s, self.lam, width=wd,
                    )
            else:
                with timer.phase("kernel_block"):
                    K_block = transformer.train_block(s, wd)  # (n_pad, b)
                with timer.phase("residual"):
                    resid, K_bb = _krr_residual(K_block, W, s, width=wd)
                    Wb_old = jax.lax.dynamic_slice_in_dim(W, s, wd, axis=0)
                    y_b = jax.lax.dynamic_slice_in_dim(Y, s, wd, axis=0)
                    rhs = y_b - (resid - _f32_mm(K_bb.T, Wb_old))
                # pad rows inside the block: K_bb row/col is zero there,
                # λI makes the system nonsingular, W stays 0 via rhs=0
                with timer.phase("host_solve"):
                    Wb_new = jnp.asarray(
                        psd_solve_host(K_bb, np.asarray(rhs), self.lam),
                        jnp.float32,
                    )
                with timer.phase("model_update"):
                    W = _krr_update_model(W, Wb_new, s, width=wd)
            done += 1
            if ckpt is not None:
                ckpt.tick(lambda: {
                    "W": np.asarray(W), "epoch": nxt[0], "pos": nxt[1],
                })
            if self.block_callback is not None:
                self.block_callback(done)
        if ckpt is not None:
            ckpt.clear()
        timer.publish()

        return KernelBlockLinearMapper(
            W, self.block_size, transformer, n
        )
