"""K-Means++ (init + Lloyd's).

Reference: nodes/learning/KMeansPlusPlus.scala — KMeansModel emits the
one-hot nearest-center assignment matrix (:16-70); the estimator runs
k-means++ seeding then Lloyd's with a cost-improvement stop (:83-181).
Lloyd's iterations are jitted device matmuls; the sequential seeding loop
runs on host over the (local) sample like the reference's driver-side fit.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, Transformer


@jax.jit
def _sq_dist_to_centers(X, means):
    """0.5·‖x−μ‖² matrix, (n, k) — the reference's 'slick vectorized'
    XSqNormHlf − X μᵀ + MSqNormHlf."""
    xsq = 0.5 * jnp.sum(X * X, axis=1, keepdims=True)
    msq = 0.5 * jnp.sum(means * means, axis=1)
    return xsq - mm(X, means.T) + msq[None, :]


@jax.jit
def _assign_one_hot(X, means):
    d = _sq_dist_to_centers(X, means)
    nearest = jnp.argmin(d, axis=1)
    return jax.nn.one_hot(nearest, means.shape[0], dtype=X.dtype)


@dataclasses.dataclass(eq=False)
class KMeansModel(Transformer):
    means: Any  # (k, d)

    def apply(self, x):
        return _assign_one_hot(x[None, :], self.means)[0]

    def apply_batch(self, ds: Dataset) -> Dataset:
        out = _assign_one_hot(ds.padded(), self.means)
        return Dataset.from_array(out * ds.mask()[:, None], n=ds.n)


@dataclasses.dataclass(eq=False)
class KMeansPlusPlusEstimator(Estimator):
    """One round = pure k-means++ initialization; more rounds = Lloyd's
    with k-means++ seeding (reference: KMeansPlusPlus.scala:83)."""

    num_means: int
    max_iterations: int
    stop_tolerance: float = 1e-3
    seed: int = 0

    def fit(self, data) -> KMeansModel:
        if isinstance(data, Dataset):
            X = np.asarray(data.array(), np.float64)
        else:
            X = np.asarray(data, np.float64)
        return self.fit_matrix(X)

    def fit_matrix(self, X: np.ndarray) -> KMeansModel:
        n = X.shape[0]
        rng = np.random.default_rng(self.seed)
        xsq_half = 0.5 * np.sum(X * X, axis=1)

        # -- k-means++ seeding (host; sequential by construction) ---------
        centers = np.zeros(self.num_means, dtype=np.int64)
        centers[0] = rng.integers(0, n)
        cur_sq_dist = None
        for k in range(self.num_means - 1):
            c = X[centers[k]]
            # host f64 numpy on purpose: seeding is sequential and its
            # distances feed a probability draw — keep full precision
            d_new = xsq_half - X @ c + 0.5 * (c @ c)
            cur_sq_dist = (
                d_new if cur_sq_dist is None else np.minimum(d_new, cur_sq_dist)
            )
            p = np.maximum(cur_sq_dist, 0.0)
            total = p.sum()
            if total <= 0:
                centers[k + 1] = rng.integers(0, n)
            else:
                centers[k + 1] = rng.choice(n, p=p / total)
        means = jnp.asarray(X[centers], jnp.float32)

        # -- Lloyd's (device) ---------------------------------------------
        Xd = jnp.asarray(X, jnp.float32)
        prev_cost = None
        for _ in range(self.max_iterations):
            d = _sq_dist_to_centers(Xd, means)
            cost = float(jnp.mean(jnp.min(d, axis=1)))
            assign = jax.nn.one_hot(
                jnp.argmin(d, axis=1), self.num_means, dtype=jnp.float32
            )
            mass = jnp.sum(assign, axis=0)
            means = mm(assign.T, Xd) / jnp.maximum(mass, 1.0)[:, None]
            if prev_cost is not None and (
                prev_cost - cost
            ) < self.stop_tolerance * abs(prev_cost):
                break
            prev_cost = cost
        return KMeansModel(means)
