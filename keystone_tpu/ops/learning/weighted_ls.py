"""Weighted block coordinate descent for per-class mixture-weighted least
squares — the ImageNet flagship solver.

Reference: nodes/learning/BlockWeightedLeastSquares.scala:36,102-320.
The objective re-weights each class's examples by ``mixture_weight`` w:
per class c the solve uses joint statistics
    jointXTX_c = (1−w)·popCov + w·classCov_c + w(1−w)·δ_c δ_cᵀ
    jointXTR_c = (1−w)·popXTR[:,c] + w·classXTR_c − jointMean_c·mmw_c
with δ_c = classMean_c − popMean and
mmw_c = (1−w)·residualMean_c + w·mean(resLocal_c).

The reference requires a partition-per-class layout (groupByClasses with
HashPartitioner(nClasses), :332-369) so per-class statistics are
partition-local. TPU-native equivalent: sort rows by class ONCE into a
(C, m, ·) class-grouped gather index (classes padded to the max class
size with zero-weight rows) — the EP-style grouping of SURVEY §2.10 —
then per-class covariances are one batched einsum over class chunks and
the per-class (b, b) solves are one batched Cholesky, all on device.
Total flops match the reference (Σ_c n_c·b² = n·b²); no shuffle, no
driver round trip, no distributed System.gc().
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.block_ls import BlockLinearMapper, _f32_mm
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


@partial(jax.jit, static_argnames=("G", "m", "width"))
def _class_chunk_stats(Xg, R, wt, counts, class_ids, c0, start,
                       *, G, m, width):
    """Per-class covariance/XTR for one chunk of classes, reading the
    CLASS-GROUPED feature layout.

    Xg: (C·m, D) features grouped by class (class c occupies rows
    [c·m, (c+1)·m), padded slots zeroed); R: (C·m, C) residual in the
    same row order; wt: (C, m) 0/1 validity; counts: (C,);
    class_ids: (G,) class index of each chunk row; c0: first class of
    the chunk. Returns classCov (G, b, b), classMean (G, b),
    classXTR (G, b), resLocalMean (G,).

    Grouping means every read here is a contiguous dynamic-slice — the
    per-chunk row gathers this replaced were re-gathering the whole
    dataset once per block (TPU row-gather is far below stream
    bandwidth; measured 10 TFLOP/s on the r3 bench before this).
    """
    D = Xg.shape[1]
    C = R.shape[1]
    Xc = jax.lax.dynamic_slice(
        Xg.reshape(-1, m, D), (c0, 0, start), (G, m, width)
    )  # (G, m, b) — padded slots are already zero
    wc = jax.lax.dynamic_slice(wt, (c0, 0), (G, m))
    inv = 1.0 / jax.lax.dynamic_slice(counts, (c0,), (G,))
    # resLocal_c = R[rows of c, c] — a (G, m, C) contiguous slice then a
    # per-class column pick
    Rc = jax.lax.dynamic_slice(
        R.reshape(-1, m, C), (c0, 0, 0), (G, m, C)
    )
    r_g = (
        jnp.take_along_axis(Rc, class_ids[:, None, None], axis=2)[..., 0]
        * wc
    )  # (G, m)
    class_mean, class_xtr, res_local_mean = _chunk_moments(Xc, r_g, inv)
    # HIGHEST for f32 inputs: the centered covariance cancels mean^2-
    # scale terms; TPU DEFAULT precision would truncate f32 operands to
    # bf16 passes (block_ls._f32_mm documents the measured failure).
    # bf16 inputs ride the native bf16xbf16->f32 MXU path.
    hp = (
        jax.lax.Precision.HIGHEST
        if Xc.dtype == jnp.float32 else None
    )
    class_cov = (
        jnp.einsum("gmb,gmc->gbc", Xc, Xc,
                   preferred_element_type=jnp.float32, precision=hp)
        * inv[:, None, None]
        - class_mean[:, :, None] * class_mean[:, None, :]
    )
    return class_cov, class_mean, class_xtr, res_local_mean


@jax.jit
def _group_rows(X, Y, idx, wt, joint_label_mean):
    """ONE gather into the class-grouped layout: Xg (C·m, D) with padded
    slots zeroed, and the initial residual R (C·m, C) = (Y − jlm)·wt in
    the same row order. This is the only non-contiguous memory access of
    the whole fit."""
    flat = idx.reshape(-1)
    w = wt.reshape(-1)
    Xg = X[flat] * w[:, None].astype(X.dtype)
    R = (Y[flat] - joint_label_mean[None, :]) * w[:, None]
    return Xg, R


@partial(jax.jit, static_argnames=("width", "n"))
def _pop_stats(X, R, mask, start, *, width, n):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    pop_mean = jnp.einsum("nb->b", Xb * mask[:, None]) / n
    pop_cov = _f32_mm(Xb.T, Xb) / n - jnp.outer(pop_mean, pop_mean)
    pop_xtr = _f32_mm(Xb.T, R) / n
    return pop_mean, pop_cov, pop_xtr


@jax.jit
def _batched_psd_solve(A, B, lam):
    """Solve (A_g + λI) x_g = B_g batched, Jacobi-preconditioned f32
    Cholesky (systems are covariance-normalized, O(1) scale)."""
    b = A.shape[-1]
    A = A + lam * jnp.eye(b, dtype=A.dtype)[None]
    d = jnp.sqrt(jnp.maximum(jnp.diagonal(A, axis1=1, axis2=2), 1e-12))
    scale = d[:, :, None] * d[:, None, :]
    An = A / scale
    L = jnp.linalg.cholesky(An)
    Bn = B / d[:, :, None] if B.ndim == 3 else (B / d)[:, :, None]
    y = jax.scipy.linalg.solve_triangular(L, Bn, lower=True)
    x = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, 1, 2), y, lower=False
    )
    return x[:, :, 0] / d if B.ndim == 2 else x / d[:, :, None]


@partial(jax.jit, static_argnames=("width",), donate_argnums=(1,))
def _apply_delta(X, R, delta, start, *, width):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    return R - _f32_mm(Xb, delta)


def _device_memory_limit() -> int:
    """Best-effort device memory size in bytes (budget input for the
    chol-path grouped-copy decision). Accelerators without stats (the
    axon tunnel) fall back to 16 GiB (v5e); CPU backends without stats
    budget from HOST RAM instead — a flat 16 GiB there could drive the
    grouped-layout decision to OOM a small CPU host (ADVICE r4), and
    ``layout='gathered'`` stays the manual escape hatch. The stats
    probe itself is the shared None-guarded helper in
    ``observability/device.py`` (one code path with auto_cache and the
    memory telemetry gauges)."""
    from keystone_tpu.observability.device import (
        device_memory_stats,
        host_memory_stats,
    )

    dev = jax.devices()[0]
    stats = device_memory_stats(dev)
    if stats and "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    if dev.platform == "cpu":
        host = host_memory_stats()
        if host and "bytes_limit" in host and "bytes_in_use" in host:
            # budget a quarter of available RAM: the layout copy
            # competes with the data itself + the OS
            return (host["bytes_limit"] - host["bytes_in_use"]) // 4
        return 4 * 1024**3
    return 16 * 1024**3


@jax.jit
def _precond_inverse(pop_cov, w, lam):
    """EXPLICIT inverse of the shared CG preconditioner M = (1−w)·popCov
    + (λ+ε·scale)·I, via one Cholesky + cho_solve against I (~3 ms at
    b=4096 on v5e). The r3 implementation kept the factor and did two
    triangular solves per CG iteration — measured 5 ms/iteration for a
    16-rhs chunk, which at 8 chunks × ~8 iterations × 2 blocks was the
    single largest cost of the flagship fit (PROFILE_r04). As a GEMM the
    per-iteration apply is ~0.2 ms. Inverse rounding (κ(M)·ε_f32) only
    perturbs the preconditioner, never the solution; symmetrization
    keeps PCG's SPD contract.

    The ε jitter guards rank-deficient population covariances (λ may be
    0); it biases only the preconditioner, never the solution."""
    b = pop_cov.shape[0]
    eps = 1e-6 * jnp.maximum(jnp.trace(pop_cov) / b, 1e-12)
    M = (1.0 - w) * pop_cov + (lam + eps) * jnp.eye(b, dtype=pop_cov.dtype)
    L = jnp.linalg.cholesky(M)
    Minv = jax.scipy.linalg.cho_solve(
        (L, True), jnp.eye(b, dtype=pop_cov.dtype)
    )
    return (Minv + Minv.T) * 0.5


def _chunk_moments(Xc, r_g, inv):
    """Shared per-chunk moments: classMean (G, b), classXTR (G, b),
    resLocalMean (G,). Invariant: padded slots of Xc and r_g are ZEROED
    by the caller (grouping or gather wrappers), so plain sums are
    per-class sums. Precision policy: f32 accumulation everywhere; the
    r_g contraction is always f32 (residual) -> HIGHEST."""
    f32 = jnp.float32
    cmean = (
        jnp.einsum("gmb->gb", Xc, preferred_element_type=f32)
        * inv[:, None]
    )
    cxtr = (
        jnp.einsum("gmb,gm->gb", Xc, r_g,
                   preferred_element_type=f32,
                   precision=jax.lax.Precision.HIGHEST)
        * inv[:, None]
    )
    rlm = jnp.einsum("gm->g", r_g) * inv
    return cmean, cxtr, rlm


def _limb3(a, axis):
    """Split an f32 array into 3 bf16 limbs concatenated along ``axis``
    (hi+mid+lo carries ~24 mantissa bits, relative error ~2^-24). A
    contraction of bf16 data against the concatenated limbs is ONE
    native-MXU GEMM that reads the big operand once and recovers f32
    accuracy by summing the three output slabs — versus XLA's 6-pass
    HIGHEST decomposition for f32 operands (bf16 x bf16 products are
    exact in the MXU's f32 accumulator, so only the f32 side needs
    splitting)."""
    hi = a.astype(jnp.bfloat16)
    r1 = a - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([hi, mid, lo], axis=axis)


def _sum3(t, axis):
    """Sum the 3 limb slabs of a contraction against ``_limb3`` output."""
    k = t.shape[axis] // 3
    s0 = jax.lax.slice_in_dim(t, 0, k, axis=axis)
    s1 = jax.lax.slice_in_dim(t, k, 2 * k, axis=axis)
    s2 = jax.lax.slice_in_dim(t, 2 * k, 3 * k, axis=axis)
    return s0 + s1 + s2


def _dot00(a, b):
    """dot_general contracting both leading axes (no transpose relayout),
    f32 accumulation."""
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot11(a, b):
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot10(a, b):
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _pcg_block_core(X, R, P, Wb, inv_counts, valid, start, w, lam,
                    *, width, n, max_iters=96, tol=1e-6):
    """One whole weighted-BCD block update for ALL classes at once, on
    the ORIGINAL (ungrouped) row layout, as a single device program:
    population stats, shared-preconditioner inverse, batched matrix-free
    PCG over the C per-class systems, and the residual update.

    This replaced the r3 design (class-grouped gather + 8 class-chunks,
    each its own CG with triangular-solve preconditioning) after
    PROFILE_r04 measured the chunked TRSMs at 5 ms/CG-iteration — the
    largest single cost of the flagship fit. Here:

    - per-class contractions ride ONE-HOT GEMMs: with P (n, C) the 0/1
      class-membership matrix, classMean = PᵀX_b, resLocal = (R ⊙ P)·1,
      and the CG matvec's class-restricted products
      X_bᵀ(diag(z) X_b v_c-per-row) become two (n,b)x(b,3C)-shaped MXU
      GEMMs via ``_limb3`` — no grouping gather (the r3 grouped copy
      doubled HBM and cost ~160 ms), no host-side index building, no
      per-chunk padding pathology for skewed classes (ADVICE r3), and
      every CG iteration reads X_b exactly twice at stream bandwidth;
    - all C systems share one CG loop (the per-class solves are batched
      rows of the iterate), preconditioned by the explicit inverse of
      M = (1−w)·popCov + (λ+ε)I (see ``_precond_inverse``) applied as
      one small GEMM per iteration;
    - the solve is matrix-free:
        A_c v = (1−w)·popCov·v + w·(X_cᵀ(X_c v)/n_c − μ_c(μ_cᵀv))
                + w(1−w)·δ_c(δ_cᵀv) + λv
      so no (C, b, b) covariances are ever materialized.

    Returns (Wb_new, R_new, jointMeans (C, b), exit max rel residual,
    CG iteration count). ``R`` is donated.
    """
    hp = jax.lax.Precision.HIGHEST
    f32 = jnp.float32
    C = R.shape[1]
    bf16_data = X.dtype == jnp.bfloat16

    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Pf = P.astype(f32)

    def onehot_scale_limbs(z):
        """(n,) f32 -> (n, 3C) bf16 = the 3 limbs of P ⊙ z, built from
        z's SCALAR limbs (P is exactly 0/1 in bf16, so P·z_limb is an
        exact bf16 product) — skips materializing the (n, C) f32
        product and its 3 re-reads that ``_limb3`` would need."""
        z0 = z.astype(jnp.bfloat16)
        r1 = z - z0.astype(f32)
        z1 = r1.astype(jnp.bfloat16)
        z2 = (r1 - z1.astype(f32)).astype(jnp.bfloat16)
        return jnp.concatenate(
            [P * z0[:, None], P * z1[:, None], P * z2[:, None]], axis=1
        )

    def mm_bf16_f32_00(a_f32):
        """X_bᵀ · a for f32 ``a`` (n, k): one X_b read via limbs when
        X_b is bf16, 6-pass HIGHEST otherwise (small test problems)."""
        if bf16_data:
            return _sum3(_dot00(Xb, _limb3(a_f32, 1)), axis=1)
        return jax.lax.dot_general(
            Xb, a_f32, (((0,), (0,)), ((), ())),
            preferred_element_type=f32, precision=hp,
        )

    def mm_bf16_f32_11(a_f32):
        """X_b · aᵀ for f32 ``a`` (k, b) -> (n, k), one X_b read."""
        if bf16_data:
            return _sum3(_dot11(Xb, _limb3(a_f32, 0)), axis=1)
        return jax.lax.dot_general(
            Xb, a_f32, (((1,), (1,)), ((), ())),
            preferred_element_type=f32, precision=hp,
        )

    def mm_bf16_f32_10(a_f32):
        """X_b · a for f32 ``a`` (b, k) -> (n, k), one X_b read."""
        if bf16_data:
            return _sum3(_dot10(Xb, _limb3(a_f32, 1)), axis=1)
        return jax.lax.dot_general(
            Xb, a_f32, (((1,), (0,)), ((), ())),
            preferred_element_type=f32, precision=hp,
        )

    # -- population stats + per-class moments (pad rows of X and R are
    # zero by the Dataset padding contract) -------------------------------
    if bf16_data:
        gram = _dot00(Xb, Xb)
        # ONE X_b read for all three moment contractions: class sums
        # (one-hot columns), XᵀR (3 limbs), and Xᵀ(P⊙r) (3 limbs)
        r = jnp.einsum("nc,nc->n", R, Pf)  # own-class residual per row
        cols = jnp.concatenate(
            [P, _limb3(R, 1), onehot_scale_limbs(r)], axis=1
        )  # (n, 7C) bf16
        G = _dot00(Xb, cols)  # (b, 7C)
        C_ = R.shape[1]
        cmean = G[:, :C_].T * inv_counts[:, None]  # (C, b)
        pop_xtr = _sum3(G[:, C_: 4 * C_], axis=1) / n  # (b, C)
        cxtr = (
            _sum3(G[:, 4 * C_:], axis=1).T * inv_counts[:, None]
        )  # (C, b)
    else:
        gram = jax.lax.dot_general(
            Xb, Xb, (((0,), (0,)), ((), ())),
            preferred_element_type=f32, precision=hp,
        )
        pop_xtr = mm_bf16_f32_00(R) / n  # (b, C)
        cmean = jax.lax.dot_general(
            Pf, Xb, (((0,), (0,)), ((), ())),
            preferred_element_type=f32, precision=hp,
        ) * inv_counts[:, None]
        r = jnp.einsum("nc,nc->n", R, Pf)
        cxtr = mm_bf16_f32_00(Pf * r[:, None]).T * inv_counts[:, None]
    # popMean = Σ_c n_c·classMean_c / n (P already excludes pad rows and
    # empty classes contribute zero) — no extra X pass
    counts = valid / inv_counts
    pop_mean = jnp.einsum("c,cb->b", counts, cmean) / n
    pop_cov = gram / n - jnp.outer(pop_mean, pop_mean)
    residual_mean = jnp.einsum("nc->c", R) / n
    rlm = jnp.einsum("nc,n->c", Pf, r) * inv_counts

    Minv = _precond_inverse(pop_cov, w, lam)

    mean_diff = cmean - pop_mean[None, :]
    jm = cmean * w + pop_mean[None, :] * (1.0 - w)
    mmw = residual_mean * (1.0 - w) + w * rlm
    joint_xtr = pop_xtr.T * (1.0 - w) + cxtr * w - jm * mmw[:, None]
    rhs = joint_xtr - Wb.T * lam  # (C, b)

    def matvec(v):  # (C, b) -> (C, b)
        pv = (1.0 - w) * jnp.matmul(v, pop_cov, precision=hp)
        T = mm_bf16_f32_11(v)  # (n, C) rows X_b·v_c for every class c
        z = jnp.einsum("nc,nc->n", T, Pf)  # pick own-class entry
        if bf16_data:
            xxv = _sum3(_dot00(Xb, onehot_scale_limbs(z)), axis=1).T
        else:
            xxv = mm_bf16_f32_00(Pf * z[:, None]).T  # (C, b)
        cm_dot = jnp.einsum("gb,gb->g", cmean, v, precision=hp)
        ccov_v = xxv * inv_counts[:, None] - cmean * cm_dot[:, None]
        dd = (
            mean_diff
            * jnp.einsum("gb,gb->g", mean_diff, v, precision=hp)[:, None]
            * (w * (1.0 - w))
        )
        return pv + w * ccov_v + dd + lam * v

    def minv(r_):  # explicit-inverse preconditioner as ONE GEMM
        return jnp.matmul(r_, Minv, precision=hp)

    tiny = jnp.asarray(1e-30, f32)
    b_norm = jnp.maximum(jnp.linalg.norm(rhs, axis=1), tiny)

    def rel_res(r_):
        return jnp.max(jnp.linalg.norm(r_, axis=1) / b_norm)

    def cg_loop(mv, x_init, r_init, it_init, iter_cap, exit_tol):
        def cond(state):
            it, x, r_, z, p_, rz = state
            return jnp.logical_and(it < iter_cap, rel_res(r_) > exit_tol)

        def body(state):
            it, x, r_, z, p_, rz = state
            Ap = mv(p_)
            denom = jnp.einsum("gb,gb->g", p_, Ap, precision=hp)
            alpha = jnp.where(
                denom > 0, rz / jnp.maximum(denom, tiny), 0.0
            )
            x = x + alpha[:, None] * p_
            r_ = r_ - alpha[:, None] * Ap
            z = minv(r_)
            rz_new = jnp.einsum("gb,gb->g", r_, z, precision=hp)
            beta = jnp.where(rz > 0, rz_new / jnp.maximum(rz, tiny), 0.0)
            p_ = z + beta[:, None] * p_
            return it + 1, x, r_, z, p_, rz_new

        z0 = minv(r_init)
        rz0 = jnp.einsum("gb,gb->g", r_init, z0, precision=hp)
        return jax.lax.while_loop(
            cond, body, (it_init, x_init, r_init, z0, z0, rz0)
        )

    # single-phase exact-operator CG. (A two-phase variant — 2-limb
    # warm start + exact restart — was measured at parity: the cheaper
    # operator's error perturbs the CG directions enough that total
    # iterations grow ~20%, cancelling the per-iteration savings.)
    x0 = jnp.zeros_like(rhs)
    it, dW, r_fin, _, _, _ = cg_loop(
        matvec, x0, rhs, jnp.asarray(0), max_iters, tol
    )

    # -- apply the update --------------------------------------------------
    delta = (dW * valid[:, None]).T  # (b, C), empty classes masked
    Wb_new = Wb + delta
    R_new = R - mm_bf16_f32_10(delta)
    return Wb_new, R_new, jm * valid[:, None], rel_res(r_fin), it


@partial(
    jax.jit,
    static_argnames=("width", "n", "max_iters", "tol"),
    donate_argnums=(1,),
)
def _pcg_block_step(X, R, P, Wb, inv_counts, valid, start, w, lam,
                    *, width, n, max_iters=96, tol=1e-6):
    """Single-block dispatch of ``_pcg_block_core`` (used for non-uniform
    tail blocks; uniform-width fits go through ``_pcg_fit_full``)."""
    return _pcg_block_core(X, R, P, Wb, inv_counts, valid, start, w, lam,
                           width=width, n=n, max_iters=max_iters, tol=tol)


def _pcg_setup_core(Y, mask, w, n):
    # Class membership must match the chol path / the reference
    # (indexOf(label.max), i.e. argmax with first-index tie-breaking,
    # BlockWeightedLeastSquares.scala) — an explicit argmax + one_hot
    # measured 58 ms at the flagship shape, so membership is the FIRST
    # positive entry per row instead: pos ∧ (cumsum(pos) == 1) is a
    # fused ~1 ms pass, and for indicator labels (ClassLabelIndicators:
    # entries in {−1, +1}, possibly multi-hot) every positive entry
    # ties at +1, so first-positive IS argmax. Rows with no positive
    # entry (pad rows, malformed labels) belong to no class. Contract:
    # labels whose positive entries are NOT all equal (arbitrary
    # real-valued Y) would need a true argmax — the estimator's
    # docstring pins indicator-style labels for this path.
    pos = Y > 0
    first_pos = pos & (jnp.cumsum(pos, axis=1) == 1)
    P = first_pos.astype(jnp.bfloat16) * mask[:, None].astype(jnp.bfloat16)
    counts = jnp.einsum("nc->c", P.astype(jnp.float32))
    inv_counts = 1.0 / jnp.maximum(counts, 1.0)
    valid = (counts > 0).astype(jnp.float32)
    # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1 (reference :148-155)
    jlm = 2.0 * w + 2.0 * (1.0 - w) * counts / n - 1.0
    R = (Y - jlm[None, :]) * mask[:, None]
    return P, inv_counts, valid, jlm, R


@partial(jax.jit, static_argnames=("n",))
def _pcg_setup(Y, mask, w, *, n):
    """One-hot class membership P (bf16, exact 0/1), per-class counts,
    joint label mean, and the initial residual — all on device (the r3
    implementation synced class ids to host and built gather indices in
    a Python loop over classes, ~250 ms of the flagship fit). Dispatch
    wrapper for the ragged-block path; uniform fits use the fully fused
    ``_pcg_fit_full``."""
    return _pcg_setup_core(Y, mask, w, n)


@partial(
    jax.jit,
    static_argnames=("width", "n", "num_iter", "max_iters", "tol"),
)
def _pcg_fit_full(X, Y, mask, starts, w, lam,
                  *, width, n, num_iter, max_iters=96, tol=1e-5):
    """The ENTIRE weighted-BCD fit — label setup, every epoch's scanned
    block updates, model concatenation, and the intercept — as ONE
    jitted program: a single dispatch and zero host work per fit.
    Returns (W (D, C), intercept (C,), max rel residual, max CG iters).
    """
    P, inv_counts, valid, jlm, R = _pcg_setup_core(Y, mask, w, n)
    C = Y.shape[1]
    nb = starts.shape[0]
    W0 = jnp.zeros((nb, width, C), jnp.float32)

    def step(carry, xs):
        R_c, Wstack = carry
        i, start = xs
        Wb_new, R_new, jm, rel, its = _pcg_block_core(
            X, R_c, P, Wstack[i], inv_counts, valid, start, w, lam,
            width=width, n=n, max_iters=max_iters, tol=tol,
        )
        Wstack = jax.lax.dynamic_update_index_in_dim(
            Wstack, Wb_new, i, axis=0
        )
        return (R_new, Wstack), (jm, rel, its)

    idx = jnp.tile(jnp.arange(nb), num_iter)
    all_starts = jnp.tile(starts, num_iter)
    (_, Wstack), (jms, rels, itss) = jax.lax.scan(
        step, (R, W0), (idx, all_starts)
    )
    # blocks are contiguous ascending column ranges: stacking IS the
    # feature-axis concatenation
    W = Wstack.reshape(nb * width, C)
    jm_full = jnp.transpose(jms[-nb:], (1, 0, 2)).reshape(C, nb * width)
    # finalB = jointLabelMean − Σ_d jointMeans[c,d]·W[d,c] (:311-314)
    intercept = jlm - jnp.einsum("cd,dc->c", jm_full, W)
    return W, intercept, jnp.max(rels), jnp.max(itss)


@partial(jax.jit, static_argnames=("m", "width"))
def _class_chunk_stats_gathered(
    X, R, idx_c, wt_c, counts_c, class_ids, start, *, m, width,
):
    """Gathered-layout variant of ``_class_chunk_stats`` (same returns);
    pads only to the chunk's own max class size."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xc = Xb[idx_c] * wt_c[:, :, None].astype(Xb.dtype)
    inv = 1.0 / counts_c
    r_g = R[idx_c, class_ids[:, None]] * wt_c
    class_mean, class_xtr, res_local_mean = _chunk_moments(Xc, r_g, inv)
    hp = (
        jax.lax.Precision.HIGHEST
        if Xc.dtype == jnp.float32 else None
    )
    class_cov = (
        jnp.einsum("gmb,gmc->gbc", Xc, Xc,
                   preferred_element_type=jnp.float32, precision=hp)
        * inv[:, None, None]
        - class_mean[:, :, None] * class_mean[:, None, :]
    )
    return class_cov, class_mean, class_xtr, res_local_mean


@dataclasses.dataclass(eq=False)
class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """fit(features, ±1 indicator labels) -> BlockLinearMapper
    (reference: BlockWeightedLeastSquares.scala:36; weight=(3·numIter)+1).

    Label contract: indicator-style matrices (ClassLabelIndicators —
    entries in {−1, +1}). Each row's class is its argmax with
    first-index tie-breaking, matching the reference's
    indexOf(label.max): multi-hot rows join exactly ONE class (the
    first positive) in BOTH solver paths. Arbitrary real-valued Y with
    unequal positive entries is outside the contract — the pcg path
    keys on the first positive entry, not the largest."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None
    class_chunk: int = 16  # classes per batched device step (chol path)
    solve: str = "auto"  # "chol": exact batched per-class Cholesky over
    # the class-grouped layout | "pcg": batched matrix-free
    # preconditioned CG over the original layout (never materializes
    # class covariances, the grouped copy, or the C per-class b³/3
    # factorizations — each class has a single rhs) | "auto": pcg when
    # the first block is wide (≥1024, where factorizations dominate)
    # and w ≤ 0.9 (as w→1 the shared popCov preconditioner drains and
    # CG may hit its iteration cap), chol otherwise
    layout: str = "auto"  # chol-path row layout: "grouped" (one padded
    # (C, m, ·) gather), "gathered" (per-chunk gathers, for skewed
    # classes / tight HBM), "auto" (grouped iff padding ≤ ~1.5n AND the
    # copy fits a third of device memory — ADVICE r3)
    convergence_check: str = "warn"  # after a pcg/auto fit, read the
    # max CG exit residual and "warn" / "raise" when it exceeds
    # ``pcg_tol`` (a capped CG exit would otherwise pass silently —
    # ADVICE r3). The read syncs the dispatch stream (~100 ms through a
    # remote tunnel); latency-critical callers set "off" and check
    # ``model.solver_info['pcg_max_rel_residual']`` themselves.
    pcg_tol: float = 1e-5  # CG exit: relative residual per class. At
    # 1e-5 the solution error vs the exact per-class solve is ~κ·tol ≈
    # 1e-4 relative (the fixture suite asserts pcg↔chol agreement at
    # 5e-4 and vs an f64 reference at 2e-2) — far below feature noise;
    # tighten to 1e-6 when comparing solvers numerically (≈3 extra CG
    # iterations per block).

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        if self.solve not in ("auto", "chol", "pcg"):
            raise ValueError(
                f"solve must be 'auto', 'chol', or 'pcg', got {self.solve!r}"
            )
        if self.convergence_check not in ("off", "warn", "raise"):
            raise ValueError(
                "convergence_check must be 'off', 'warn', or 'raise', "
                f"got {self.convergence_check!r}"
            )
        if self.layout not in ("auto", "grouped", "gathered"):
            raise ValueError(
                "layout must be 'auto', 'grouped', or 'gathered', "
                f"got {self.layout!r}"
            )
        if data.is_host:
            # out-of-aggregate-HBM fit: host-RAM column blocks streamed
            # per pass (the BlockLS host mode, block_ls.py). Only the
            # matrix-free PCG solver applies — it is the auto choice at
            # the wide blocks where host-blocking matters, and the chol
            # path's class-grouped row layouts are built from a
            # device-resident X.
            if self.solve == "chol":
                raise ValueError(
                    "host-blocks datasets require the pcg solver "
                    "(solve='auto' or 'pcg'); the chol path gathers "
                    "class-grouped layouts from a device-resident X"
                )
            return self._fit_pcg_host(data, labels)
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        # one solver per fit (blocks share the residual's physical
        # layout): PCG for wide blocks — there the C per-class b³/3
        # factorizations dominate — but not as w→1, where the shared
        # popCov preconditioner drains and CG may hit its iteration cap
        use_pcg = self.solve == "pcg" or (
            self.solve == "auto"
            and blocks[0][1] >= 1024
            and self.mixture_weight <= 0.9
        )
        if use_pcg:
            return self._fit_pcg(data, X, Y, n, blocks)
        return self._fit_chol(data, X, Y, n, blocks)

    def _fit_pcg(self, data, X, Y, n, blocks):
        """Batched all-class PCG on the original row layout (see
        ``_pcg_block_step``); zero host work, one dispatch per block."""
        w = self.mixture_weight
        mask = data.mask()
        C = Y.shape[1]
        if len({wd for _, wd in blocks}) == 1:
            # uniform widths (every real config: block_size divides D or
            # one block): the ENTIRE fit — setup, every epoch's scanned
            # block updates, concatenation, intercept — is one jitted
            # program and one dispatch (_pcg_fit_full)
            wd = blocks[0][1]
            starts = jnp.asarray([s for s, _ in blocks], jnp.int32)
            W, intercept, pcg_rel, pcg_iters = _pcg_fit_full(
                X, Y, mask, starts, w, self.lam, width=wd, n=n,
                num_iter=self.num_iter, tol=self.pcg_tol,
            )
            self._check_convergence(pcg_rel, pcg_iters)
            return BlockLinearMapper(
                W, self.block_size, explicit_intercept=intercept,
                solver_info={"pcg_max_rel_residual": pcg_rel,
                             "pcg_iterations": pcg_iters},
            )
        # ragged tail block: one dispatch per block
        P, inv_counts, valid, jlm, R = _pcg_setup(Y, mask, w, n=n)
        Wb = {s: jnp.zeros((wd, C), jnp.float32) for s, wd in blocks}
        joint_means = {}
        pcg_rel = None  # max CG exit residual across block solves
        pcg_iters = None  # max CG iteration count (at the cap together
        # with a large residual = preconditioner ill-suited for this
        # mixture weight; see solve= docstring)
        for _ in range(self.num_iter):
            for s, wd in blocks:
                Wb[s], R, jm, rel, its = _pcg_block_step(
                    X, R, P, Wb[s], inv_counts, valid, s,
                    w, self.lam, width=wd, n=n, tol=self.pcg_tol,
                )
                joint_means[s] = jm
                pcg_rel = rel if pcg_rel is None else (
                    jnp.maximum(pcg_rel, rel)
                )
                pcg_iters = its if pcg_iters is None else (
                    jnp.maximum(pcg_iters, its)
                )

        self._check_convergence(pcg_rel, pcg_iters)
        return self._finish(blocks, Wb, joint_means, jlm, {
            "pcg_max_rel_residual": pcg_rel,
            "pcg_iterations": pcg_iters,
        })

    def _fit_pcg_host(self, data, labels) -> BlockLinearMapper:
        """Weighted BCD from HOST-RAM feature blocks: each slab rides an
        async ``device_put`` double-buffered against the previous
        block's whole-block PCG program (same streaming discipline as
        ``BlockLeastSquaresEstimator._fit_host_blocks``; the slab stays
        resident for all of its block's CG iterations, so transfer
        volume is one slab per block per sweep). The dataset's own
        block layout IS the coordinate blocking, matching the
        reference's Seq-of-per-block-RDDs."""
        from keystone_tpu.ops.learning.block_ls import _RunAheadLimiter

        lab = labels.to_array_mode()
        if lab.padded_n != data.padded_n:
            lab = lab._pad_to(data.padded_n)
        Y = lab.padded().astype(jnp.float32)
        n = data.n
        mask = data.mask()
        w = self.mixture_weight
        host_blocks = data.host_blocks
        widths = data.block_widths
        starts = np.cumsum([0] + widths[:-1]).tolist()
        blocks = list(zip(starts, widths))
        C = Y.shape[1]

        P, inv_counts, valid, jlm, R = _pcg_setup(Y, mask, w, n=n)
        Wb = {s: jnp.zeros((wd, C), jnp.float32) for s, wd in blocks}
        joint_means = {}
        pcg_rel = None
        pcg_iters = None
        limiter = _RunAheadLimiter()
        schedule = [
            (it, bi)
            for it in range(self.num_iter)
            for bi in range(len(blocks))
        ]
        nxt = jax.device_put(host_blocks[schedule[0][1]])
        for j, (it, bi) in enumerate(schedule):
            Xb = nxt
            if j + 1 < len(schedule):
                nxt = jax.device_put(host_blocks[schedule[j + 1][1]])
            s, wd = blocks[bi]
            # the slab IS the block: start=0, width=slab width
            Wb[s], R, jm, rel, its = _pcg_block_step(
                Xb, R, P, Wb[s], inv_counts, valid, 0, w, self.lam,
                width=wd, n=n, tol=self.pcg_tol,
            )
            joint_means[s] = jm
            pcg_rel = rel if pcg_rel is None else jnp.maximum(pcg_rel, rel)
            pcg_iters = (
                its if pcg_iters is None else jnp.maximum(pcg_iters, its)
            )
            del Xb
            limiter.add(Wb[s])

        self._check_convergence(pcg_rel, pcg_iters)
        return self._finish(blocks, Wb, joint_means, jlm, {
            "pcg_max_rel_residual": pcg_rel,
            "pcg_iterations": pcg_iters,
        })

    def _check_convergence(self, pcg_rel, pcg_iters) -> None:
        if self.convergence_check == "off":
            return
        # reading the device scalar syncs the dispatch stream; the CG
        # loop exits with rel <= tol unless the iteration cap hit
        rel_val = float(pcg_rel)
        if rel_val > self.pcg_tol:
            msg = (
                f"weighted PCG hit its iteration cap "
                f"(max {int(pcg_iters)} iters) with max relative "
                f"residual {rel_val:.2e} > tol {self.pcg_tol:.0e}; "
                "the fit may be under-converged — try solve='chol', "
                "a smaller mixture_weight, or a larger lam"
            )
            if self.convergence_check == "raise":
                raise RuntimeError(msg)
            import warnings

            warnings.warn(msg, stacklevel=2)

    def _fit_chol(self, data, X, Y, n, blocks):
        """Exact batched per-class Cholesky path (narrow blocks / w→1).
        Needs per-class covariances, so rows are class-grouped — ONE
        device gather into a padded (C, m, ·) layout when that fits the
        memory budget, per-chunk gathers padded to the chunk's own max
        otherwise (skewed classes or tight HBM; ADVICE r3). The weighted
        solve is row-permutation invariant, so the layout choice changes
        nothing numerically."""
        w = self.mixture_weight
        D = X.shape[1]
        C = Y.shape[1]
        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        counts = np.bincount(class_of, minlength=C).astype(np.int64)
        # Classes with no examples get no model update (the reference's
        # groupByClasses simply yields no partition for them; the suite's
        # "empty partitions" / "1 class only" tests exercise this).
        valid_class = counts > 0
        m = int(counts.max())
        grouped_bytes = (C * m) * (
            D * X.dtype.itemsize + C * 4  # Xg copy + R in grouped order
        )
        if self.layout == "auto":
            # grouped only when the padding stays modest AND the copy
            # fits the memory budget (a dataset already filling HBM must
            # not be doubled — ADVICE r3)
            use_grouped = (
                C * m <= int(1.5 * n) + 4096
                and grouped_bytes <= 0.33 * _device_memory_limit()
            )
        else:
            use_grouped = self.layout == "grouped"
        # clamp to 1 so empty-class divisions stay finite; their zero wt
        # rows already zero the numerators, and their delta is masked out
        counts_j = jnp.asarray(np.maximum(counts, 1), jnp.float32)
        valid_j = jnp.asarray(valid_class, jnp.float32)

        # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1 (reference :148-155)
        joint_label_mean = jnp.asarray(
            2 * w + 2 * (1 - w) * counts / n - 1.0, jnp.float32
        )

        rows_of = {
            c: np.flatnonzero(class_of == c).astype(np.int32)
            for c in range(C)
        }
        if use_grouped:
            idx = np.zeros((C, m), np.int32)
            wt = np.zeros((C, m), np.float32)
            for c in range(C):
                idx[c, : counts[c]] = rows_of[c]
                wt[c, : counts[c]] = 1.0
            idx = jnp.asarray(idx)
            wt = jnp.asarray(wt)
            XX, R = _group_rows(X, Y, idx, wt, joint_label_mean)
            mask = wt.reshape(-1)
            chunk_order = list(range(C))
        else:
            XX = X
            mask = data.mask()
            R = (Y - joint_label_mean[None, :]) * mask[:, None]
            # chunk classes in DESCENDING size order so same-size classes
            # share a chunk and per-chunk padding stays small
            chunk_order = list(np.argsort(-counts, kind="stable"))

        Wb = {s: jnp.zeros((wd, C), jnp.float32) for s, wd in blocks}
        joint_means = {}  # per block: (C, b)
        chunks = [
            chunk_order[g : g + self.class_chunk]
            for g in range(0, C, self.class_chunk)
        ]
        if not use_grouped:
            # per-chunk gather indices, padded to the chunk's own max
            # (pow2-rounded so compile count stays bounded)
            chunk_idx = {}
            for ci, chunk in enumerate(chunks):
                mc = max(1, max(int(counts[c]) for c in chunk))
                mc = 1 << (mc - 1).bit_length()
                ic = np.zeros((len(chunk), mc), np.int32)
                wc = np.zeros((len(chunk), mc), np.float32)
                for g, c in enumerate(chunk):
                    ic[g, : counts[c]] = rows_of[c]
                    wc[g, : counts[c]] = 1.0
                chunk_idx[ci] = (jnp.asarray(ic), jnp.asarray(wc), mc)

        for _ in range(self.num_iter):
            for s, wd in blocks:
                pop_mean, pop_cov, pop_xtr = _pop_stats(
                    XX, R, mask, s, width=wd, n=n
                )
                residual_mean = (
                    jnp.einsum("nc->c", R) / n
                )  # MatrixUtils.computeMean over all rows
                delta = jnp.zeros((wd, C), jnp.float32)
                jm_block = jnp.zeros((C, wd), jnp.float32)
                for ci, chunk in enumerate(chunks):
                    cids = jnp.asarray(np.asarray(chunk, np.int32))
                    if use_grouped:
                        ccov, cmean, cxtr, rlm = _class_chunk_stats(
                            XX, R, wt, counts_j, cids, int(chunk[0]),
                            s, G=len(chunk), m=m, width=wd,
                        )
                    else:
                        ic, wc, mc = chunk_idx[ci]
                        ccov, cmean, cxtr, rlm = (
                            _class_chunk_stats_gathered(
                                XX, R, ic, wc, counts_j[cids], cids,
                                s, m=mc, width=wd,
                            )
                        )
                    mean_diff = cmean - pop_mean[None, :]
                    joint_xtx = (
                        pop_cov[None] * (1.0 - w)
                        + ccov * w
                        + mean_diff[:, :, None]
                        * mean_diff[:, None, :]
                        * ((1.0 - w) * w)
                    )
                    jm = cmean * w + pop_mean[None, :] * (1.0 - w)
                    mmw = residual_mean[cids] * (1.0 - w) + w * rlm
                    joint_xtr = (
                        pop_xtr[:, cids].T * (1.0 - w)
                        + cxtr * w
                        - jm * mmw[:, None]
                    )
                    rhs = joint_xtr - Wb[s][:, cids].T * self.lam
                    dW = _batched_psd_solve(joint_xtx, rhs, self.lam)
                    v = valid_j[cids][:, None]
                    delta = delta.at[:, cids].set((dW * v).T)
                    jm_block = jm_block.at[cids].set(jm * v)
                Wb[s] = Wb[s] + delta
                joint_means[s] = jm_block
                R = _apply_delta(XX, R, delta, s, width=wd)

        return self._finish(
            blocks, Wb, joint_means, joint_label_mean, None
        )

    def _finish(self, blocks, Wb, joint_means, joint_label_mean,
                solver_info):
        W = jnp.concatenate([Wb[s] for s, _ in blocks], axis=0)
        jm_full = jnp.concatenate(
            [joint_means[s] for s, _ in blocks], axis=1
        )  # (C, D)
        # finalB = jointLabelMean − Σ_d jointMeans[c,d]·W[d,c] (:311-314)
        intercept = joint_label_mean - jnp.einsum("cd,dc->c", jm_full, W)
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept,
            # lazy device scalars: reading them syncs, ignoring is free —
            # surfaces a PCG iteration-cap exit instead of failing silently
            solver_info=solver_info,
        )

    @property
    def weight(self) -> int:
        return (3 * self.num_iter) + 1


@partial(jax.jit, static_argnames=("width", "first_pass"))
def _rwls_block_step(X, mu_b, B, y_zm, res, Wb, aTa, lam_eye, start,
                     *, width, first_pass):
    """One ReWeightedLeastSquaresSolver block update (reference:
    internal/ReWeightedLeastSquares.scala:80-137):
        aTa   = X̃ᵀ(B ∘ X̃)               (pass 0, cached)
        res'  = res − B ∘ (X̃ W_old)
        aTb   = X̃ᵀ(B ∘ y − res')
        W_new = (aTa + λI) \\ aTb
        res   = res' + B ∘ (X̃ W_new)
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xzm = (Xb - mu_b[None, :]) * (B > 0)[:, None]  # B>0 masks pad rows
    BX = Xzm * B[:, None]
    if first_pass:
        aTa = _f32_mm(Xzm.T, BX)
    res_upd = res - _f32_mm(BX, Wb)
    aTb = _f32_mm(Xzm.T, (y_zm * B)[:, None] - res_upd)
    Wb_new = jax.scipy.linalg.solve(aTa + lam_eye, aTb, assume_a="pos")
    res_new = res_upd + _f32_mm(BX, Wb_new)
    return Wb_new, res_new, aTa


@dataclasses.dataclass(eq=False)
class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    """Same mixture-weighted objective solved class-by-class via reweighted
    single-output BCD (reference: PerClassWeightedLeastSquares.scala:31,
    63-227 + internal/ReWeightedLeastSquares.scala:18,36). Weight vector
    per class c: (1−w)/n everywhere plus w/n_c on class-c rows; features
    centered by the per-class joint mean, labels by the joint label mean."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        C = Y.shape[1]
        w = self.mixture_weight
        mask = np.asarray(data.mask())

        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        counts = np.bincount(class_of, minlength=C).astype(np.float64)
        if (counts == 0).any():
            raise ValueError("every class needs at least one example")

        pop_mean = np.asarray(
            jnp.sum(X.astype(jnp.float32) * data.mask()[:, None], axis=0)
        ) / n
        # per-class mean and joint feature mean (C, D)
        onehot = np.zeros((X.shape[0], C), np.float32)
        onehot[np.arange(n), class_of] = 1.0
        class_sums = np.asarray(_f32_mm(jnp.asarray(onehot).T, X))
        class_means = class_sums / counts[:, None]
        jfm = class_means * w + pop_mean[None, :] * (1.0 - w)
        joint_label_mean = (
            2.0 * w + 2.0 * (1.0 - w) * counts / n - 1.0
        ).astype(np.float32)

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        W = np.zeros((D, C), np.float32)
        neg_wt = (1.0 - w) / n
        Y_np = np.asarray(Y)

        for c in range(C):
            B = np.full(X.shape[0], neg_wt, np.float32) * mask
            B[np.arange(n)[class_of == c]] += w / counts[c]
            Bj = jnp.asarray(B)
            y_zm = jnp.asarray(
                (Y_np[:, c] - joint_label_mean[c]) * mask
            )
            res = jnp.zeros((X.shape[0], 1), jnp.float32)
            Wb = {s: jnp.zeros((wd, 1), jnp.float32) for s, wd in blocks}
            aTa = {s: jnp.zeros((wd, wd), jnp.float32) for s, wd in blocks}
            mu_bs = {
                s: jnp.asarray(jfm[c, s : s + wd]) for s, wd in blocks
            }
            lam_eyes = {
                wd: self.lam * jnp.eye(wd, dtype=jnp.float32)
                for _, wd in blocks
            }
            for it in range(self.num_iter):
                for s, wd in blocks:
                    Wb[s], res, aTa[s] = _rwls_block_step(
                        X, mu_bs[s], Bj, y_zm, res, Wb[s], aTa[s],
                        lam_eyes[wd], s, width=wd, first_pass=(it == 0),
                    )
            W[:, c] = np.concatenate(
                [np.asarray(Wb[s])[:, 0] for s, _ in blocks]
            )

        W = jnp.asarray(W)
        intercept = jnp.asarray(joint_label_mean) - jnp.einsum(
            "cd,dc->c", jnp.asarray(jfm, jnp.float32), W
        )
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept
        )
