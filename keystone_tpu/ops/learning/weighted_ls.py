"""Weighted block coordinate descent for per-class mixture-weighted least
squares — the ImageNet flagship solver.

Reference: nodes/learning/BlockWeightedLeastSquares.scala:36,102-320.
The objective re-weights each class's examples by ``mixture_weight`` w:
per class c the solve uses joint statistics
    jointXTX_c = (1−w)·popCov + w·classCov_c + w(1−w)·δ_c δ_cᵀ
    jointXTR_c = (1−w)·popXTR[:,c] + w·classXTR_c − jointMean_c·mmw_c
with δ_c = classMean_c − popMean and
mmw_c = (1−w)·residualMean_c + w·mean(resLocal_c).

The reference requires a partition-per-class layout (groupByClasses with
HashPartitioner(nClasses), :332-369) so per-class statistics are
partition-local. TPU-native equivalent: sort rows by class ONCE into a
(C, m, ·) class-grouped gather index (classes padded to the max class
size with zero-weight rows) — the EP-style grouping of SURVEY §2.10 —
then per-class covariances are one batched einsum over class chunks and
the per-class (b, b) solves are one batched Cholesky, all on device.
Total flops match the reference (Σ_c n_c·b² = n·b²); no shuffle, no
driver round trip, no distributed System.gc().
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.block_ls import BlockLinearMapper, _f32_mm
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


@partial(jax.jit, static_argnames=("width",))
def _class_chunk_stats(X, R, idx, wt, counts, class_ids, start, *, width):
    """Per-class covariance/XTR for one chunk of classes.

    X: (n, D) raw features; R: (n, C) residual; idx: (G, m) row indices of
    each class's examples (padded); wt: (G, m) 0/1 validity; counts: (G,);
    class_ids: (G,) the class index of each chunk row.
    Returns classCov (G, b, b), classMean (G, b), classXTR (G, b),
    resLocalMean (G,).
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xg = Xb[idx] * wt[:, :, None]  # (G, m, b)
    inv = 1.0 / counts
    class_mean = jnp.einsum("gmb->gb", Xg) * inv[:, None]
    # HIGHEST: the centered covariance cancels mean^2-scale terms; TPU
    # DEFAULT precision would truncate f32 operands to bf16 passes
    # (block_ls._f32_mm documents the measured failure)
    hp = jax.lax.Precision.HIGHEST
    class_cov = (
        jnp.einsum("gmb,gmc->gbc", Xg, Xg,
                   preferred_element_type=jnp.float32, precision=hp)
        * inv[:, None, None]
        - class_mean[:, :, None] * class_mean[:, None, :]
    )
    # resLocal_c = R[rows of c, c]
    r_g = R[idx, class_ids[:, None]] * wt  # (G, m)
    class_xtr = jnp.einsum("gmb,gm->gb", Xg, r_g, precision=hp) * inv[:, None]
    res_local_mean = jnp.einsum("gm->g", r_g) * inv
    return class_cov, class_mean, class_xtr, res_local_mean


@partial(jax.jit, static_argnames=("width", "n"))
def _pop_stats(X, R, mask, start, *, width, n):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    pop_mean = jnp.einsum("nb->b", Xb * mask[:, None]) / n
    pop_cov = _f32_mm(Xb.T, Xb) / n - jnp.outer(pop_mean, pop_mean)
    pop_xtr = _f32_mm(Xb.T, R) / n
    return pop_mean, pop_cov, pop_xtr


@jax.jit
def _batched_psd_solve(A, B, lam):
    """Solve (A_g + λI) x_g = B_g batched, Jacobi-preconditioned f32
    Cholesky (systems are covariance-normalized, O(1) scale)."""
    b = A.shape[-1]
    A = A + lam * jnp.eye(b, dtype=A.dtype)[None]
    d = jnp.sqrt(jnp.maximum(jnp.diagonal(A, axis1=1, axis2=2), 1e-12))
    scale = d[:, :, None] * d[:, None, :]
    An = A / scale
    L = jnp.linalg.cholesky(An)
    Bn = B / d[:, :, None] if B.ndim == 3 else (B / d)[:, :, None]
    y = jax.scipy.linalg.solve_triangular(L, Bn, lower=True)
    x = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, 1, 2), y, lower=False
    )
    return x[:, :, 0] / d if B.ndim == 2 else x / d[:, :, None]


@partial(jax.jit, static_argnames=("width",), donate_argnums=(1,))
def _apply_delta(X, R, delta, start, *, width):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    return R - _f32_mm(Xb, delta)


@dataclasses.dataclass(eq=False)
class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """fit(features, ±1 indicator labels) -> BlockLinearMapper
    (reference: BlockWeightedLeastSquares.scala:36; weight=(3·numIter)+1)."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None
    class_chunk: int = 16  # classes per batched device step

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        C = Y.shape[1]
        w = self.mixture_weight
        mask = data.mask()

        # -- class grouping (host, once; the groupByClasses equivalent) ---
        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        order = np.argsort(class_of, kind="stable")
        counts = np.bincount(class_of, minlength=C).astype(np.int64)
        # Classes with no examples get no model update (the reference's
        # groupByClasses simply yields no partition for them; the suite's
        # "empty partitions" / "1 class only" tests exercise this).
        valid_class = counts > 0
        m = int(counts.max())
        idx = np.zeros((C, m), np.int32)
        wt = np.zeros((C, m), np.float32)
        off = 0
        for c in range(C):
            rows = order[off : off + counts[c]]
            idx[c, : counts[c]] = rows
            wt[c, : counts[c]] = 1.0
            off += counts[c]
        idx = jnp.asarray(idx)
        wt = jnp.asarray(wt)
        # clamp to 1 so empty-class divisions stay finite; their zero wt
        # rows already zero the numerators, and their delta is masked out
        counts_j = jnp.asarray(np.maximum(counts, 1), jnp.float32)
        valid_j = jnp.asarray(valid_class, jnp.float32)

        # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1 (reference :148-155)
        joint_label_mean = jnp.asarray(
            2 * w + 2 * (1 - w) * counts / n - 1.0, jnp.float32
        )
        R = (Y - joint_label_mean[None, :]) * mask[:, None]

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        Wb = {s: jnp.zeros((wd, C), jnp.float32) for s, wd in blocks}
        joint_means = {}  # per block: (C, b)
        chunks = [
            np.arange(g, min(g + self.class_chunk, C))
            for g in range(0, C, self.class_chunk)
        ]

        for _ in range(self.num_iter):
            for s, wd in blocks:
                pop_mean, pop_cov, pop_xtr = _pop_stats(
                    X, R, mask, s, width=wd, n=n
                )
                residual_mean = (
                    jnp.einsum("nc->c", R) / n
                )  # MatrixUtils.computeMean over all rows
                delta = jnp.zeros((wd, C), jnp.float32)
                jm_block = jnp.zeros((C, wd), jnp.float32)
                for chunk in chunks:
                    cids = jnp.asarray(chunk, jnp.int32)
                    ccov, cmean, cxtr, rlm = _class_chunk_stats(
                        X, R, idx[chunk], wt[chunk], counts_j[chunk],
                        cids, s, width=wd,
                    )
                    mean_diff = cmean - pop_mean[None, :]
                    joint_xtx = (
                        pop_cov[None] * (1.0 - w)
                        + ccov * w
                        + mean_diff[:, :, None]
                        * mean_diff[:, None, :]
                        * ((1.0 - w) * w)
                    )
                    jm = cmean * w + pop_mean[None, :] * (1.0 - w)
                    mmw = residual_mean[cids] * (1.0 - w) + w * rlm
                    joint_xtr = (
                        pop_xtr[:, cids].T * (1.0 - w)
                        + cxtr * w
                        - jm * mmw[:, None]
                    )
                    rhs = joint_xtr - Wb[s][:, cids].T * self.lam
                    dW = _batched_psd_solve(joint_xtx, rhs, self.lam)
                    v = valid_j[cids][:, None]
                    delta = delta.at[:, cids].set((dW * v).T)
                    jm_block = jm_block.at[cids].set(jm * v)
                Wb[s] = Wb[s] + delta
                joint_means[s] = jm_block
                R = _apply_delta(X, R, delta, s, width=wd)

        W = jnp.concatenate([Wb[s] for s, _ in blocks], axis=0)
        jm_full = jnp.concatenate(
            [joint_means[s] for s, _ in blocks], axis=1
        )  # (C, D)
        # finalB = jointLabelMean − Σ_d jointMeans[c,d]·W[d,c] (:311-314)
        intercept = joint_label_mean - jnp.einsum("cd,dc->c", jm_full, W)
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept
        )

    @property
    def weight(self) -> int:
        return (3 * self.num_iter) + 1


@partial(jax.jit, static_argnames=("width", "first_pass"))
def _rwls_block_step(X, mu_b, B, y_zm, res, Wb, aTa, lam_eye, start,
                     *, width, first_pass):
    """One ReWeightedLeastSquaresSolver block update (reference:
    internal/ReWeightedLeastSquares.scala:80-137):
        aTa   = X̃ᵀ(B ∘ X̃)               (pass 0, cached)
        res'  = res − B ∘ (X̃ W_old)
        aTb   = X̃ᵀ(B ∘ y − res')
        W_new = (aTa + λI) \\ aTb
        res   = res' + B ∘ (X̃ W_new)
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xzm = (Xb - mu_b[None, :]) * (B > 0)[:, None]  # B>0 masks pad rows
    BX = Xzm * B[:, None]
    if first_pass:
        aTa = _f32_mm(Xzm.T, BX)
    res_upd = res - _f32_mm(BX, Wb)
    aTb = _f32_mm(Xzm.T, (y_zm * B)[:, None] - res_upd)
    Wb_new = jax.scipy.linalg.solve(aTa + lam_eye, aTb, assume_a="pos")
    res_new = res_upd + _f32_mm(BX, Wb_new)
    return Wb_new, res_new, aTa


@dataclasses.dataclass(eq=False)
class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    """Same mixture-weighted objective solved class-by-class via reweighted
    single-output BCD (reference: PerClassWeightedLeastSquares.scala:31,
    63-227 + internal/ReWeightedLeastSquares.scala:18,36). Weight vector
    per class c: (1−w)/n everywhere plus w/n_c on class-c rows; features
    centered by the per-class joint mean, labels by the joint label mean."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        C = Y.shape[1]
        w = self.mixture_weight
        mask = np.asarray(data.mask())

        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        counts = np.bincount(class_of, minlength=C).astype(np.float64)
        if (counts == 0).any():
            raise ValueError("every class needs at least one example")

        pop_mean = np.asarray(
            jnp.sum(X.astype(jnp.float32) * data.mask()[:, None], axis=0)
        ) / n
        # per-class mean and joint feature mean (C, D)
        onehot = np.zeros((X.shape[0], C), np.float32)
        onehot[np.arange(n), class_of] = 1.0
        class_sums = np.asarray(_f32_mm(jnp.asarray(onehot).T, X))
        class_means = class_sums / counts[:, None]
        jfm = class_means * w + pop_mean[None, :] * (1.0 - w)
        joint_label_mean = (
            2.0 * w + 2.0 * (1.0 - w) * counts / n - 1.0
        ).astype(np.float32)

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        W = np.zeros((D, C), np.float32)
        neg_wt = (1.0 - w) / n
        Y_np = np.asarray(Y)

        for c in range(C):
            B = np.full(X.shape[0], neg_wt, np.float32) * mask
            B[np.arange(n)[class_of == c]] += w / counts[c]
            Bj = jnp.asarray(B)
            y_zm = jnp.asarray(
                (Y_np[:, c] - joint_label_mean[c]) * mask
            )
            res = jnp.zeros((X.shape[0], 1), jnp.float32)
            Wb = {s: jnp.zeros((wd, 1), jnp.float32) for s, wd in blocks}
            aTa = {s: jnp.zeros((wd, wd), jnp.float32) for s, wd in blocks}
            mu_bs = {
                s: jnp.asarray(jfm[c, s : s + wd]) for s, wd in blocks
            }
            lam_eyes = {
                wd: self.lam * jnp.eye(wd, dtype=jnp.float32)
                for _, wd in blocks
            }
            for it in range(self.num_iter):
                for s, wd in blocks:
                    Wb[s], res, aTa[s] = _rwls_block_step(
                        X, mu_bs[s], Bj, y_zm, res, Wb[s], aTa[s],
                        lam_eyes[wd], s, width=wd, first_pass=(it == 0),
                    )
            W[:, c] = np.concatenate(
                [np.asarray(Wb[s])[:, 0] for s, _ in blocks]
            )

        W = jnp.asarray(W)
        intercept = jnp.asarray(joint_label_mean) - jnp.einsum(
            "cd,dc->c", jnp.asarray(jfm, jnp.float32), W
        )
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept
        )
