"""Weighted block coordinate descent for per-class mixture-weighted least
squares — the ImageNet flagship solver.

Reference: nodes/learning/BlockWeightedLeastSquares.scala:36,102-320.
The objective re-weights each class's examples by ``mixture_weight`` w:
per class c the solve uses joint statistics
    jointXTX_c = (1−w)·popCov + w·classCov_c + w(1−w)·δ_c δ_cᵀ
    jointXTR_c = (1−w)·popXTR[:,c] + w·classXTR_c − jointMean_c·mmw_c
with δ_c = classMean_c − popMean and
mmw_c = (1−w)·residualMean_c + w·mean(resLocal_c).

The reference requires a partition-per-class layout (groupByClasses with
HashPartitioner(nClasses), :332-369) so per-class statistics are
partition-local. TPU-native equivalent: sort rows by class ONCE into a
(C, m, ·) class-grouped gather index (classes padded to the max class
size with zero-weight rows) — the EP-style grouping of SURVEY §2.10 —
then per-class covariances are one batched einsum over class chunks and
the per-class (b, b) solves are one batched Cholesky, all on device.
Total flops match the reference (Σ_c n_c·b² = n·b²); no shuffle, no
driver round trip, no distributed System.gc().
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning.block_ls import BlockLinearMapper, _f32_mm
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import LabelEstimator


@partial(jax.jit, static_argnames=("G", "m", "width"))
def _class_chunk_stats(Xg, R, wt, counts, class_ids, c0, start,
                       *, G, m, width):
    """Per-class covariance/XTR for one chunk of classes, reading the
    CLASS-GROUPED feature layout.

    Xg: (C·m, D) features grouped by class (class c occupies rows
    [c·m, (c+1)·m), padded slots zeroed); R: (C·m, C) residual in the
    same row order; wt: (C, m) 0/1 validity; counts: (C,);
    class_ids: (G,) class index of each chunk row; c0: first class of
    the chunk. Returns classCov (G, b, b), classMean (G, b),
    classXTR (G, b), resLocalMean (G,).

    Grouping means every read here is a contiguous dynamic-slice — the
    per-chunk row gathers this replaced were re-gathering the whole
    dataset once per block (TPU row-gather is far below stream
    bandwidth; measured 10 TFLOP/s on the r3 bench before this).
    """
    D = Xg.shape[1]
    C = R.shape[1]
    Xc = jax.lax.dynamic_slice(
        Xg.reshape(-1, m, D), (c0, 0, start), (G, m, width)
    )  # (G, m, b) — padded slots are already zero
    wc = jax.lax.dynamic_slice(wt, (c0, 0), (G, m))
    inv = 1.0 / jax.lax.dynamic_slice(counts, (c0,), (G,))
    # resLocal_c = R[rows of c, c] — a (G, m, C) contiguous slice then a
    # per-class column pick
    Rc = jax.lax.dynamic_slice(
        R.reshape(-1, m, C), (c0, 0, 0), (G, m, C)
    )
    r_g = (
        jnp.take_along_axis(Rc, class_ids[:, None, None], axis=2)[..., 0]
        * wc
    )  # (G, m)
    class_mean, class_xtr, res_local_mean = _chunk_moments(Xc, r_g, inv)
    # HIGHEST for f32 inputs: the centered covariance cancels mean^2-
    # scale terms; TPU DEFAULT precision would truncate f32 operands to
    # bf16 passes (block_ls._f32_mm documents the measured failure).
    # bf16 inputs ride the native bf16xbf16->f32 MXU path.
    hp = (
        jax.lax.Precision.HIGHEST
        if Xc.dtype == jnp.float32 else None
    )
    class_cov = (
        jnp.einsum("gmb,gmc->gbc", Xc, Xc,
                   preferred_element_type=jnp.float32, precision=hp)
        * inv[:, None, None]
        - class_mean[:, :, None] * class_mean[:, None, :]
    )
    return class_cov, class_mean, class_xtr, res_local_mean


@jax.jit
def _group_rows(X, Y, idx, wt, joint_label_mean):
    """ONE gather into the class-grouped layout: Xg (C·m, D) with padded
    slots zeroed, and the initial residual R (C·m, C) = (Y − jlm)·wt in
    the same row order. This is the only non-contiguous memory access of
    the whole fit."""
    flat = idx.reshape(-1)
    w = wt.reshape(-1)
    Xg = X[flat] * w[:, None].astype(X.dtype)
    R = (Y[flat] - joint_label_mean[None, :]) * w[:, None]
    return Xg, R


@partial(jax.jit, static_argnames=("width", "n"))
def _pop_stats(X, R, mask, start, *, width, n):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    pop_mean = jnp.einsum("nb->b", Xb * mask[:, None]) / n
    pop_cov = _f32_mm(Xb.T, Xb) / n - jnp.outer(pop_mean, pop_mean)
    pop_xtr = _f32_mm(Xb.T, R) / n
    return pop_mean, pop_cov, pop_xtr


@jax.jit
def _batched_psd_solve(A, B, lam):
    """Solve (A_g + λI) x_g = B_g batched, Jacobi-preconditioned f32
    Cholesky (systems are covariance-normalized, O(1) scale)."""
    b = A.shape[-1]
    A = A + lam * jnp.eye(b, dtype=A.dtype)[None]
    d = jnp.sqrt(jnp.maximum(jnp.diagonal(A, axis1=1, axis2=2), 1e-12))
    scale = d[:, :, None] * d[:, None, :]
    An = A / scale
    L = jnp.linalg.cholesky(An)
    Bn = B / d[:, :, None] if B.ndim == 3 else (B / d)[:, :, None]
    y = jax.scipy.linalg.solve_triangular(L, Bn, lower=True)
    x = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, 1, 2), y, lower=False
    )
    return x[:, :, 0] / d if B.ndim == 2 else x / d[:, :, None]


@partial(jax.jit, static_argnames=("width",), donate_argnums=(1,))
def _apply_delta(X, R, delta, start, *, width):
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    return R - _f32_mm(Xb, delta)


@jax.jit
def _precond_factor(pop_cov, w, lam):
    """Cholesky of the shared CG preconditioner M = (1−w)·popCov +
    (λ+ε·scale)·I. The ε jitter guards rank-deficient population
    covariances (λ may be 0); it biases only the preconditioner, never
    the solution."""
    b = pop_cov.shape[0]
    eps = 1e-6 * jnp.maximum(jnp.trace(pop_cov) / b, 1e-12)
    M = (1.0 - w) * pop_cov + (lam + eps) * jnp.eye(b, dtype=pop_cov.dtype)
    return jnp.linalg.cholesky(M)


def _chunk_moments(Xc, r_g, inv):
    """Shared per-chunk moments: classMean (G, b), classXTR (G, b),
    resLocalMean (G,). Invariant: padded slots of Xc and r_g are ZEROED
    by the caller (grouping or gather wrappers), so plain sums are
    per-class sums. Precision policy: f32 accumulation everywhere; the
    r_g contraction is always f32 (residual) -> HIGHEST."""
    f32 = jnp.float32
    cmean = (
        jnp.einsum("gmb->gb", Xc, preferred_element_type=f32)
        * inv[:, None]
    )
    cxtr = (
        jnp.einsum("gmb,gm->gb", Xc, r_g,
                   preferred_element_type=f32,
                   precision=jax.lax.Precision.HIGHEST)
        * inv[:, None]
    )
    rlm = jnp.einsum("gm->g", r_g) * inv
    return cmean, cxtr, rlm


def _pcg_core(Xc, inv, r_g, class_ids,
              pop_mean, pop_cov, pop_xtr, residual_mean, L0, Wb_block,
              w, lam, max_iters):
    """Shared per-chunk solve core (called inside a jitted wrapper):
    batched preconditioned CG over one chunk's classes — dW (G, b),
    jointMean (G, b), and the exit max relative residual (scalar, for
    convergence diagnostics).

    Each class solves (jointXTX_c + λI) x = rhs_c for a SINGLE rhs
    vector, so an exact per-class (b, b) Cholesky (b³/3 flops each, C of
    them per block — measured to dominate the r3 weighted bench at
    4096³) buys nothing reuse can't. Instead:

    - the operator is applied matrix-free:
        A_c v = (1−w)·popCov·v + w·(Xcᵀ(Xc v)/n_c − μ_c(μ_cᵀv))
                + w(1−w)·δ_c(δ_cᵀv) + λv
      so the (G, b, b) class covariances are never materialized (that
      einsum was the other 2·N·b² of the chol path), and the Xc matvecs
      ride the MXU as batched GEMMs;
    - the shared preconditioner M = (1−w)·popCov + (λ+ε)I is factored
      ONCE per block (L0) — per iteration it costs two batched
      triangular solves. Since all A_c equal M + w·(class terms), the
      preconditioned spectrum clusters and CG converges in tens of
      iterations; preconditioner inexactness affects only the iteration
      count, never the solution. The returned residual exposes the
      ``max_iters`` cap: an ill-suited preconditioner (w→1 drains the
      popCov term) exits with a large residual instead of failing
      silently — fit() surfaces the max over all chunks.
    """
    hp = jax.lax.Precision.HIGHEST
    f32 = jnp.float32

    cmean, cxtr, rlm = _chunk_moments(Xc, r_g, inv)
    mean_diff = cmean - pop_mean[None, :]
    jm = cmean * w + pop_mean[None, :] * (1.0 - w)
    mmw = jnp.take(residual_mean, class_ids) * (1.0 - w) + w * rlm
    joint_xtr = (
        jnp.take(pop_xtr, class_ids, axis=1).T * (1.0 - w)
        + cxtr * w
        - jm * mmw[:, None]
    )
    rhs = joint_xtr - jnp.take(Wb_block, class_ids, axis=1).T * lam

    def matvec(v):  # (G, b) -> (G, b)
        pv = (1.0 - w) * jnp.einsum(
            "bc,gc->gb", pop_cov, v, preferred_element_type=f32,
            precision=hp,
        )
        xv = jnp.einsum("gmb,gb->gm", Xc, v,
                        preferred_element_type=f32, precision=hp)
        xxv = jnp.einsum("gm,gmb->gb", xv, Xc,
                         preferred_element_type=f32, precision=hp)
        cm_dot = jnp.einsum("gb,gb->g", cmean, v, precision=hp)
        ccov_v = xxv * inv[:, None] - cmean * cm_dot[:, None]
        dd = (
            mean_diff
            * jnp.einsum("gb,gb->g", mean_diff, v, precision=hp)[:, None]
            * (w * (1.0 - w))
        )
        return pv + w * ccov_v + dd + lam * v

    def minv(r):  # shared-factor preconditioner, (G, b) -> (G, b)
        y = jax.scipy.linalg.solve_triangular(L0, r.T, lower=True)
        return jax.scipy.linalg.solve_triangular(
            L0.T, y, lower=False
        ).T

    tiny = jnp.asarray(1e-30, f32)
    b_norm = jnp.maximum(jnp.linalg.norm(rhs, axis=1), tiny)

    def rel_res(r):
        return jnp.max(jnp.linalg.norm(r, axis=1) / b_norm)

    def cond(state):
        it, x, r, z, p, rz = state
        return jnp.logical_and(it < max_iters, rel_res(r) > 1e-6)

    def body(state):
        it, x, r, z, p, rz = state
        Ap = matvec(p)
        denom = jnp.einsum("gb,gb->g", p, Ap, precision=hp)
        alpha = jnp.where(denom > 0, rz / jnp.maximum(denom, tiny), 0.0)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * Ap
        z = minv(r)
        rz_new = jnp.einsum("gb,gb->g", r, z, precision=hp)
        beta = jnp.where(rz > 0, rz_new / jnp.maximum(rz, tiny), 0.0)
        p = z + beta[:, None] * p
        return it + 1, x, r, z, p, rz_new

    x0 = jnp.zeros_like(rhs)
    z0 = minv(rhs)
    rz0 = jnp.einsum("gb,gb->g", rhs, z0,
                     precision=jax.lax.Precision.HIGHEST)
    _, dW, r_fin, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0), x0, rhs, z0, z0, rz0)
    )
    return dW, jm, rel_res(r_fin)


@partial(
    jax.jit, static_argnames=("G", "m", "width", "max_iters"),
)
def _class_chunk_update_pcg(
    Xg, R, wt, counts, class_ids, c0, start,
    pop_mean, pop_cov, pop_xtr, residual_mean, L0, Wb_block, w, lam,
    *, G, m, width, max_iters=96,
):
    """Grouped-layout wrapper for ``_pcg_core``: contiguous slices out
    of the class-grouped (C·m, ·) arrays."""
    D = Xg.shape[1]
    C = R.shape[1]
    Xc = jax.lax.dynamic_slice(
        Xg.reshape(-1, m, D), (c0, 0, start), (G, m, width)
    )
    wc = jax.lax.dynamic_slice(wt, (c0, 0), (G, m))
    inv = 1.0 / jax.lax.dynamic_slice(counts, (c0,), (G,))
    Rc = jax.lax.dynamic_slice(R.reshape(-1, m, C), (c0, 0, 0), (G, m, C))
    r_g = (
        jnp.take_along_axis(Rc, class_ids[:, None, None], axis=2)[..., 0]
        * wc
    )
    return _pcg_core(Xc, inv, r_g, class_ids, pop_mean, pop_cov,
                     pop_xtr, residual_mean, L0, Wb_block, w, lam,
                     max_iters)


@partial(jax.jit, static_argnames=("m", "width", "max_iters"))
def _class_chunk_update_pcg_gathered(
    X, R, idx_c, wt_c, counts_c, class_ids, start,
    pop_mean, pop_cov, pop_xtr, residual_mean, L0, Wb_block, w, lam,
    *, m, width, max_iters=96,
):
    """Gathered-layout wrapper for ``_pcg_core``: used when class sizes
    are skewed enough that padding every class to the global max would
    blow up memory (see fit()); pads only to this chunk's own max."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xc = Xb[idx_c] * wt_c[:, :, None].astype(Xb.dtype)
    inv = 1.0 / counts_c
    r_g = R[idx_c, class_ids[:, None]] * wt_c
    return _pcg_core(Xc, inv, r_g, class_ids, pop_mean, pop_cov,
                     pop_xtr, residual_mean, L0, Wb_block, w, lam,
                     max_iters)


@partial(jax.jit, static_argnames=("m", "width"))
def _class_chunk_stats_gathered(
    X, R, idx_c, wt_c, counts_c, class_ids, start, *, m, width,
):
    """Gathered-layout variant of ``_class_chunk_stats`` (same returns);
    pads only to the chunk's own max class size."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xc = Xb[idx_c] * wt_c[:, :, None].astype(Xb.dtype)
    inv = 1.0 / counts_c
    r_g = R[idx_c, class_ids[:, None]] * wt_c
    class_mean, class_xtr, res_local_mean = _chunk_moments(Xc, r_g, inv)
    hp = (
        jax.lax.Precision.HIGHEST
        if Xc.dtype == jnp.float32 else None
    )
    class_cov = (
        jnp.einsum("gmb,gmc->gbc", Xc, Xc,
                   preferred_element_type=jnp.float32, precision=hp)
        * inv[:, None, None]
        - class_mean[:, :, None] * class_mean[:, None, :]
    )
    return class_cov, class_mean, class_xtr, res_local_mean


@dataclasses.dataclass(eq=False)
class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """fit(features, ±1 indicator labels) -> BlockLinearMapper
    (reference: BlockWeightedLeastSquares.scala:36; weight=(3·numIter)+1)."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None
    class_chunk: int = 16  # classes per batched device step
    solve: str = "auto"  # "chol": exact batched per-class Cholesky |
    # "pcg": matrix-free preconditioned CG (skips materializing class
    # covariances AND the C per-class b³/3 factorizations — each class
    # has a single rhs) | "auto": pcg for wide blocks (≥1024) where the
    # factorizations dominate, chol otherwise

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        C = Y.shape[1]
        w = self.mixture_weight

        # -- class grouping (the groupByClasses equivalent). Two layouts:
        #
        # grouped (balanced classes): ONE device gather into a padded
        #   (C·m, ·) class-grouped copy, after which every pass is a
        #   contiguous slice (per-chunk row-gathers were re-reading the
        #   whole dataset once per block at far-below-stream bandwidth).
        #   Padding every class to the global max m costs C·m − n extra
        #   rows — fine when classes are balanced.
        #
        # gathered (skewed classes): when C·m would blow past ~1.5·n
        #   (one giant class forces every class's padding), keep the
        #   original row layout and gather each chunk's rows on the fly,
        #   padded only to that CHUNK's own max class size.
        #
        # The weighted solve is row-permutation invariant, so the layout
        # choice changes nothing numerically.
        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        counts = np.bincount(class_of, minlength=C).astype(np.int64)
        # Classes with no examples get no model update (the reference's
        # groupByClasses simply yields no partition for them; the suite's
        # "empty partitions" / "1 class only" tests exercise this).
        valid_class = counts > 0
        m = int(counts.max())
        use_grouped = C * m <= int(1.5 * n) + 4096
        # clamp to 1 so empty-class divisions stay finite; their zero wt
        # rows already zero the numerators, and their delta is masked out
        counts_j = jnp.asarray(np.maximum(counts, 1), jnp.float32)
        valid_j = jnp.asarray(valid_class, jnp.float32)

        # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1 (reference :148-155)
        joint_label_mean = jnp.asarray(
            2 * w + 2 * (1 - w) * counts / n - 1.0, jnp.float32
        )

        rows_of = {
            c: np.flatnonzero(class_of == c).astype(np.int32)
            for c in range(C)
        }
        if use_grouped:
            idx = np.zeros((C, m), np.int32)
            wt = np.zeros((C, m), np.float32)
            for c in range(C):
                idx[c, : counts[c]] = rows_of[c]
                wt[c, : counts[c]] = 1.0
            idx = jnp.asarray(idx)
            wt = jnp.asarray(wt)
            XX, R = _group_rows(X, Y, idx, wt, joint_label_mean)
            mask = wt.reshape(-1)
            chunk_order = list(range(C))
        else:
            XX = X
            mask = data.mask()
            R = (Y - joint_label_mean[None, :]) * mask[:, None]
            # chunk classes in DESCENDING size order so same-size classes
            # share a chunk and per-chunk padding stays small
            chunk_order = list(np.argsort(-counts, kind="stable"))

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        Wb = {s: jnp.zeros((wd, C), jnp.float32) for s, wd in blocks}
        joint_means = {}  # per block: (C, b)
        chunks = [
            chunk_order[g : g + self.class_chunk]
            for g in range(0, C, self.class_chunk)
        ]
        if not use_grouped:
            # per-chunk gather indices, padded to the chunk's own max
            # (pow2-rounded so compile count stays bounded)
            chunk_idx = {}
            for ci, chunk in enumerate(chunks):
                mc = max(1, max(int(counts[c]) for c in chunk))
                mc = 1 << (mc - 1).bit_length()
                ic = np.zeros((len(chunk), mc), np.int32)
                wc = np.zeros((len(chunk), mc), np.float32)
                for g, c in enumerate(chunk):
                    ic[g, : counts[c]] = rows_of[c]
                    wc[g, : counts[c]] = 1.0
                chunk_idx[ci] = (jnp.asarray(ic), jnp.asarray(wc), mc)

        if self.solve not in ("auto", "chol", "pcg"):
            raise ValueError(
                f"solve must be 'auto', 'chol', or 'pcg', got {self.solve!r}"
            )

        pcg_rel = None  # max PCG exit residual across all chunk solves
        for _ in range(self.num_iter):
            for s, wd in blocks:
                # auto: PCG where the C per-class b³/3 factorizations
                # dominate, but not as w→1 — there the shared popCov
                # preconditioner drains and CG may hit its iteration cap
                use_pcg = self.solve == "pcg" or (
                    self.solve == "auto" and wd >= 1024 and w <= 0.9
                )
                pop_mean, pop_cov, pop_xtr = _pop_stats(
                    XX, R, mask, s, width=wd, n=n
                )
                residual_mean = (
                    jnp.einsum("nc->c", R) / n
                )  # MatrixUtils.computeMean over all rows
                delta = jnp.zeros((wd, C), jnp.float32)
                jm_block = jnp.zeros((C, wd), jnp.float32)
                if use_pcg:
                    L0 = _precond_factor(pop_cov, w, self.lam)
                for ci, chunk in enumerate(chunks):
                    cids = jnp.asarray(np.asarray(chunk, np.int32))
                    if use_pcg and use_grouped:
                        dW, jm, rel = _class_chunk_update_pcg(
                            XX, R, wt, counts_j, cids, int(chunk[0]), s,
                            pop_mean, pop_cov, pop_xtr, residual_mean,
                            L0, Wb[s], w, self.lam,
                            G=len(chunk), m=m, width=wd,
                        )
                    elif use_pcg:
                        ic, wc, mc = chunk_idx[ci]
                        dW, jm, rel = _class_chunk_update_pcg_gathered(
                            XX, R, ic, wc, counts_j[cids], cids, s,
                            pop_mean, pop_cov, pop_xtr, residual_mean,
                            L0, Wb[s], w, self.lam,
                            m=mc, width=wd,
                        )
                    else:
                        if use_grouped:
                            ccov, cmean, cxtr, rlm = _class_chunk_stats(
                                XX, R, wt, counts_j, cids, int(chunk[0]),
                                s, G=len(chunk), m=m, width=wd,
                            )
                        else:
                            ic, wc, mc = chunk_idx[ci]
                            ccov, cmean, cxtr, rlm = (
                                _class_chunk_stats_gathered(
                                    XX, R, ic, wc, counts_j[cids], cids,
                                    s, m=mc, width=wd,
                                )
                            )
                        mean_diff = cmean - pop_mean[None, :]
                        joint_xtx = (
                            pop_cov[None] * (1.0 - w)
                            + ccov * w
                            + mean_diff[:, :, None]
                            * mean_diff[:, None, :]
                            * ((1.0 - w) * w)
                        )
                        jm = cmean * w + pop_mean[None, :] * (1.0 - w)
                        mmw = residual_mean[cids] * (1.0 - w) + w * rlm
                        joint_xtr = (
                            pop_xtr[:, cids].T * (1.0 - w)
                            + cxtr * w
                            - jm * mmw[:, None]
                        )
                        rhs = joint_xtr - Wb[s][:, cids].T * self.lam
                        dW = _batched_psd_solve(joint_xtx, rhs, self.lam)
                        rel = None
                    if rel is not None:
                        pcg_rel = rel if pcg_rel is None else (
                            jnp.maximum(pcg_rel, rel)
                        )
                    v = valid_j[cids][:, None]
                    delta = delta.at[:, cids].set((dW * v).T)
                    jm_block = jm_block.at[cids].set(jm * v)
                Wb[s] = Wb[s] + delta
                joint_means[s] = jm_block
                R = _apply_delta(XX, R, delta, s, width=wd)

        W = jnp.concatenate([Wb[s] for s, _ in blocks], axis=0)
        jm_full = jnp.concatenate(
            [joint_means[s] for s, _ in blocks], axis=1
        )  # (C, D)
        # finalB = jointLabelMean − Σ_d jointMeans[c,d]·W[d,c] (:311-314)
        intercept = joint_label_mean - jnp.einsum("cd,dc->c", jm_full, W)
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept,
            # lazy device scalar: reading it syncs, ignoring it is free —
            # surfaces a PCG iteration-cap exit instead of failing silently
            solver_info=(
                None if pcg_rel is None
                else {"pcg_max_rel_residual": pcg_rel}
            ),
        )

    @property
    def weight(self) -> int:
        return (3 * self.num_iter) + 1


@partial(jax.jit, static_argnames=("width", "first_pass"))
def _rwls_block_step(X, mu_b, B, y_zm, res, Wb, aTa, lam_eye, start,
                     *, width, first_pass):
    """One ReWeightedLeastSquaresSolver block update (reference:
    internal/ReWeightedLeastSquares.scala:80-137):
        aTa   = X̃ᵀ(B ∘ X̃)               (pass 0, cached)
        res'  = res − B ∘ (X̃ W_old)
        aTb   = X̃ᵀ(B ∘ y − res')
        W_new = (aTa + λI) \\ aTb
        res   = res' + B ∘ (X̃ W_new)
    """
    Xb = jax.lax.dynamic_slice_in_dim(X, start, width, axis=1)
    Xzm = (Xb - mu_b[None, :]) * (B > 0)[:, None]  # B>0 masks pad rows
    BX = Xzm * B[:, None]
    if first_pass:
        aTa = _f32_mm(Xzm.T, BX)
    res_upd = res - _f32_mm(BX, Wb)
    aTb = _f32_mm(Xzm.T, (y_zm * B)[:, None] - res_upd)
    Wb_new = jax.scipy.linalg.solve(aTa + lam_eye, aTb, assume_a="pos")
    res_new = res_upd + _f32_mm(BX, Wb_new)
    return Wb_new, res_new, aTa


@dataclasses.dataclass(eq=False)
class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    """Same mixture-weighted objective solved class-by-class via reweighted
    single-output BCD (reference: PerClassWeightedLeastSquares.scala:31,
    63-227 + internal/ReWeightedLeastSquares.scala:18,36). Weight vector
    per class c: (1−w)/n everywhere plus w/n_c on class-c rows; features
    centered by the per-class joint mean, labels by the joint label mean."""

    block_size: int
    num_iter: int
    lam: float
    mixture_weight: float
    num_features: Optional[int] = None

    def fit(self, data: Dataset, labels: Dataset) -> BlockLinearMapper:
        data = data.to_array_mode()
        labels = labels.to_array_mode()
        X = data.padded()
        Y = labels.padded().astype(jnp.float32)
        n = data.n
        D = X.shape[1]
        C = Y.shape[1]
        w = self.mixture_weight
        mask = np.asarray(data.mask())

        class_of = np.asarray(jnp.argmax(Y, axis=1))[: n]
        counts = np.bincount(class_of, minlength=C).astype(np.float64)
        if (counts == 0).any():
            raise ValueError("every class needs at least one example")

        pop_mean = np.asarray(
            jnp.sum(X.astype(jnp.float32) * data.mask()[:, None], axis=0)
        ) / n
        # per-class mean and joint feature mean (C, D)
        onehot = np.zeros((X.shape[0], C), np.float32)
        onehot[np.arange(n), class_of] = 1.0
        class_sums = np.asarray(_f32_mm(jnp.asarray(onehot).T, X))
        class_means = class_sums / counts[:, None]
        jfm = class_means * w + pop_mean[None, :] * (1.0 - w)
        joint_label_mean = (
            2.0 * w + 2.0 * (1.0 - w) * counts / n - 1.0
        ).astype(np.float32)

        blocks = [
            (s, min(s + self.block_size, D) - s)
            for s in range(0, D, self.block_size)
        ]
        W = np.zeros((D, C), np.float32)
        neg_wt = (1.0 - w) / n
        Y_np = np.asarray(Y)

        for c in range(C):
            B = np.full(X.shape[0], neg_wt, np.float32) * mask
            B[np.arange(n)[class_of == c]] += w / counts[c]
            Bj = jnp.asarray(B)
            y_zm = jnp.asarray(
                (Y_np[:, c] - joint_label_mean[c]) * mask
            )
            res = jnp.zeros((X.shape[0], 1), jnp.float32)
            Wb = {s: jnp.zeros((wd, 1), jnp.float32) for s, wd in blocks}
            aTa = {s: jnp.zeros((wd, wd), jnp.float32) for s, wd in blocks}
            mu_bs = {
                s: jnp.asarray(jfm[c, s : s + wd]) for s, wd in blocks
            }
            lam_eyes = {
                wd: self.lam * jnp.eye(wd, dtype=jnp.float32)
                for _, wd in blocks
            }
            for it in range(self.num_iter):
                for s, wd in blocks:
                    Wb[s], res, aTa[s] = _rwls_block_step(
                        X, mu_bs[s], Bj, y_zm, res, Wb[s], aTa[s],
                        lam_eyes[wd], s, width=wd, first_pass=(it == 0),
                    )
            W[:, c] = np.concatenate(
                [np.asarray(Wb[s])[:, 0] for s, _ in blocks]
            )

        W = jnp.asarray(W)
        intercept = jnp.asarray(joint_label_mean) - jnp.einsum(
            "cd,dc->c", jnp.asarray(jfm, jnp.float32), W
        )
        return BlockLinearMapper(
            W, self.block_size, explicit_intercept=intercept
        )
