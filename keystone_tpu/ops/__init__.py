"""Operator library ("nodes"): featurizers, solvers, preprocessing.

Mirrors the reference's nodes/{learning,images,stats,nlp,util} inventory
(SURVEY.md §2.2-2.6) with TPU-first implementations.
"""
