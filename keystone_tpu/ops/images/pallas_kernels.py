"""Pallas TPU kernels for the flagship featurize hot loops.

The SIFT and LCS extractors both reduce their heavy stage to a GEMM
sandwich ``Aᵀ · Z · B`` over a stack of small planes (sift.py
``_sampling_matrix`` / lcs.py ``_lcs_sampling_matrix`` document the
reformulation) — exactly the shape the MXU wants, but as plain XLA the
plane stack round-trips HBM between the binning that produces it and
the two matmuls that consume it. These kernels fuse that seam, the
same VMEM-residency move ``fv_pallas`` makes for the FV statistics:

- ``sift_bin_sample``: trilinear orientation binning (the vl_dsift
  gradient→8-plane scatter) fused with the two sampling-matrix GEMMs.
  The grid walks the 8 orientations; each step materializes ONE
  (H, W) orientation plane in VMEM from the gradient magnitude/angle
  fields and contracts it down to (M, N) on the MXU — the (8, H, W)
  plane stack never exists in HBM.
- ``plane_sandwich``: the plain sandwich for LCS box-mean/variance
  extraction (image and image² share the chain as stacked planes).

Both run under ``interpret=True`` off-TPU (``auto_interpret``), so
CPU tier-1/CI exercises the exact kernel dataflow; both batch cleanly
under ``vmap`` (pallas_call's batching rule folds the batch into the
grid), which is how the bucket-vmapped extractors drive them. Dots
pin f32 HIGHEST precision — the extractors' parity tolerances
(1e-4 vs the independent numpy translations) were set against it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NUM_ORIENTATIONS = 8

_HP = jax.lax.Precision.HIGHEST


def auto_interpret(interpret: Optional[bool] = None) -> bool:
    """Resolve an ``interpret`` flag: ``None`` selects the Mosaic
    compile path on TPU and the Pallas interpreter everywhere else —
    kernels stay drop-in on CPU/GPU CI without caller-side backend
    checks. Resolved at trace time, so a jitted caller bakes the
    choice into its program like any other static."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def _sift_bin_sample_kernel(
    mag_ref, orient_ref, ayt_ref, ax_ref, out_ref
):
    t = pl.program_id(0)
    tq = orient_ref[:]  # continuous orientation in [0, 8)
    b0f = jnp.floor(tq)
    frac = tq - b0f
    b0 = b0f.astype(jnp.int32) % NUM_ORIENTATIONS
    b1 = (b0 + 1) % NUM_ORIENTATIONS
    # this orientation's trilinear share of the gradient magnitude —
    # the vl_dsift bilinear-over-orientation binning, one plane at a
    # time so the full (8, H, W) stack never leaves VMEM
    plane = mag_ref[:] * (
        jnp.where(b0 == t, 1.0 - frac, 0.0)
        + jnp.where(b1 == t, frac, 0.0)
    )
    t1 = jnp.dot(ayt_ref[:], plane,
                 preferred_element_type=jnp.float32, precision=_HP)
    out_ref[0] = jnp.dot(t1, ax_ref[:],
                         preferred_element_type=jnp.float32,
                         precision=_HP)


def sift_bin_sample(
    mag: jnp.ndarray,
    orient: jnp.ndarray,
    ayt: jnp.ndarray,
    ax: jnp.ndarray,
    *,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused trilinear orientation binning + spatial-binning GEMMs.

    ``mag``/``orient``: (H, W) gradient magnitude and continuous
    orientation (angle / 2π · 8); ``ayt``: (M, H) transposed y-axis
    sampling matrix; ``ax``: (W, N) x-axis sampling matrix. Returns
    (8, M, N) — orientation t's plane contracted through both
    sampling operators, bit-for-bit the one_hot+einsum formulation it
    replaces."""
    H, W = mag.shape
    M, N = ayt.shape[0], ax.shape[1]
    return pl.pallas_call(
        _sift_bin_sample_kernel,
        grid=(NUM_ORIENTATIONS,),
        in_specs=[
            pl.BlockSpec((H, W), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, W), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W, N), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, M, N), lambda t: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (NUM_ORIENTATIONS, M, N), jnp.float32
        ),
        interpret=auto_interpret(interpret),
    )(
        mag.astype(jnp.float32),
        orient.astype(jnp.float32),
        ayt.astype(jnp.float32),
        ax.astype(jnp.float32),
    )


def _plane_sandwich_kernel(plane_ref, at_ref, b_ref, out_ref):
    t1 = jnp.dot(at_ref[:], plane_ref[0],
                 preferred_element_type=jnp.float32, precision=_HP)
    out_ref[0] = jnp.dot(t1, b_ref[:],
                         preferred_element_type=jnp.float32,
                         precision=_HP)


def plane_sandwich(
    planes: jnp.ndarray,
    at: jnp.ndarray,
    b: jnp.ndarray,
    *,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """(P, M, N) GEMM sandwich ``out[p] = at @ planes[p] @ b`` — the
    LCS box-filter→sample stage over the stacked image/image² channel
    planes (``at``: (M, X) transposed x-axis sampling matrix, ``b``:
    (Y, N) y-axis one). The grid walks planes; each stays VMEM-resident
    between its two dots."""
    P, H, W = planes.shape
    M, N = at.shape[0], b.shape[1]
    return pl.pallas_call(
        _plane_sandwich_kernel,
        grid=(P,),
        in_specs=[
            pl.BlockSpec((1, H, W), lambda p: (p, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, H), lambda p: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W, N), lambda p: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, M, N), lambda p: (p, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P, M, N), jnp.float32),
        interpret=auto_interpret(interpret),
    )(
        planes.astype(jnp.float32),
        at.astype(jnp.float32),
        b.astype(jnp.float32),
    )


__all__ = ["auto_interpret", "sift_bin_sample", "plane_sandwich"]
