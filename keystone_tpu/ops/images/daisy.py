"""DAISY dense descriptors (Tola et al.).

Reference: nodes/images/DaisyExtractor.scala:28 — oriented half-rectified
gradient layers, cascaded Gaussian blurs per ring (sigma differences
derived from daisyR/daisyQ), histogram sampling at ring points around
each grid keypoint, per-histogram L2 normalization with a zero threshold.
Output: (daisyFeatureSize, numKeypoints) matrix, matching the SIFT
orientation convention.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images.lcs import _box_filter_same  # asym-pad helper
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer


def _conv2d_same(img2d: jnp.ndarray, kx: np.ndarray, ky: np.ndarray):
    """Separable same-size conv with the reference's asymmetric zero
    padding (ImageUtils.conv2D)."""

    def conv_axis(x, k, axis):
        pad_low = (len(k) - 1) // 2
        pad_high = len(k) - 1 - pad_low
        moved = jnp.moveaxis(x, axis, -1)
        shape = moved.shape
        flat = moved.reshape(-1, 1, shape[-1])
        out = jax.lax.conv_general_dilated(
            flat, jnp.asarray(k, jnp.float32)[None, None, :], (1,),
            [(pad_low, pad_high)], dimension_numbers=("NCH", "OIH", "NCH"),
        )
        return jnp.moveaxis(out.reshape(shape), -1, axis)

    return conv_axis(conv_axis(img2d, kx, 0), ky, 1)


@dataclasses.dataclass(eq=False)
class DaisyExtractor(Transformer):
    daisy_t: int = 8  # angles per ring
    daisy_q: int = 3  # rings
    daisy_r: int = 7  # outer radius
    daisy_h: int = 8  # orientation histograms
    pixel_border: int = 16
    stride: int = 4
    patch_size: int = 24
    feature_threshold: float = 1e-8
    conv_threshold: float = 1e-6
    vmap_batch = False  # ragged across shapes
    bucket_vmap = True  # but vmappable within a shape bucket

    def __post_init__(self):
        q, r = self.daisy_q, self.daisy_r
        sigma_sq = [(r * n / (2 * q)) ** 2 for n in range(q + 1)]
        self._sigma_sq_diff = [
            b - a for a, b in zip(sigma_sq, sigma_sq[1:])
        ]
        self._g: List[np.ndarray] = []
        for t in self._sigma_sq_diff:
            half = int(
                math.ceil(
                    math.sqrt(
                        -2 * t * math.log(self.conv_threshold)
                        - t * math.log(2 * math.pi * t)
                    )
                )
            )
            ns = np.arange(-half, half + 1)
            self._g.append(
                np.exp(-(ns**2) / (2 * t)) / math.sqrt(2 * math.pi * t)
            )

    @property
    def daisy_feature_size(self) -> int:
        return self.daisy_h * (self.daisy_t * self.daisy_q + 1)

    def apply(self, img):
        x = jnp.asarray(img, jnp.float32)
        if x.ndim == 3:
            x = x[:, :, 0]
        return self._extract(x)

    @partial(jax.jit, static_argnums=(0,))
    def _extract(self, img):
        H, Q, T = self.daisy_h, self.daisy_q, self.daisy_t
        ix = _conv2d_same(img, [1.0, 0.0, -1.0], [1.0, 2.0, 1.0])
        iy = _conv2d_same(img, [1.0, 2.0, 1.0], [1.0, 0.0, -1.0])

        # oriented half-rectified layers, cascade-blurred per ring
        layers = []  # layers[level] : (H, X, Y)
        level0 = []
        for a in range(H):
            angle = 2 * math.pi * a / H
            plane = jnp.maximum(
                math.cos(angle) * ix + math.sin(angle) * iy, 0.0
            )
            level0.append(_conv2d_same(plane, self._g[0], self._g[0]))
        layers.append(jnp.stack(level0))
        for level in range(1, Q):
            layers.append(
                jnp.stack(
                    [
                        _conv2d_same(
                            layers[level - 1][a],
                            self._g[level],
                            self._g[level],
                        )
                        for a in range(H)
                    ]
                )
            )

        X, Y = img.shape
        kx = np.arange(self.pixel_border, X - self.pixel_border, self.stride)
        ky = np.arange(self.pixel_border, Y - self.pixel_border, self.stride)
        n_keys = len(kx) * len(ky)
        gx, gy = np.meshgrid(kx, ky, indexing="ij")  # (nx, ny)
        gxf = jnp.asarray(gx.reshape(-1))
        gyf = jnp.asarray(gy.reshape(-1))

        def norm_hist(h):
            # (n_keys, H) L2 normalize w/ zero threshold
            nrm = jnp.linalg.norm(h, axis=1, keepdims=True)
            return jnp.where(
                nrm > self.feature_threshold, h / nrm, 0.0
            )

        out = jnp.zeros((n_keys, self.daisy_feature_size), jnp.float32)
        center = norm_hist(
            layers[0][:, gxf, gyf].T
        )  # (n_keys, H)
        out = out.at[:, :H].set(center)

        for level in range(Q):
            cur_rad = self.daisy_r * (1 + level) / Q
            for a in range(T):
                theta = 2 * math.pi * (a - 1) / T
                ox = int(round(cur_rad * math.sin(theta)))
                oy = int(round(cur_rad * math.cos(theta)))
                h = layers[level][:, gxf + ox, gyf + oy].T
                h = norm_hist(h)
                col = H + a * Q * H + level * H
                out = out.at[:, col : col + H].set(h)

        return out.T  # (daisyFeatureSize, numKeypoints)
