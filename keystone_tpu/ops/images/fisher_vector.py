"""Fisher vector encoding from GMM posteriors.

Reference: nodes/images/FisherVector.scala:21-94 (the Sanchez et al. FV
survey formulation) and nodes/images/external/FisherVector.scala:17
(enceval JNI variant — on TPU the "native" path is the same fused XLA
program, so GMMFisherVectorEstimator's k>=32 native switch collapses to
one implementation).

Input per example: a (d, m) descriptor matrix (d descriptor dims, m
descriptors, the SIFT/LCS output convention); output: the (d, 2k) FV.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from keystone_tpu.ops.learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, Transformer
from keystone_tpu.workflow.node_optimization import Optimizable


@partial(jax.jit, static_argnums=(0,))
def _fisher_vector(fv_self, x):
    """x: (d, m) descriptors. Direct transliteration of the Sanchez
    formulas (FisherVector.scala:33-52)."""
    gmm = fv_self.gmm
    m = x.shape[1]
    q = gmm._posteriors(x.T)  # (m, k)
    s0 = jnp.mean(q, axis=0)  # (k,)
    s1 = mm(x, q) / m  # (d, k)
    s2 = mm(x * x, q) / m  # (d, k)
    means, variances = gmm.means, gmm.variances  # (d, k)
    weights = gmm.weights  # (k,)
    fv1 = (s1 - means * s0[None, :]) / (
        jnp.sqrt(variances) * jnp.sqrt(weights)[None, :]
    )
    fv2 = (
        s2
        - 2.0 * means * s1
        + (means * means - variances) * s0[None, :]
    ) / (variances * jnp.sqrt(2.0 * weights)[None, :])
    return jnp.concatenate([fv1, fv2], axis=1)  # (d, 2k)


def _fv_from_stats(gmm, s0, s1, s2):
    """Sanchez FV from the (already /m) statistics
    (FisherVector.scala:42-52)."""
    means, variances = gmm.means, gmm.variances  # (d, k)
    weights = gmm.weights  # (k,)
    fv1 = (s1 - means * s0[None, :]) / (
        jnp.sqrt(variances) * jnp.sqrt(weights)[None, :]
    )
    fv2 = (
        s2
        - 2.0 * means * s1
        + (means * means - variances) * s0[None, :]
    ) / (variances * jnp.sqrt(2.0 * weights)[None, :])
    return jnp.concatenate([fv1, fv2], axis=1)  # (d, 2k)


@dataclasses.dataclass(eq=False)
class FisherVector(Transformer):
    gmm: GaussianMixtureModel

    def apply(self, x):
        return _fisher_vector(self, jnp.asarray(x, jnp.float32))

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            out = jax.vmap(lambda m: _fisher_vector(self, m))(
                ds.padded().astype(jnp.float32)
            )
            return Dataset.from_array(out, n=ds.n)
        return ds.map(self.apply)


@dataclasses.dataclass(eq=False)
class FisherVectorFused(Transformer):
    """FV via the fused Pallas statistics kernel (the TPU equivalent of
    the reference's enceval-native path, external/FisherVector.scala:17 →
    EncEval.cxx:19): posterior computation and the three statistics
    matmuls run in one kernel, never writing the (m, k) posterior matrix
    to HBM — the win grows with k, hence the k >= 32 physical choice in
    GMMFisherVectorEstimator."""

    gmm: GaussianMixtureModel

    def apply(self, x):
        from keystone_tpu.ops.images.fv_pallas import (
            fisher_vector_stats_pallas,
        )

        g = self.gmm
        s0, s1, s2 = fisher_vector_stats_pallas(
            jnp.asarray(x, jnp.float32), g.means, g.variances, g.weights,
            g.weight_threshold,
        )
        return _fv_from_stats(g, s0, s1, s2)

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            out = jax.vmap(self.apply)(ds.padded().astype(jnp.float32))
            return Dataset.from_array(out, n=ds.n)
        return ds.map(self.apply)


def _columns_of(data: Dataset):
    """Flatten (d, m) descriptor matrices into one (N, d) row matrix for
    GMM training (reference: flatMap(matrixToColArray))."""
    import numpy as np

    cols = [np.asarray(m).T for m in data.items()]
    return Dataset.from_array(jnp.asarray(np.concatenate(cols, axis=0)))


@dataclasses.dataclass(eq=False)
class ScalaGMMFisherVectorEstimator(Estimator):
    """GMM-fit + unfused FisherVector (reference: FisherVector.scala:65
    — the Scala implementation parallel)."""

    k: int
    seed: int = 0

    def fit(self, data: Dataset) -> FisherVector:
        gmm = GaussianMixtureModelEstimator(self.k, seed=self.seed).fit(
            _columns_of(data)
        )
        return FisherVector(gmm)


@dataclasses.dataclass(eq=False)
class EncEvalGMMFisherVectorEstimator(Estimator):
    """GMM-fit + fused-kernel FisherVector (reference:
    external/FisherVector.scala:49 — the enceval-native parallel; here
    the native path is the Pallas kernel in fv_pallas.py)."""

    k: int
    seed: int = 0

    def fit(self, data: Dataset) -> FisherVectorFused:
        gmm = GaussianMixtureModelEstimator(self.k, seed=self.seed).fit(
            _columns_of(data)
        )
        return FisherVectorFused(gmm)


@dataclasses.dataclass(eq=False)
class GMMFisherVectorEstimator(Estimator, Optimizable):
    """Optimizable physical choice (reference: FisherVector.scala:84-94
    picks the native enceval implementation when k >= 32): large k favors
    the fused Pallas kernel (posteriors stay in VMEM); small k favors the
    plain XLA program (kernel launch overhead dominates)."""

    k: int
    seed: int = 0

    def _choice(self) -> Estimator:
        if self.k >= 32:
            return EncEvalGMMFisherVectorEstimator(self.k, self.seed)
        return ScalaGMMFisherVectorEstimator(self.k, self.seed)

    def fit(self, data: Dataset) -> Transformer:
        return self._choice().fit(data)

    def fit_datasets(self, datasets):
        return self.fit(datasets[0])

    def optimize(self, samples, n_total: int):
        return self._choice()
