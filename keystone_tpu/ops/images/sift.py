"""Dense multi-scale SIFT.

Reference: nodes/images/external/SIFTExtractor.scala:16 +
src/main/cpp/VLFeat.cxx:36-200 (getMultiScaleDSIFTs_f driving vlfeat
0.9.20's vl_dsift). The multi-scale driver here matches VLFeat.cxx
exactly: per scale s, bin size = bin + 2s, Gaussian pre-smoothing with
sigma = binSize/magnif (magnif = 6), sampling bounds offset
(1 + 2·numScales) − 3s to the image edge, step = step + s·scaleStep,
contrast-threshold 0.005 zeroing of low-energy descriptors, descriptors
scaled x512 and clamped to 255 (the MATLAB uint8 convention,
VLFeat.cxx:230-260).

The per-scale descriptor follows vl_dsift's dense formulation: 4x4
spatial bins x 8 orientations; gradient magnitude is binned bilinearly
over orientation; spatial binning is the triangular (bilinear)
convolution vl_imconvcoltri implements; bins are modulated by the
Gaussian window factor (windowSize = 1.5, flat-window approximation
evaluates it per bin center); each descriptor is L2-normalized, clamped
at 0.2, renormalized (Lowe's normalization).

NOTE: the reference's golden fixture (feats128.csv, ±1-of-99.5% vs MATLAB
vl_phow) is not present in its repo, and vlfeat sources are not available
in this environment, so bit-level parity against vlfeat cannot be
asserted here; the algorithm is validated against an independent numpy
translation of the same spec (tests/ops/test_sift_fv.py).

TPU mapping: the whole spatial-binning stage (triangular convolution +
bin-center sampling + Gaussian window factors) folds into two small
per-scale SAMPLING MATRICES applied as MXU GEMMs. The stage is linear
in the orientation planes and separable per axis, so
``A[y, f·4+j] = tri(y − (bound + f·step + j·bin)) · wf[j]`` expresses
tri-conv→sample→window exactly; measured ~5× over the
conv→strided-slice formulation on the v5e (SIFT device time ~110 →
~22 ms per 128×256² batch; the C=1 depthwise convs ran on the VPU and
the slicing materialized awkwardly-tiled intermediates), lifting the
flagship featurize row from 889 to 1806 ex/s/chip (PERF_r05.md).
The binning+GEMM hot loop itself runs as the ``pallas_kernels.
sift_bin_sample`` kernel: the trilinear orientation scatter and both
sampling-matrix contractions fuse in VMEM, so the (8, H, W) plane
stack never hits HBM (interpret-mode fallback keeps CPU CI on the
same dataflow). Static shapes per (W, H, scale).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images.pallas_kernels import sift_bin_sample
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer

NUM_ORIENTATIONS = 8
NUM_SPATIAL_BINS = 4
DESCRIPTOR_DIMS = 128
MAGNIF = 6.0
CONTRAST_THRESHOLD = 0.005
WINDOW_SIZE = 1.5


def _gaussian_kernel(sigma: float) -> np.ndarray:
    """vl_imsmooth-style truncated Gaussian (radius ceil(4 sigma))."""
    if sigma < 1e-8:
        return np.ones(1, np.float32)
    r = int(np.ceil(4.0 * sigma))
    xs = np.arange(-r, r + 1)
    k = np.exp(-(xs**2) / (2.0 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def _sep_conv2d(planes: jnp.ndarray, k: np.ndarray) -> jnp.ndarray:
    """Separable same-size conv of (P, H, W) planes with a 1-D kernel,
    borders replicated (vl_imsmooth's continuity padding). Only the
    Gaussian pre-smooth comes through here — the triangular spatial
    binning is folded into the sampling-matrix GEMMs
    (_sampling_matrix)."""
    kj = jnp.asarray(k)
    pad = (len(k) - 1) // 2

    def conv1d(x, axis):
        moved = jnp.moveaxis(x, axis, -1)
        shape = moved.shape
        flat = moved.reshape(-1, 1, shape[-1])
        if pad > 0:
            flat = jnp.pad(
                flat, ((0, 0), (0, 0), (pad, pad)), mode="edge"
            )
        out = jax.lax.conv_general_dilated(
            flat, kj[None, None, :], (1,), [(0, 0)],
            dimension_numbers=("NCH", "OIH", "NCH"),
        )
        return jnp.moveaxis(
            out.reshape(shape[:-1] + (out.shape[-1],)), -1, axis
        )

    return conv1d(conv1d(planes, 1), 2)


def _window_factors(bin_size: int) -> np.ndarray:
    """Per-bin Gaussian window factor at bin centers (flat-window
    approximation): exp(−½ (δ/σ_win)²), σ_win = windowSize·binSize, δ =
    bin-center offset from the descriptor center."""
    centers = (
        np.arange(NUM_SPATIAL_BINS) - (NUM_SPATIAL_BINS - 1) / 2.0
    ) * bin_size
    sigma = WINDOW_SIZE * bin_size
    return np.exp(-0.5 * (centers / sigma) ** 2).astype(np.float32)


def _sampling_matrix(
    n: int, nf: int, bin_size: int, step: int, bound: int
) -> np.ndarray:
    """(n, nf·4) one-axis spatial-binning operator: column f·4+j holds
    the triangular kernel tri(d) = max(0, (bin−|d|)/bin) centered at
    bound + f·step + j·bin (zero outside the image — vl_imconvcoltri's
    zero padding), pre-scaled by the Gaussian window factor wf[j].
    Applying it on each axis reproduces triangular conv → bin-center
    sample → window EXACTLY (the stage is linear and separable), as two
    MXU GEMMs instead of VPU-bound C=1 convs plus slicing. Built per
    trace — jit's per-static-shape caching makes memoization redundant,
    and the build is nf·4 tiny numpy rows."""
    wf = _window_factors(bin_size)
    m = np.zeros((n, nf * NUM_SPATIAL_BINS), np.float32)
    ys = np.arange(n)
    for f in range(nf):
        for j in range(NUM_SPATIAL_BINS):
            c = bound + f * step + j * bin_size
            tri = np.maximum(0.0, (bin_size - np.abs(ys - c)) / bin_size)
            m[:, f * NUM_SPATIAL_BINS + j] = tri * wf[j]
    return m


@partial(jax.jit, static_argnames=("bin_size", "step", "bound_min"))
def _dsift_one_scale(img, *, bin_size: int, step: int, bound_min: int):
    """Dense SIFT at one scale over a pre-smoothed (H, W) image.

    Returns (num_frames, 128) raw descriptors (normalized + clamped) and
    (num_frames,) pre-normalization norms. Frame grid: top-left corners
    at bound_min + f·step along both axes, descriptor extent
    4·binSize."""
    H, W = img.shape
    gy, gx = jnp.gradient(img)
    mag = jnp.sqrt(gx * gx + gy * gy)
    ang = jnp.arctan2(gy, gx) % (2.0 * jnp.pi)
    t = ang / (2.0 * jnp.pi) * NUM_ORIENTATIONS

    extent = (NUM_SPATIAL_BINS - 1) * bin_size
    nfy = max((H - 1 - bound_min - extent) // step + 1, 0)
    nfx = max((W - 1 - bound_min - extent) // step + 1, 0)
    if nfy == 0 or nfx == 0:
        return (
            jnp.zeros((0, DESCRIPTOR_DIMS), jnp.float32),
            jnp.zeros((0,), jnp.float32),
        )
    # the whole tri-conv → bin-sample → window stage as two GEMMs (see
    # _sampling_matrix), fused with the trilinear orientation binning
    # in one Pallas kernel — each orientation plane is built and
    # contracted in VMEM, never written to HBM
    Ay = _sampling_matrix(H, nfy, bin_size, step, bound_min)
    Ax = jnp.asarray(_sampling_matrix(W, nfx, bin_size, step, bound_min))
    g = sift_bin_sample(mag, t, jnp.asarray(Ay.T.copy()), Ax)
    g = g.reshape(
        NUM_ORIENTATIONS, nfy, NUM_SPATIAL_BINS, nfx, NUM_SPATIAL_BINS
    )
    g = jnp.transpose(g, (1, 3, 2, 4, 0))  # (nfy, nfx, j, i, t)
    raw = g.reshape(-1, DESCRIPTOR_DIMS)
    norms = jnp.linalg.norm(raw, axis=1)
    desc = raw / jnp.maximum(norms, 1e-12)[:, None]
    desc = jnp.minimum(desc, 0.2)
    desc = desc / jnp.maximum(
        jnp.linalg.norm(desc, axis=1), 1e-12
    )[:, None]
    return desc, norms


@dataclasses.dataclass(eq=False)
class SIFTExtractor(Transformer):
    """Image -> (128, numDescriptors) short-valued descriptor matrix
    (reference: SIFTExtractor.scala — the columns are descriptors)."""

    step: int = 3
    bin: int = 4
    num_scales: int = 4
    scale_step: int = 1  # reference default (SIFTExtractor.scala:16)
    vmap_batch = False  # ragged across shapes
    bucket_vmap = True  # but vmappable within a shape bucket

    def apply(self, img):
        x = jnp.asarray(img, jnp.float32)
        if x.ndim == 3:
            x = x[:, :, 0]
        H, W = x.shape
        descs: List[jnp.ndarray] = []
        for scale in range(self.num_scales):
            bin_size = self.bin + 2 * scale
            sigma = bin_size / MAGNIF
            k = _gaussian_kernel(sigma)
            sm = _sep_conv2d(x[None], k)[0]
            bound = (1 + 2 * self.num_scales) - 3 * scale
            desc, norms = _dsift_one_scale(
                sm,
                bin_size=bin_size,
                step=self.step + scale * self.scale_step,
                bound_min=bound,
            )
            # contrast-threshold zeroing (VLFeat.cxx:141-175)
            desc = jnp.where(
                (norms >= CONTRAST_THRESHOLD)[:, None], desc, 0.0
            )
            descs.append(desc)
        all_desc = jnp.concatenate(descs, axis=0)
        # x512, clamp 255, to the uint8-style convention (VLFeat.cxx glue)
        quantized = jnp.minimum(
            jnp.floor(all_desc * 512.0), 255.0
        )
        return quantized.T  # (128, numDescriptors)

    @property
    def descriptor_dims(self) -> int:
        return DESCRIPTOR_DIMS
