"""Core image nodes: convolution, pooling, rectification, patch extraction.

Reference: nodes/images/{Convolver,Pooler,SymmetricRectifier,Windower,
CenterCornerPatcher,RandomPatcher,RandomImageTransformer,Cropper}.scala and
the small utilities in nodes/images/*.scala (ImageVectorizer, PixelScaler,
GrayScaler); image conventions from utils/images/Image.scala.

Conventions: an image is a jnp array ``A[x, y, c]`` (the reference's
``Image.get(x, y, channel)``); channel-major vectorization flattens as
``vec[c + x·C + y·C·X]`` (ChannelMajorArrayVectorizedImage), i.e.
``A.transpose(1, 0, 2).ravel()``.

TPU-first: the Convolver is NOT an im2col + GEMM translation. Patch
normalization and whitening are folded into closed-form corrections around
one XLA convolution (which the compiler maps onto the MXU):

    out = (conv(A, W) − m·S_f) / sd − ⟨μ_zca, W_f⟩

where m/sd are per-patch mean/std obtained from two box-filter convs.
This reproduces makePatches(normalizePatches)+whitener-mean-subtraction+
GEMM (Convolver.scala:128-205) without materializing a patch matrix.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import FunctionNode, Transformer

# MATLAB rgb2gray weights (reference: utils/images/ImageUtils.scala:73-76)
GRAYSCALE_WEIGHTS = (0.2989, 0.5870, 0.1140)


def channel_major_vectorize(img: jnp.ndarray) -> jnp.ndarray:
    """A[x,y,c] -> vec[c + x·C + y·C·X] (ChannelMajor flatten)."""
    return jnp.transpose(img, (1, 0, 2)).reshape(-1)


def pack_filters(filters: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Stack filter images into the (num_filters, k·k·C) matrix layout of
    Convolver.packFilters (row i, col c + x·C + y·C·k = filter_i[x,y,c])."""
    return jnp.stack([channel_major_vectorize(f) for f in filters])


@dataclasses.dataclass(eq=False)
class Convolver(Transformer):
    """Convolve images with a filter bank (reference: Convolver.scala:20).

    ``filters``: (num_filters, k·k·C) packed rows (optionally already
    whitened, as RandomPatchCifar does); ``whitener``: the ZCAWhitener whose
    means are subtracted from each (normalized) patch.
    """

    filters: Any
    img_width: int
    img_height: int
    img_channels: int
    whitener: Optional[Any] = None
    normalize_patches: bool = True
    var_constant: float = 10.0
    fast: bool = False  # True trades ~0.4% feature error for MXU-native
    # speed: f32 inputs then run at TPU DEFAULT matmul precision (bf16
    # passes) instead of HIGHEST. The default keeps f32 semantics — the
    # patch-variance term s2 − P·m² cancels a decimal order on byte-range
    # images, which DEFAULT precision cannot represent.

    def __post_init__(self):
        C = self.img_channels
        k = int(np.sqrt(self.filters.shape[1] // C))
        self.conv_size = k
        F = self.filters.shape[0]
        # unpack rows (col c + x·C + y·C·k) back to W[f, x, y, c]
        self._W = jnp.transpose(
            jnp.asarray(self.filters, jnp.float32).reshape(F, k, k, C),
            (0, 2, 1, 3),
        )
        self._filter_sums = jnp.sum(self._W, axis=(1, 2, 3))  # S_f
        if self.whitener is not None:
            flat = self._W.transpose(0, 2, 1, 3).reshape(F, -1)
            self._whitener_dot = mm(flat, jnp.asarray(
                self.whitener.means, jnp.float32
            ))
        else:
            self._whitener_dot = None

    @property
    def res_width(self) -> int:
        return self.img_width - self.conv_size + 1

    @property
    def res_height(self) -> int:
        return self.img_height - self.conv_size + 1

    def apply(self, img):
        return self._convolve(img[None])[0]

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            return Dataset.from_array(self._convolve(ds.padded()), n=ds.n)
        return ds.map(self.apply)

    @partial(jax.jit, static_argnums=(0,))
    def _convolve(self, imgs):
        """imgs: (n, X, Y, C) -> (n, resX, resY, F)."""
        k = self.conv_size
        C = self.img_channels
        x = imgs.astype(jnp.float32)
        hp = None if self.fast else jax.lax.Precision.HIGHEST
        # XLA correlation: out[n,x,y,f] = Σ A[n,x+dx,y+dy,c]·W[f,dx,dy,c]
        dn = jax.lax.conv_dimension_numbers(
            x.shape, self._W.shape, ("NHWC", "OHWI", "NHWC")
        )
        raw = jax.lax.conv_general_dilated(
            x, self._W, (1, 1), "VALID", dimension_numbers=dn,
            preferred_element_type=jnp.float32, precision=hp,
        )
        if not self.normalize_patches and self._whitener_dot is None:
            return raw
        P = k * k * C
        ones = jnp.ones((1, k, k, C), jnp.float32)
        s1 = jax.lax.conv_general_dilated(
            x, ones, (1, 1), "VALID", dimension_numbers=dn, precision=hp
        )
        out = raw
        if self.normalize_patches:
            s2 = jax.lax.conv_general_dilated(
                x * x, ones, (1, 1), "VALID", dimension_numbers=dn,
                precision=hp,
            )
            m = s1 / P
            # Stats.normalizeRows: var over patch entries, /(P-1), +alpha
            var = (s2 - P * m * m) / (P - 1)
            sd = jnp.sqrt(var + self.var_constant)
            out = (raw - m * self._filter_sums[None, None, None, :]) / sd
        if self._whitener_dot is not None:
            out = out - self._whitener_dot[None, None, None, :]
        return out


@dataclasses.dataclass(eq=False)
class Pooler(Transformer):
    """Strided spatial pooling (reference: Pooler.scala:21 — strides start
    at poolSize/2, windows truncate at the image edge, pixel_fn applied
    before pooling, pool_fn reduces each window; sum by default)."""

    stride: int
    pool_size: int
    pixel_fn: Optional[Callable] = None
    pool_fn: Optional[Callable] = None

    def apply(self, img):
        return self._pool(img[None])[0]

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            return Dataset.from_array(self._pool(ds.padded()), n=ds.n)
        return ds.map(self.apply)

    @partial(jax.jit, static_argnums=(0,))
    def _pool(self, imgs):
        x_dim, y_dim = imgs.shape[1], imgs.shape[2]
        half = self.pool_size // 2
        start = half
        xs = list(range(start, x_dim, self.stride))
        ys = list(range(start, y_dim, self.stride))
        vals = imgs.astype(jnp.float32)
        if self.pixel_fn is not None:
            vals = self.pixel_fn(vals)
        pool_fn = self.pool_fn or (lambda w: jnp.sum(w, axis=(1, 2)))
        rows = []
        for px in xs:
            cols = []
            for py in ys:
                window = vals[
                    :, px - half : min(px + half, x_dim),
                    py - half : min(py + half, y_dim), :,
                ]
                cols.append(pool_fn(window))
            rows.append(jnp.stack(cols, axis=1))  # (n, ny, C)
        return jnp.stack(rows, axis=1)  # (n, nx, ny, C)


@dataclasses.dataclass(eq=False)
class SymmetricRectifier(Transformer):
    """Two-sided ReLU doubling the channel count: channels [0,C) are
    max(maxVal, x−α), channels [C,2C) are max(maxVal, −x−α)
    (reference: SymmetricRectifier.scala:7)."""

    max_val: float = 0.0
    alpha: float = 0.0

    def apply(self, img):
        pos = jnp.maximum(self.max_val, img - self.alpha)
        neg = jnp.maximum(self.max_val, -img - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            x = ds.padded()
            pos = jnp.maximum(self.max_val, x - self.alpha)
            neg = jnp.maximum(self.max_val, -x - self.alpha)
            out = jnp.concatenate([pos, neg], axis=-1)
            if self.max_val > 0 or self.alpha < 0:
                out = out * ds.mask().reshape(
                    (-1,) + (1,) * (out.ndim - 1)
                )
            return Dataset.from_array(out, n=ds.n)
        return ds.map(self.apply)


class ImageVectorizer(Transformer):
    """Image -> channel-major vector (reference:
    nodes/images/ImageVectorizer.scala)."""

    def apply(self, img):
        return channel_major_vectorize(img)

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            x = ds.padded()
            out = jnp.transpose(x, (0, 2, 1, 3)).reshape(x.shape[0], -1)
            return Dataset.from_array(out, n=ds.n)
        return ds.map(self.apply)

    def eq_key(self):
        return ("image_vectorizer",)


class PixelScaler(Transformer):
    """x / 255 (reference: nodes/images/PixelScaler.scala)."""

    def apply(self, img):
        return img.astype(jnp.float32) / 255.0

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            return Dataset.from_array(
                ds.padded().astype(jnp.float32) / 255.0, n=ds.n
            )
        return self._bucketed_batch(ds)

    def eq_key(self):
        return ("pixel_scaler",)


class GrayScaler(Transformer):
    """RGB -> single-channel grayscale with MATLAB rgb2gray weights
    (reference: GrayScaler.scala via ImageUtils.toGrayScale)."""

    def apply(self, img):
        w = jnp.asarray(GRAYSCALE_WEIGHTS, jnp.float32)
        return (img.astype(jnp.float32) @ w)[..., None]

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            w = jnp.asarray(GRAYSCALE_WEIGHTS, jnp.float32)
            out = (ds.padded().astype(jnp.float32) @ w)[..., None]
            return Dataset.from_array(out, n=ds.n)
        return self._bucketed_batch(ds)

    def eq_key(self):
        return ("gray_scaler",)


@dataclasses.dataclass(eq=False)
class Cropper(Transformer):
    """Static crop [startX:endX, startY:endY] (reference:
    nodes/images/Cropper.scala)."""

    start_x: int
    start_y: int
    end_x: int
    end_y: int

    def apply(self, img):
        return img[self.start_x : self.end_x, self.start_y : self.end_y]

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            return Dataset.from_array(
                ds.padded()[
                    :, self.start_x : self.end_x, self.start_y : self.end_y
                ],
                n=ds.n,
            )
        return ds.map(self.apply)


class Windower(FunctionNode):
    """Eagerly explode each image into all strided windows (reference:
    nodes/images/Windower.scala:13 — a FunctionNode flatMap)."""

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def apply(self, data) -> Dataset:
        ds = Dataset.of(data).to_array_mode()
        imgs = ds.padded()[: ds.n]
        k = self.window_size
        xs = range(0, imgs.shape[1] - k + 1, self.stride)
        ys = range(0, imgs.shape[2] - k + 1, self.stride)
        windows = [
            imgs[:, x : x + k, y : y + k, :] for x in xs for y in ys
        ]
        # (n·numWindows, k, k, C) — window-major within each image
        stacked = jnp.stack(windows, axis=1).reshape(
            (-1, k, k, imgs.shape[3])
        )
        return Dataset.from_array(stacked)


@dataclasses.dataclass(eq=False)
class RandomPatcher(Transformer):
    """Random crops for train augmentation (reference:
    RandomPatcher.scala:17): emits ``num_patches`` random (size x size)
    crops per image."""

    num_patches: int
    patch_size_x: int
    patch_size_y: int
    seed: int = 0
    vmap_batch = False

    def apply_batch(self, ds: Dataset) -> Dataset:
        ds = ds.to_array_mode()
        imgs = np.asarray(ds.padded()[: ds.n])
        rng = np.random.default_rng(self.seed)
        out = []
        px, py = self.patch_size_x, self.patch_size_y
        for img in imgs:
            for _ in range(self.num_patches):
                x = rng.integers(0, img.shape[0] - px + 1)
                y = rng.integers(0, img.shape[1] - py + 1)
                out.append(img[x : x + px, y : y + py])
        return Dataset.from_array(jnp.asarray(np.stack(out)))

    def apply(self, img):
        raise TypeError("RandomPatcher is a batch augmentation node")


@dataclasses.dataclass(eq=False)
class CenterCornerPatcher(Transformer):
    """Test-time augmentation: center + 4 corner crops, optionally with
    horizontal flips (reference: CenterCornerPatcher.scala:19)."""

    patch_size_x: int
    patch_size_y: int
    horizontal_flips: bool = False
    vmap_batch = False

    def _positions(self, X, Y):
        px, py = self.patch_size_x, self.patch_size_y
        return [
            (0, 0),
            (X - px, 0),
            (0, Y - py),
            (X - px, Y - py),
            ((X - px) // 2, (Y - py) // 2),
        ]

    def apply_batch(self, ds: Dataset) -> Dataset:
        ds = ds.to_array_mode()
        imgs = ds.padded()[: ds.n]
        X, Y = imgs.shape[1], imgs.shape[2]
        px, py = self.patch_size_x, self.patch_size_y
        crops = []
        for (x, y) in self._positions(X, Y):
            crop = imgs[:, x : x + px, y : y + py, :]
            crops.append(crop)
            if self.horizontal_flips:
                crops.append(crop[:, :, ::-1, :])
        # patch-major within each image: (n·numPatches, px, py, C)
        return Dataset.from_array(
            jnp.stack(crops, axis=1).reshape((-1, px, py, imgs.shape[3]))
        )

    def apply(self, img):
        raise TypeError("CenterCornerPatcher is a batch augmentation node")

    @property
    def patches_per_image(self) -> int:
        return 10 if self.horizontal_flips else 5


@dataclasses.dataclass(eq=False)
class RandomImageTransformer(Transformer):
    """Random horizontal flip with probability ``flip_chance``
    (reference: RandomImageTransformer.scala)."""

    flip_chance: float = 0.5
    seed: int = 0
    vmap_batch = False

    def apply_batch(self, ds: Dataset) -> Dataset:
        ds = ds.to_array_mode()
        imgs = ds.padded()
        rng = np.random.default_rng(self.seed)
        flips = jnp.asarray(
            rng.random(imgs.shape[0]) < self.flip_chance
        )
        flipped = imgs[:, :, ::-1, :]
        out = jnp.where(flips[:, None, None, None], flipped, imgs)
        return Dataset.from_array(out, n=ds.n)

    def apply(self, img):
        return img
