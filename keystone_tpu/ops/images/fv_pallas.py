"""Fused Fisher-vector statistics as a Pallas TPU kernel.

Reference native path: nodes/images/external/FisherVector.scala:17 →
src/main/cpp/EncEval.cxx:19 (enceval `fisher<float>::compute`), the C++
implementation the reference switches to for k >= 32
(nodes/images/FisherVector.scala:84-94). The TPU equivalent of "native"
is a Pallas kernel that fuses the three matmuls and the softmax of the
FV statistics pass so the (m, k) posterior matrix is never written to
HBM:

    logits = -0.5 * X² @ (1/σ²) + X @ (μ/σ²) + c        (MXU)
    q      = softmax(logits, axis=-1)                    (VPU, in VMEM)
    s0    += Σ_rows q ;  s1 += Xᵀ q ;  s2 += (X²)ᵀ q     (MXU)

The grid walks descriptor chunks; s0/s1/s2 accumulate in revisited VMEM
output blocks. For the unfused baseline (and the k < 32 physical
choice) see fisher_vector.FisherVector.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.images.pallas_kernels import auto_interpret

TILE_M = 512  # descriptors per grid step; X chunk is TILE_M x d in VMEM


def _fv_stats_kernel(
    m_valid_ref, thresh_ref, x_ref, inv_var_ref, proj_ref, const_ref,
    s0_ref, s1_ref, s2_ref,
):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        s0_ref[:] = jnp.zeros_like(s0_ref)
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    x = x_ref[:]  # (TILE_M, d)
    x2 = x * x
    logits = (
        -0.5 * jnp.dot(x2, inv_var_ref[:],
                       precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
        + jnp.dot(x, proj_ref[:], preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)
        + const_ref[:]
    )  # (TILE_M, k)
    logits = logits - jnp.max(logits, axis=1, keepdims=True)
    q = jnp.exp(logits)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    # aggressive posterior thresholding + renormalize, matching
    # GaussianMixtureModel._posteriors (gmm.py:55-60)
    q = jnp.where(q > thresh_ref[0], q, 0.0)
    q = q / jnp.sum(q, axis=1, keepdims=True)

    # zero pad rows (global row index >= m_valid)
    rows = step * TILE_M + jax.lax.broadcasted_iota(
        jnp.int32, q.shape, 0
    )
    q = jnp.where(rows < m_valid_ref[0], q, 0.0)

    s0_ref[:] += jnp.sum(q, axis=0, keepdims=True)
    s1_ref[:] += jnp.dot(x.T, q, preferred_element_type=jnp.float32,
                         precision=jax.lax.Precision.HIGHEST)
    s2_ref[:] += jnp.dot(x2.T, q, preferred_element_type=jnp.float32,
                         precision=jax.lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=("interpret",))
def fisher_vector_stats_pallas(
    x, means, variances, weights, weight_threshold=1e-4,
    *, interpret: Optional[bool] = None
):
    """x: (d, m) descriptors -> (s0 (k,), s1 (d, k), s2 (d, k)), each
    already divided by m (the FisherVector.scala:33-41 statistics, with
    the GMM's posterior thresholding applied). ``interpret=None``
    auto-selects the backend: Mosaic-compiled on TPU, the Pallas
    interpreter elsewhere (``pallas_kernels.auto_interpret``) — callers
    no longer carry their own backend check."""
    interpret = auto_interpret(interpret)
    d, m = x.shape
    k = means.shape[1]
    inv_var = 1.0 / variances  # (d, k)
    proj = means / variances  # (d, k)
    const = (
        jnp.log(weights)[None, :]
        - 0.5 * jnp.sum(jnp.log(2.0 * np.pi * variances), axis=0)[None, :]
        - 0.5 * jnp.sum(means * proj, axis=0)[None, :]
    )  # (1, k)

    m_pad = max(((m + TILE_M - 1) // TILE_M) * TILE_M, TILE_M)
    xt = jnp.zeros((m_pad, d), jnp.float32).at[:m].set(
        x.T.astype(jnp.float32)
    )
    grid = m_pad // TILE_M

    s0, s1, s2 = pl.pallas_call(
        _fv_stats_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE_M, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((d, k), jnp.float32),
            jax.ShapeDtypeStruct((d, k), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray([m], jnp.int32),
        jnp.asarray([weight_threshold], jnp.float32),
        xt,
        inv_var.astype(jnp.float32),
        proj.astype(jnp.float32),
        const.astype(jnp.float32),
    )
    inv_m = 1.0 / m
    return s0[0] * inv_m, s1 * inv_m, s2 * inv_m
