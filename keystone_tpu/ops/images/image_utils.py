"""Image utility functions.

Reference: utils/images/ImageUtils.scala:16-399 — loadImage, toGrayScale,
mapPixels, crop, pixelCombine, separable conv2D, splitChannels,
flipImage/flipHorizontal; ImageConversions for decode. Images are
``A[x, y, c]`` float arrays.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images.core import GRAYSCALE_WEIGHTS
from keystone_tpu.ops.images.daisy import _conv2d_same


def load_image(path: str) -> Optional[jnp.ndarray]:
    """Decode an image file to an (x, y, 3) float32 array (reference:
    ImageUtils.loadImage via ImageIO)."""
    from PIL import Image as PILImage

    try:
        img = PILImage.open(path).convert("RGB")
    except Exception:
        return None
    return jnp.asarray(np.asarray(img, np.float32))


def to_gray_scale(img: jnp.ndarray) -> jnp.ndarray:
    """MATLAB rgb2gray weights (reference: ImageUtils.toGrayScale:73)."""
    w = jnp.asarray(GRAYSCALE_WEIGHTS, jnp.float32)
    return (img.astype(jnp.float32) @ w)[..., None]


def map_pixels(img: jnp.ndarray, fn: Callable) -> jnp.ndarray:
    return fn(img)


def crop(img: jnp.ndarray, start_x: int, start_y: int, end_x: int,
         end_y: int) -> jnp.ndarray:
    return img[start_x:end_x, start_y:end_y]


def pixel_combine(a: jnp.ndarray, b: jnp.ndarray,
                  fn: Callable = jnp.add) -> jnp.ndarray:
    return fn(a, b)


def split_channels(img: jnp.ndarray) -> List[jnp.ndarray]:
    return [img[:, :, c : c + 1] for c in range(img.shape[2])]


def conv2d(img: jnp.ndarray, x_filter: Sequence[float],
           y_filter: Sequence[float]) -> jnp.ndarray:
    """Separable same-size convolution with the reference's asymmetric
    zero padding (ImageUtils.conv2D:226)."""
    squeeze = img.ndim == 3 and img.shape[2] == 1
    x = img[:, :, 0] if squeeze else img
    if x.ndim == 3:
        out = jnp.stack(
            [
                _conv2d_same(x[:, :, c], np.asarray(x_filter),
                             np.asarray(y_filter))
                for c in range(x.shape[2])
            ],
            axis=2,
        )
        return out
    out = _conv2d_same(x, np.asarray(x_filter), np.asarray(y_filter))
    return out[:, :, None] if squeeze else out


def flip_horizontal(img: jnp.ndarray) -> jnp.ndarray:
    """Mirror along the y (column) axis."""
    return img[:, ::-1]


def flip_image(img: jnp.ndarray) -> jnp.ndarray:
    """Flip both spatial axes (reference: ImageUtils.flipImage — used to
    flip convolution filters for MATLAB convnd comparability)."""
    return img[::-1, ::-1]
