"""Local Color Statistics (LCS) extractor.

Reference: nodes/images/LCSExtractor.scala:25 — per grid keypoint, the
means and standard deviations of each RGB channel over a 4x4 neighborhood
of sub-patches (96-dim descriptors); means/stds come from a centered box
filter (ImageUtils.conv2D zero-pads floor((L-1)/2) low / rest high, so an
even-length box is right-biased exactly as the reference's).

TPU mapping: two depthwise box convolutions (sum and sum-of-squares) +
one gather over the keypoint/neighborhood grid — all fused under jit.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer


def _box_filter_same(img: jnp.ndarray, size: int) -> jnp.ndarray:
    """(H, W, C) -> same-size box mean with the reference's asymmetric
    zero padding (ImageUtils.conv2D:226-238)."""
    pad_low = (size - 1) // 2
    pad_high = size - 1 - pad_low
    k = jnp.full((size,), 1.0 / size, jnp.float32)

    def conv_axis(x, axis):
        moved = jnp.moveaxis(x, axis, -1)
        shape = moved.shape
        flat = moved.reshape(-1, 1, shape[-1])
        out = jax.lax.conv_general_dilated(
            flat, k[None, None, :], (1,), [(pad_low, pad_high)],
            dimension_numbers=("NCH", "OIH", "NCH"),
            precision=jax.lax.Precision.HIGHEST,  # validated at 1e-4 vs
            # the naive translation; TPU DEFAULT lands at ~1e-3
        )
        return jnp.moveaxis(out.reshape(shape), -1, axis)

    return conv_axis(conv_axis(img, 0), 1)


@dataclasses.dataclass(eq=False)
class LCSExtractor(Transformer):
    """Image (X, Y, C) -> (numLCSValues, numKeypoints) descriptor matrix,
    column xKey·numPoolsY + yKey, row order: for each channel, for each
    (nx, ny) neighbor: [mean, std] interleaved (LCSExtractor.scala:96-127).
    """

    stride: int
    stride_start: int
    sub_patch_size: int
    vmap_batch = False  # ragged across shapes
    bucket_vmap = True  # but vmappable within a shape bucket

    def apply(self, img):
        return self._extract(jnp.asarray(img, jnp.float32))

    @partial(jax.jit, static_argnums=(0,))
    def _extract(self, img):
        s = self.sub_patch_size
        X, Y, C = img.shape
        means = _box_filter_same(img, s)
        sq = _box_filter_same(img * img, s)
        stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

        xs = jnp.arange(self.stride_start, X - self.stride_start, self.stride)
        ys = jnp.arange(self.stride_start, Y - self.stride_start, self.stride)
        # neighborhood offsets: -2s + s/2 - 1 .. s + s/2 - 1 step s
        start = -2 * s + s // 2 - 1
        end = s + s // 2 - 1
        offs = jnp.arange(start, end + 1, s)

        px = xs[:, None] + offs[None, :]  # (nx_keys, nb)
        py = ys[:, None] + offs[None, :]  # (ny_keys, nb)
        # gather (nx_keys, nb, ny_keys, nb, C)
        m = means[px][:, :, py]
        sd = stds[px][:, :, py]
        # target layout rows: c, nx, ny -> interleaved mean/std;
        # columns: xKey * numPoolsY + yKey
        m = jnp.transpose(m, (4, 1, 3, 0, 2))  # (C, nbx, nby, xk, yk)
        sd = jnp.transpose(sd, (4, 1, 3, 0, 2))
        inter = jnp.stack([m, sd], axis=3)  # (C, nbx, nby, 2, xk, yk)
        n_keys = xs.shape[0] * ys.shape[0]
        return inter.reshape(-1, n_keys)
