"""Local Color Statistics (LCS) extractor.

Reference: nodes/images/LCSExtractor.scala:25 — per grid keypoint, the
means and standard deviations of each RGB channel over a 4x4 neighborhood
of sub-patches (96-dim descriptors); means/stds come from a centered box
filter (ImageUtils.conv2D zero-pads floor((L-1)/2) low / rest high, so an
even-length box is right-biased exactly as the reference's).

TPU mapping: the box filter is linear and separable, and the keypoint/
neighborhood positions are affine in (key, neighbor) — so box-mean →
sample folds into one per-axis SAMPLING MATRIX applied as MXU GEMMs
(same reformulation as SIFT's spatial binning, sift.py
``_sampling_matrix``), once on the image for means and once on its
square for the variances. No convs, no gathers. The GEMM pair runs as
the ``pallas_kernels.plane_sandwich`` kernel — each channel plane
(image and image² stacked) stays VMEM-resident between its two dots,
with interpret-mode fallback keeping CPU CI on the same dataflow.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images.pallas_kernels import plane_sandwich
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer


def _box_filter_same(img: jnp.ndarray, size: int) -> jnp.ndarray:
    """(H, W, C) -> same-size box mean with the reference's asymmetric
    zero padding (ImageUtils.conv2D:226-238)."""
    pad_low = (size - 1) // 2
    pad_high = size - 1 - pad_low
    k = jnp.full((size,), 1.0 / size, jnp.float32)

    def conv_axis(x, axis):
        moved = jnp.moveaxis(x, axis, -1)
        shape = moved.shape
        flat = moved.reshape(-1, 1, shape[-1])
        out = jax.lax.conv_general_dilated(
            flat, k[None, None, :], (1,), [(pad_low, pad_high)],
            dimension_numbers=("NCH", "OIH", "NCH"),
            precision=jax.lax.Precision.HIGHEST,  # validated at 1e-4 vs
            # the naive translation; TPU DEFAULT lands at ~1e-3
        )
        return jnp.moveaxis(out.reshape(shape), -1, axis)

    return conv_axis(conv_axis(img, 0), 1)


def _lcs_sampling_matrix(
    n: int, keys: np.ndarray, offs: np.ndarray, s: int
) -> np.ndarray:
    """(n, n_keys·nb) one-axis operator: column k·nb + j holds the 1/s
    box window whose output position is keys[k] + offs[j] under the
    reference's asymmetric zero padding (window start = pos −
    floor((s−1)/2); out-of-image taps drop, matching conv2D's zero
    pad). Box-filter → sample is linear and separable, so applying this
    per axis reproduces it exactly as MXU GEMMs."""
    pad_low = (s - 1) // 2
    nb = len(offs)
    m = np.zeros((n, len(keys) * nb), np.float32)
    for k, x0 in enumerate(keys):
        for j, o in enumerate(offs):
            lo = x0 + o - pad_low
            for t in range(s):
                p = lo + t
                if 0 <= p < n:
                    m[p, k * nb + j] += 1.0 / s
    return m


@dataclasses.dataclass(eq=False)
class LCSExtractor(Transformer):
    """Image (X, Y, C) -> (numLCSValues, numKeypoints) descriptor matrix,
    column xKey·numPoolsY + yKey, row order: for each channel, for each
    (nx, ny) neighbor: [mean, std] interleaved (LCSExtractor.scala:96-127).
    """

    stride: int
    stride_start: int
    sub_patch_size: int
    vmap_batch = False  # ragged across shapes
    bucket_vmap = True  # but vmappable within a shape bucket

    def apply(self, img):
        return self._extract(jnp.asarray(img, jnp.float32))

    @partial(jax.jit, static_argnums=(0,))
    def _extract(self, img):
        s = self.sub_patch_size
        X, Y, C = img.shape
        xs = np.arange(self.stride_start, X - self.stride_start, self.stride)
        ys = np.arange(self.stride_start, Y - self.stride_start, self.stride)
        # neighborhood offsets: -2s + s/2 - 1 .. s + s/2 - 1 step s
        start = -2 * s + s // 2 - 1
        end = s + s // 2 - 1
        offs = np.arange(start, end + 1, s)

        Ax = _lcs_sampling_matrix(X, xs, offs, s)
        Ay = jnp.asarray(_lcs_sampling_matrix(Y, ys, offs, s))
        # image and its square share the GEMM chain (stacked channel
        # planes through the Pallas sandwich kernel; HIGHEST-precision
        # dots in-kernel — validated at 1e-4 vs the naive translation,
        # TPU DEFAULT lands at ~1e-3)
        z = jnp.concatenate([img, img * img], axis=-1)
        out = plane_sandwich(
            jnp.transpose(z, (2, 0, 1)), jnp.asarray(Ax.T.copy()), Ay
        )
        both = jnp.transpose(out, (1, 2, 0))  # (nxk·nb, nyk·nb, 2C)
        m, sq = both[..., :C], both[..., C:]
        sd = jnp.sqrt(jnp.maximum(sq - m * m, 0.0))

        nxk, nyk, nb = len(xs), len(ys), len(offs)

        # target layout rows: c, nx, ny -> interleaved mean/std;
        # columns: xKey * numPoolsY + yKey
        def arrange(z):
            z = z.reshape(nxk, nb, nyk, nb, C)
            return jnp.transpose(z, (4, 1, 3, 0, 2))  # (C, nbx, nby, xk, yk)

        inter = jnp.stack([arrange(m), arrange(sd)], axis=3)
        return inter.reshape(-1, nxk * nyk)
