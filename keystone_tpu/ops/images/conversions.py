"""Explicit image representation conversions + round-trips.

Reference: utils/images/ImageConversions.scala — decoded byte buffers
(BGR / ABGR / gray) to the row-major image wrapper
(bufferedImageToWrapper:10), grayscale tripling (grayScaleImageToWrapper:
26), and image -> packed-int RGB export with optional min/max scaling
(imageToBufferedImage:48). The TPU-native image representation is a plain
(H, W, C) float array, so conversions are vectorized array ops instead of
per-pixel loops; the packed-RGB pair gives an exact export/import
round-trip for display and debugging.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


def bytes_to_image(
    data, height: int, width: int, channels: int, order: str = "bgr"
) -> jnp.ndarray:
    """Interleaved decoded bytes -> (H, W, C) float32 image. ``order``
    names the source channel layout ("bgr", "abgr", "rgb", "gray");
    output is always RGB (or single-channel), alpha dropped — the
    Java-decoder layouts ImageConversions.scala:10-24 normalizes."""
    arr = np.frombuffer(bytes(data), np.uint8).astype(np.float32)
    arr = arr.reshape(height, width, channels)
    if order == "bgr":
        if channels != 3:
            raise ValueError("bgr order requires 3 channels")
        arr = arr[:, :, ::-1]
    elif order == "abgr":
        if channels != 4:
            raise ValueError("abgr order requires 4 channels")
        arr = arr[:, :, :0:-1]  # drop alpha, reverse to RGB
    elif order == "gray":
        if channels != 1:
            raise ValueError("gray order requires 1 channel")
    elif order != "rgb":
        raise ValueError(f"unknown channel order {order!r}")
    return jnp.asarray(np.ascontiguousarray(arr))


def gray_to_rgb(img: jnp.ndarray) -> jnp.ndarray:
    """(H, W) or (H, W, 1) -> (H, W, 3) by channel replication
    (ImageConversions.scala:26-37)."""
    if img.ndim == 2:
        img = img[:, :, None]
    if img.shape[-1] != 1:
        raise ValueError(f"expected 1 channel, got {img.shape[-1]}")
    return jnp.broadcast_to(img, img.shape[:2] + (3,))


def image_to_rgb_ints(
    img: jnp.ndarray, scale: bool = False
) -> jnp.ndarray:
    """(H, W, 3|1) float image -> (H, W) packed int32 RGB
    (r<<16 | g<<8 | b), optionally min/max-scaled to [0, 255]
    (ImageConversions.scala:48-83)."""
    if img.ndim == 2:
        img = img[:, :, None]
    if img.shape[-1] == 1:
        img = gray_to_rgb(img)
    if scale:
        lo, hi = jnp.min(img), jnp.max(img)
        img = 255.0 * (img - lo) / jnp.maximum(hi - lo, 1e-12)
    rgb = jnp.clip(img, 0, 255).astype(jnp.int32)
    return (rgb[..., 0] << 16) | (rgb[..., 1] << 8) | rgb[..., 2]


def rgb_ints_to_image(packed: jnp.ndarray) -> jnp.ndarray:
    """(H, W) packed int32 RGB -> (H, W, 3) float32 — inverse of
    ``image_to_rgb_ints`` (exact for byte-valued images)."""
    r = (packed >> 16) & 0xFF
    g = (packed >> 8) & 0xFF
    b = packed & 0xFF
    return jnp.stack([r, g, b], axis=-1).astype(jnp.float32)


def hwc_to_chw(img: jnp.ndarray) -> jnp.ndarray:
    return jnp.transpose(img, (2, 0, 1))


def chw_to_hwc(img: jnp.ndarray) -> jnp.ndarray:
    return jnp.transpose(img, (1, 2, 0))


def vectorize(img: jnp.ndarray) -> jnp.ndarray:
    """(H, W, C) -> flat channel-major vector (all of channel 0, then
    channel 1, ...) — the reference wrappers' vectorized layout
    (utils/images/Image.scala ChannelMajorArrayVectorizedImage)."""
    return hwc_to_chw(img).reshape(-1)


def unvectorize(
    vec: jnp.ndarray, shape: Tuple[int, int, int]
) -> jnp.ndarray:
    """Inverse of ``vectorize`` given the (H, W, C) shape."""
    h, w, c = shape
    return chw_to_hwc(vec.reshape(c, h, w))
