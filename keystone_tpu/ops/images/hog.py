"""Histogram of Oriented Gradients (Felzenszwalb/voc-release variant).

Reference: nodes/images/HogExtractor.scala:33 (itself a translation of
Girshick's voc-dpm features.cc): per-pixel max-channel central-difference
gradient, snapping to 18 contrast-sensitive orientations via dot products
with 9 unit vectors, bilinear binning into binSize cells, 4-way block
normalization with 0.2 clamping, 27+4+1 features per interior cell.

TPU mapping: the per-pixel work is fused elementwise XLA; the bilinear
scatter is one segment-sum (.at[].add); the normalization stage is pure
gather arithmetic.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer

EPSILON = 0.0001
UU = np.array(
    [1.0, 0.9397, 0.766, 0.5, 0.1736, -0.1736, -0.5, -0.766, -0.9397]
)
VV = np.array(
    [0.0, 0.342, 0.6428, 0.866, 0.9848, 0.9848, 0.866, 0.6428, 0.342]
)


@dataclasses.dataclass(eq=False)
class HogExtractor(Transformer):
    """Image (X, Y, C) -> (numInteriorCells, 32) feature matrix."""

    bin_size: int
    vmap_batch = False  # ragged across shapes
    bucket_vmap = True  # but vmappable within a shape bucket

    def apply(self, img):
        return self._extract(jnp.asarray(img, jnp.float32))

    @partial(jax.jit, static_argnums=(0,))
    def _extract(self, img):
        b = self.bin_size
        X, Y, C = img.shape
        nx = int(round(X / b))
        ny = int(round(Y / b))
        vis_x = min(nx * b, X)
        vis_y = min(ny * b, Y)

        # -- per-pixel gradient, max-magnitude channel ------------------
        xs = jnp.arange(1, vis_x - 1)
        ys = jnp.arange(1, vis_y - 1)
        sub = img[:vis_x, :vis_y]
        dx = sub[2:, 1:-1, :] - sub[:-2, 1:-1, :]
        dy = sub[1:-1, 2:, :] - sub[1:-1, :-2, :]
        mag2 = dx * dx + dy * dy
        # reference iterates channels 2->0 keeping strictly-greater:
        # highest channel index wins ties; argmax picks first max, so
        # reverse the channel order
        rev = mag2[:, :, ::-1]
        best = jnp.argmax(rev, axis=2)
        ch = C - 1 - best
        gx = jnp.take_along_axis(dx, ch[:, :, None], axis=2)[:, :, 0]
        gy = jnp.take_along_axis(dy, ch[:, :, None], axis=2)[:, :, 0]
        mag = jnp.sqrt(
            jnp.take_along_axis(mag2, ch[:, :, None], axis=2)[:, :, 0]
        )

        # -- orientation snapping (interleaved pos/neg candidates keeps
        # the reference's first-strict-max tie-breaking) ----------------
        uu = jnp.asarray(UU, jnp.float32)
        vv = jnp.asarray(VV, jnp.float32)
        dots = uu[None, None, :] * gy[:, :, None] + vv[None, None, :] * gx[
            :, :, None
        ]  # (px, py, 9)
        cand = jnp.stack([dots, -dots], axis=3).reshape(
            dots.shape[0], dots.shape[1], 18
        )  # interleaved: pos0, neg0, pos1, neg1, ...
        arg = jnp.argmax(cand, axis=2)
        orient = (arg // 2) + 9 * (arg % 2)
        orient = jnp.where(jnp.max(cand, axis=2) > 0.0, orient, 0)

        # -- bilinear binning into cells --------------------------------
        px = xs[:, None] * jnp.ones_like(ys)[None, :]
        py = jnp.ones_like(xs)[:, None] * ys[None, :]
        xp = (px + 0.5) / b - 0.5
        yp = (py + 0.5) / b - 0.5
        ixp = jnp.floor(xp).astype(jnp.int32)
        iyp = jnp.floor(yp).astype(jnp.int32)
        vx0 = xp - ixp
        vy0 = yp - iyp
        hist = jnp.zeros((nx, ny, 18), jnp.float32)

        def scatter(hist, cx, cy, w):
            ok = (cx >= 0) & (cx < nx) & (cy >= 0) & (cy < ny)
            cxc = jnp.clip(cx, 0, nx - 1)
            cyc = jnp.clip(cy, 0, ny - 1)
            return hist.at[cxc, cyc, orient].add(
                jnp.where(ok, w * mag, 0.0)
            )

        hist = scatter(hist, ixp, iyp, (1 - vx0) * (1 - vy0))
        hist = scatter(hist, ixp, iyp + 1, (1 - vx0) * vy0)
        hist = scatter(hist, ixp + 1, iyp, vx0 * (1 - vy0))
        hist = scatter(hist, ixp + 1, iyp + 1, vx0 * vy0)

        # -- block energies ---------------------------------------------
        combined = hist[:, :, :9] + hist[:, :, 9:]
        norm = jnp.sum(combined * combined, axis=2)  # (nx, ny)

        nxf = max(nx - 2, 0)
        nyf = max(ny - 2, 0)
        if nxf == 0 or nyf == 0:
            return jnp.zeros((0, 32), jnp.float32)
        cx = jnp.arange(nxf)
        cy = jnp.arange(nyf)
        gx_, gy_ = jnp.meshgrid(cx, cy, indexing="ij")

        def block(nox, noy):
            return (
                norm[gx_ + nox, gy_ + noy]
                + norm[gx_ + nox + 1, gy_ + noy]
                + norm[gx_ + nox, gy_ + noy + 1]
                + norm[gx_ + nox + 1, gy_ + noy + 1]
            )

        n1 = 1.0 / jnp.sqrt(block(1, 1) + EPSILON)
        n2 = 1.0 / jnp.sqrt(block(0, 1) + EPSILON)
        n3 = 1.0 / jnp.sqrt(block(1, 0) + EPSILON)
        n4 = 1.0 / jnp.sqrt(block(0, 0) + EPSILON)

        h_cell = hist[gx_ + 1, gy_ + 1, :]  # (nxf, nyf, 18)
        h1 = jnp.minimum(h_cell * n1[:, :, None], 0.2)
        h2 = jnp.minimum(h_cell * n2[:, :, None], 0.2)
        h3 = jnp.minimum(h_cell * n3[:, :, None], 0.2)
        h4 = jnp.minimum(h_cell * n4[:, :, None], 0.2)
        sensitive = 0.5 * (h1 + h2 + h3 + h4)  # 18 features

        c_cell = combined[gx_ + 1, gy_ + 1, :]  # (nxf, nyf, 9)
        c1 = jnp.minimum(c_cell * n1[:, :, None], 0.2)
        c2 = jnp.minimum(c_cell * n2[:, :, None], 0.2)
        c3 = jnp.minimum(c_cell * n3[:, :, None], 0.2)
        c4 = jnp.minimum(c_cell * n4[:, :, None], 0.2)
        insensitive = 0.5 * (c1 + c2 + c3 + c4)  # 9 features

        texture = 0.2357 * jnp.stack(
            [jnp.sum(h1, 2), jnp.sum(h2, 2), jnp.sum(h3, 2), jnp.sum(h4, 2)],
            axis=2,
        )  # 4 features
        trunc = jnp.zeros(texture.shape[:2] + (1,), jnp.float32)

        feats = jnp.concatenate(
            [sensitive, insensitive, texture, trunc], axis=2
        )  # (nxf, nyf, 32)
        # row index: y + x * numYCellsWithFeatures (reference layout)
        return feats.reshape(nxf * nyf, 32)
