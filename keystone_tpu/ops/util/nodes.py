"""Representation/utility nodes.

Reference: nodes/util/*.scala — VectorSplitter, ClassLabelIndicators,
CommonSparseFeatures/AllSparseFeatures/SparseFeatureVectorizer,
MaxClassifier/TopKClassifier, Densify/Sparsify/FloatToDouble/
MatrixVectorizer/VectorCombiner/Shuffler.

Sparse data uses jax.experimental.sparse.BCOO so sparse models still run as
XLA programs on the MXU-adjacent hardware rather than host loops.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, FunctionNode, Transformer


class VectorSplitter(FunctionNode):
    """Split a dataset of feature vectors into feature-dimension blocks —
    the primitive behind all block solvers (reference:
    nodes/util/VectorSplitter.scala). Returns a list of Datasets, one per
    block; the last block may be narrower."""

    def __init__(self, block_size: int, num_features: int = None):
        self.block_size = block_size
        self.num_features = num_features

    def apply(self, data: Any) -> List[Dataset]:
        ds = Dataset.of(data if isinstance(data, Dataset) else data)
        x = ds.padded()
        d = self.num_features or x.shape[1]
        blocks = []
        for start in range(0, d, self.block_size):
            end = min(start + self.block_size, d)
            blocks.append(Dataset.from_array(x[:, start:end], n=ds.n))
        return blocks


@dataclasses.dataclass(eq=False)
class ClassLabelIndicators(Transformer):
    """int label -> ±1 indicator vector (reference:
    nodes/util/ClassLabelIndicators.scala:15)."""

    num_classes: int

    def apply(self, y):
        return 2.0 * jax.nn.one_hot(y, self.num_classes) - 1.0

    def apply_batch(self, ds: Dataset) -> Dataset:
        y = ds.padded().astype(jnp.int32)
        out = 2.0 * jax.nn.one_hot(y, self.num_classes) - 1.0
        # one-hot of zero pad rows is (+1,-1,...): keep pad rows zero
        out = out * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class ClassLabelIndicatorsFromIntArrayLabels(Transformer):
    """multi-label int array -> ±1 indicator vector."""

    num_classes: int
    vmap_batch = False

    def apply(self, ys):
        base = -np.ones(self.num_classes, dtype=np.float32)
        base[np.asarray(ys, dtype=np.int64)] = 1.0
        return jnp.asarray(base)


class MaxClassifier(Transformer):
    """argmax over scores (reference: nodes/util/MaxClassifier.scala)."""

    def apply(self, scores):
        return jnp.argmax(scores, axis=-1)

    def apply_batch(self, ds: Dataset) -> Dataset:
        return Dataset.from_array(
            jnp.argmax(ds.padded(), axis=-1), n=ds.n
        )

    def eq_key(self):
        return ("max_classifier",)


@dataclasses.dataclass(eq=False)
class TopKClassifier(Transformer):
    """top-k class indices, best first (reference: TopKClassifier.scala)."""

    k: int

    def apply(self, scores):
        _, idx = jax.lax.top_k(scores, min(self.k, scores.shape[-1]))
        return idx

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        _, idx = jax.lax.top_k(x, min(self.k, x.shape[-1]))
        return Dataset.from_array(idx, n=ds.n)


class VectorCombiner(Transformer):
    """Concatenate gathered branch outputs along the feature axis
    (reference: nodes/util/VectorCombiner.scala)."""

    def apply(self, parts):
        return jnp.concatenate([jnp.ravel(p) for p in parts], axis=0)

    def apply_batch(self, ds: Dataset) -> Dataset:
        arrs = ds.padded()
        if isinstance(arrs, tuple):
            flat = [a.reshape(a.shape[0], -1) for a in arrs]
            return Dataset.from_array(jnp.concatenate(flat, axis=1), n=ds.n)
        return ds.map(self.apply)

    def eq_key(self):
        return ("vector_combiner",)


class MatrixVectorizer(Transformer):
    """Flatten a matrix datum into a vector (column-major, matching Breeze's
    DenseMatrix.toDenseVector semantics in the reference)."""

    def apply(self, m):
        return jnp.ravel(m, order="F")

    def eq_key(self):
        return ("matrix_vectorizer",)


class FloatToDouble(Transformer):
    def apply(self, x):
        return x.astype(jnp.float64) if jax.config.jax_enable_x64 else x.astype(jnp.float32)

    def eq_key(self):
        return ("float_to_double",)


class Densify(Transformer):
    """Sparse BCOO -> dense."""

    vmap_batch = False

    def apply(self, x):
        return x.todense() if isinstance(x, jsparse.BCOO) else jnp.asarray(x)

    def apply_batch(self, ds: Dataset) -> Dataset:
        if ds.is_array:
            arrs = ds.padded()
            if isinstance(arrs, jsparse.BCOO):
                return Dataset.from_array(arrs.todense(), n=ds.n)
            return ds
        return ds.map(self.apply)

    def eq_key(self):
        return ("densify",)


class Sparsify(Transformer):
    """Dense -> sparse BCOO batch."""

    vmap_batch = False

    def apply(self, x):
        return jsparse.BCOO.fromdense(jnp.asarray(x))

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.to_array_mode().padded()
        return Dataset.from_array(jsparse.BCOO.fromdense(x), n=ds.n)

    def eq_key(self):
        return ("sparsify",)


class Shuffler(Transformer):
    """Random permutation of examples (reference: repartition-based
    Shuffler). ``device=True`` routes rows through one ``lax.all_to_all``
    over the mesh's data axis (parallel/shuffle.py) — the shuffle never
    leaves the devices; the default host path materializes and permutes
    (bit-identical results either way)."""

    def __init__(self, seed: int = 0, device: bool = False):
        self.seed = seed
        self.device = device

    def apply(self, x):
        return x

    def apply_batch(self, ds: Dataset) -> Dataset:
        if (
            self.device
            and ds.is_array
            and not isinstance(ds.padded(), tuple)
        ):
            from keystone_tpu.parallel import mesh as mesh_lib
            from keystone_tpu.parallel.shuffle import device_shuffle

            mesh = mesh_lib.current_mesh()
            x = ds.padded()
            if x.shape[0] % mesh_lib.n_data_shards(mesh) == 0:
                return Dataset.from_array(
                    device_shuffle(x, ds.n, self.seed, mesh), n=ds.n
                )
            import logging

            logging.getLogger(__name__).warning(
                "Shuffler(device=True): %d padded rows not divisible by "
                "%d data shards; falling back to the host path (full "
                "array materializes on host)",
                x.shape[0], mesh_lib.n_data_shards(mesh),
            )
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(ds.n)
        if ds.is_array and not isinstance(ds.padded(), tuple):
            x = ds.array()
            return Dataset.from_array(jnp.asarray(np.asarray(x))[perm], n=ds.n)
        items = ds.items()
        return Dataset.from_items([items[i] for i in perm])


# -- sparse feature space estimators ---------------------------------------


@dataclasses.dataclass(eq=False)
class SparseFeatureVectorizer(Transformer):
    """term-count dict -> BCOO sparse vector given a feature->index map
    (reference: nodes/util/SparseFeatureVectorizer.scala)."""

    feature_index: dict
    dim: int
    vmap_batch = False

    def apply(self, counts: dict):
        idx, vals = [], []
        for k, v in counts.items():
            j = self.feature_index.get(k)
            if j is not None:
                idx.append(j)
                vals.append(v)
        order = np.argsort(idx) if idx else []
        indices = np.asarray(idx, dtype=np.int32)[order].reshape(-1, 1)
        values = np.asarray(vals, dtype=np.float32)[order]
        return jsparse.BCOO(
            (jnp.asarray(values), jnp.asarray(indices)), shape=(self.dim,)
        )

    def apply_batch(self, ds: Dataset) -> Dataset:
        """Batch to one (n, dim) BCOO matrix."""
        rows, cols, vals = [], [], []
        items = ds.items()
        for i, counts in enumerate(items):
            for k, v in counts.items():
                j = self.feature_index.get(k)
                if j is not None:
                    rows.append(i)
                    cols.append(j)
                    vals.append(v)
        indices = jnp.asarray(
            np.stack(
                [np.asarray(rows, np.int32), np.asarray(cols, np.int32)],
                axis=1,
            )
            if rows
            else np.zeros((0, 2), np.int32)
        )
        values = jnp.asarray(np.asarray(vals, np.float32))
        mat = jsparse.BCOO(
            (values, indices), shape=(len(items), self.dim)
        )
        return Dataset.from_array(mat, n=len(items))

    def eq_key(self):
        return ("sparse_vectorizer", self.dim, id(self.feature_index))


@dataclasses.dataclass(eq=False)
class CommonSparseFeatures(Estimator):
    """Keep the top-k most frequent features (reference:
    nodes/util/CommonSparseFeatures.scala — per-partition takeOrdered +
    treeReduce merge; here a host Counter over the training sample)."""

    num_features: int

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        counts: Counter = Counter()
        for item in data.items():
            # every occurrence counts once, value included-but-ignored —
            # CommonSparseFeatures.scala:37 flatMaps all (feature, value)
            # pairs with weight 1 regardless of the value
            counts.update(item.keys())
        top = [k for k, _ in counts.most_common(self.num_features)]
        index = {k: i for i, k in enumerate(top)}
        return SparseFeatureVectorizer(index, self.num_features)


@dataclasses.dataclass(eq=False)
class AllSparseFeatures(Estimator):
    """Keep every observed feature, deterministically ordered (reference:
    nodes/util/AllSparseFeatures.scala)."""

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        seen = set()
        for item in data.items():
            seen.update(item.keys())
        ordered = sorted(seen, key=lambda k: str(k))
        index = {k: i for i, k in enumerate(ordered)}
        return SparseFeatureVectorizer(index, len(ordered))
