"""Cacher — identity transformer that materializes its input.

Reference: nodes/util/Cacher.scala. Doubles as the marker the optimizer's
ExtractSaveablePrefixes rule uses to decide which intermediate results are
worth persisting in the cross-pipeline prefix state.

On TPU, "cache" means: force the lazy batched computation now and keep the
resulting device buffers, so downstream consumers (and the auto-cache rule's
run-count analysis) see a materialized array instead of recomputing the
upstream chain.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class Cacher(Transformer):
    name: str = ""

    def apply(self, x: Any) -> Any:
        return x

    def apply_batch(self, ds: Dataset) -> Dataset:
        return ds.cache()

    def eq_key(self):
        return ("cacher", self.name, id(self) if not self.name else None)
