from keystone_tpu.ops.util.cacher import Cacher  # noqa: F401
from keystone_tpu.ops.util.nodes import (  # noqa: F401
    AllSparseFeatures,
    ClassLabelIndicators,
    ClassLabelIndicatorsFromIntArrayLabels,
    CommonSparseFeatures,
    Densify,
    FloatToDouble,
    MatrixVectorizer,
    MaxClassifier,
    Shuffler,
    Sparsify,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)
