"""Statistical feature nodes.

Reference: nodes/stats/*.scala — CosineRandomFeatures, PaddedFFT,
StandardScaler, LinearRectifier, RandomSignNode, NormalizeRows,
SignedHellingerMapper, TermFrequency, Sampling.

TPU-first notes: every batch path is one fused jnp expression over the
sharded (n, d) matrix — XLA maps the matmuls onto the MXU and fuses the
elementwise tails; reductions over the example axis turn into psums over the
mesh's data axis automatically under jit.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import mm
from keystone_tpu.workflow.api import Estimator, FunctionNode, Transformer


@dataclasses.dataclass(eq=False)
class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed ±1 sign vector (reference:
    nodes/stats/RandomSignNode.scala:10; factory draws Binomial signs)."""

    signs: Any  # (d,) array of ±1

    @staticmethod
    def create(d: int, seed: int = 0) -> "RandomSignNode":
        rng = np.random.default_rng(seed)
        signs = rng.integers(0, 2, size=d).astype(np.float32) * 2.0 - 1.0
        return RandomSignNode(jnp.asarray(signs))

    def apply(self, x):
        return x * self.signs

    def apply_batch(self, ds: Dataset) -> Dataset:
        return Dataset.from_array(ds.padded() * self.signs, n=ds.n)


@dataclasses.dataclass(eq=False)
class PaddedFFT(Transformer):
    """Zero-pad to the next power of two, real FFT, keep the real parts of
    the first half (reference: nodes/stats/PaddedFFT.scala:13 — Breeze
    fourierTr then x(0 until pad/2).map(_.real))."""

    def _pad_len(self, d: int) -> int:
        return int(2 ** np.ceil(np.log2(max(d, 1))))

    def apply(self, x):
        pad = self._pad_len(x.shape[-1])
        xp = jnp.zeros(pad, x.dtype).at[: x.shape[-1]].set(x)
        return jnp.real(jnp.fft.fft(xp))[: pad // 2]

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        pad = self._pad_len(x.shape[-1])
        xp = jnp.pad(x, ((0, 0), (0, pad - x.shape[-1])))
        return Dataset.from_array(
            jnp.real(jnp.fft.fft(xp, axis=-1))[:, : pad // 2], n=ds.n
        )

    def eq_key(self):
        return ("padded_fft",)


@partial(jax.jit, static_argnames=("pad", "thresh"))
def _fft_bank_chunk(chunk, signs, mask, *, pad: int, thresh: float):
    """One fused program for a row chunk of RandomFFTFeatures — module
    level so the jit cache is shared across instances and calls. ``mask``
    re-zeroes pad rows when thresh > 0 would lift them (fused, so no
    extra full-array pass; mirrors LinearRectifier.apply_batch)."""
    f = signs.shape[0]
    xs = chunk[:, None, :] * signs[None, :, :]
    spec = jnp.real(jnp.fft.fft(xs, n=pad, axis=-1))[:, :, : pad // 2]
    out = jnp.maximum(spec, thresh).reshape(chunk.shape[0], f * (pad // 2))
    if thresh > 0:
        out = out * mask[:, None]
    return out


@dataclasses.dataclass(eq=False)
class RandomFFTFeatures(Transformer):
    """All ``num_ffts`` random-sign -> PaddedFFT -> rectify branches of
    the MnistRandomFFT featurization in ONE jitted program (reference
    composes per-branch pipelines, MnistRandomFFT.scala:28-37; the math
    is identical — this is the batched physical plan: one (num_ffts, d)
    sign matrix, one batched FFT, one reshape, instead of 3 x num_ffts
    separate dispatches + a concatenate)."""

    signs: Any  # (num_ffts, d)
    rectify_threshold: float = 0.0
    row_chunk: int = 8192  # bounds the (chunk, num_ffts, pad) intermediate

    @staticmethod
    def create(
        d: int, num_ffts: int, seed: int = 0, rectify_threshold: float = 0.0
    ) -> "RandomFFTFeatures":
        """Branch i's signs match ``RandomSignNode.create(d, seed + i)``,
        so the fused node is numerically interchangeable with the
        composed per-branch pipelines."""
        signs = np.stack([
            np.random.default_rng(seed + i)
            .integers(0, 2, size=d)
            .astype(np.float32) * 2.0 - 1.0
            for i in range(num_ffts)
        ])
        return RandomFFTFeatures(
            jnp.asarray(signs), rectify_threshold=rectify_threshold
        )

    def _pad_len(self, d: int) -> int:
        return int(2 ** np.ceil(np.log2(max(d, 1))))

    @property
    def out_dim(self) -> int:
        return self.signs.shape[0] * (self._pad_len(self.signs.shape[1]) // 2)

    def apply(self, x):
        pad = self._pad_len(x.shape[-1])
        xs = x[None, :] * self.signs  # (num_ffts, d)
        spec = jnp.real(jnp.fft.fft(xs, n=pad, axis=-1))[:, : pad // 2]
        return jnp.maximum(spec, self.rectify_threshold).reshape(-1)

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        pad = self._pad_len(x.shape[-1])
        mask = ds.mask()
        outs = [
            _fft_bank_chunk(
                x[s : s + self.row_chunk], self.signs,
                mask[s : s + self.row_chunk],
                pad=pad, thresh=self.rectify_threshold,
            )
            for s in range(0, x.shape[0], self.row_chunk)
        ]
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class LinearRectifier(Transformer):
    """max(max_val, x - alpha) (reference:
    nodes/stats/LinearRectifier.scala:12)."""

    max_val: float = 0.0
    alpha: float = 0.0

    def apply(self, x):
        return jnp.maximum(self.max_val, x - self.alpha)

    def apply_batch(self, ds: Dataset) -> Dataset:
        out = jnp.maximum(self.max_val, ds.padded() - self.alpha)
        if self.max_val > 0 or self.alpha < 0:
            # rectified zero pad rows would be nonzero: keep the invariant
            out = out * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class NormalizeRows(Transformer):
    """L2 row normalization with a tiny-norm floor (reference:
    nodes/stats/NormalizeRows.scala:10, floor 2.2e-16)."""

    floor: float = 2.2e-16

    def apply(self, x):
        nrm = jnp.linalg.norm(x)
        return x / jnp.maximum(nrm, self.floor)

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        nrm = jnp.linalg.norm(x, axis=-1, keepdims=True)
        return Dataset.from_array(x / jnp.maximum(nrm, self.floor), n=ds.n)


@dataclasses.dataclass(eq=False)
class SignedHellingerMapper(Transformer):
    """Signed square-root power normalization: sign(x) * sqrt(|x|)
    (reference: nodes/stats/SignedHellingerMapper.scala:12; the Batch- matrix
    variant is the same expression on a matrix)."""

    def apply(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        return Dataset.from_array(jnp.sign(x) * jnp.sqrt(jnp.abs(x)), n=ds.n)

    def eq_key(self):
        return ("signed_hellinger",)


@dataclasses.dataclass(eq=False)
class StandardScalerModel(Transformer):
    """x -> (x - mean) / std (std division optional). Padding rows are
    re-zeroed after centering so downstream Gram-matrix math stays exact
    (reference: nodes/stats/StandardScaler.scala:16)."""

    mean: Any  # (d,)
    std: Optional[Any] = None  # (d,) or None

    def apply(self, x):
        out = x - self.mean
        if self.std is not None:
            out = out / self.std
        return out

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        out = x - self.mean
        if self.std is not None:
            out = out / self.std
        out = out * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class StandardScaler(Estimator):
    """Column mean/std via one sharded reduction pass (reference:
    nodes/stats/StandardScaler.scala:38 — treeAggregate of a
    MultivariateOnlineSummarizer; here the all-reduce is the XLA psum that
    jit inserts for the sum over the sharded example axis). Unbiased
    variance (n-1), eps guard matching MLlib behavior."""

    normalize_std_dev: bool = True
    eps: float = 1e-12

    def fit(self, data: Dataset) -> StandardScalerModel:
        x = data.padded()
        n = data.n
        s1 = jnp.sum(x, axis=0)  # pad rows are zero — exact
        s2 = jnp.sum(x * x, axis=0)
        mean = s1 / n
        if not self.normalize_std_dev:
            return StandardScalerModel(mean, None)
        var = (s2 - n * mean * mean) / max(n - 1, 1)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        std = jnp.where(std < self.eps, 1.0, std)
        return StandardScalerModel(mean, std)


@dataclasses.dataclass(eq=False)
class CosineRandomFeatures(Transformer):
    """Random Fourier features cos(x Wᵀ + b) (reference:
    nodes/stats/CosineRandomFeatures.scala:19,49 — batch path is one GEMM
    with broadcast W; here one MXU matmul + fused cos)."""

    W: Any  # (num_features, d)
    b: Any  # (num_features,)

    @staticmethod
    def create(
        d: int,
        num_features: int,
        gamma: float,
        seed: int = 0,
        distribution: str = "gaussian",
    ) -> "CosineRandomFeatures":
        rng = np.random.default_rng(seed)
        if distribution == "cauchy":
            w = rng.standard_cauchy((num_features, d)) * gamma
        else:
            w = rng.standard_normal((num_features, d)) * gamma
        b = rng.uniform(0.0, 2.0 * np.pi, num_features)
        return CosineRandomFeatures(
            jnp.asarray(w, jnp.float32), jnp.asarray(b, jnp.float32)
        )

    def apply(self, x):
        return jnp.cos(mm(x, self.W.T) + self.b)

    def apply_batch(self, ds: Dataset) -> Dataset:
        x = ds.padded()
        out = jnp.cos(mm(x, self.W.T) + self.b)
        # cos(0 + b) != 0: keep the pad-rows-are-zero invariant
        out = out * ds.mask()[:, None]
        return Dataset.from_array(out, n=ds.n)


@dataclasses.dataclass(eq=False)
class TermFrequency(Transformer):
    """term sequence -> {term: weighted count} with a pluggable weighting
    function (reference: nodes/stats/TermFrequency.scala:19)."""

    fn: Callable[[float], float] = lambda x: x
    vmap_batch = False

    def apply(self, terms):
        # Counter consumes the generator at C speed — this node is on
        # the hot host path of every text pipeline (ngram lists become
        # hashable tuples on the way in)
        counts = Counter(
            tuple(t) if isinstance(t, list) else t for t in terms
        )
        return {k: self.fn(v) for k, v in counts.items()}

    def eq_key(self):
        return ("term_frequency", self.fn)


class ColumnSampler(Transformer):
    """Sample ``num_cols`` columns of each (d, m) matrix datum — used to
    subsample per-image descriptor sets before PCA/GMM fits (reference:
    nodes/stats/Sampling.scala:12)."""

    vmap_batch = False

    def __init__(self, num_cols: int, seed: int = 0):
        self.num_cols = num_cols
        self.seed = seed
        self._counter = 0

    def apply(self, m):
        arr = np.asarray(m)
        # independent draw per datum (reference samples per image)
        rng = np.random.default_rng((self.seed, self._counter))
        self._counter += 1
        idx = rng.integers(0, arr.shape[1], self.num_cols)
        return jnp.asarray(arr[:, idx])

    def eq_key(self):
        return ("column_sampler", self.num_cols, self.seed)


class Sampler(FunctionNode):
    """Eager takeSample of ~``size`` examples (reference:
    nodes/stats/Sampling.scala:28)."""

    def __init__(self, size: int, seed: int = 0):
        self.size = size
        self.seed = seed

    def apply(self, data: Any) -> Dataset:
        ds = Dataset.of(data)
        rng = np.random.default_rng(self.seed)
        k = min(self.size, ds.n)
        idx = np.sort(rng.choice(ds.n, size=k, replace=False))
        if ds.is_array and not isinstance(ds.padded(), tuple):
            x = np.asarray(ds.array())
            return Dataset.from_array(jnp.asarray(x[idx]), n=k)
        items = ds.items()
        return Dataset.from_items([items[i] for i in idx])
