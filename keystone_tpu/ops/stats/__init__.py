from keystone_tpu.ops.stats.nodes import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
    TermFrequency,
)

__all__ = [
    "ColumnSampler",
    "CosineRandomFeatures",
    "LinearRectifier",
    "NormalizeRows",
    "PaddedFFT",
    "RandomSignNode",
    "Sampler",
    "SignedHellingerMapper",
    "StandardScaler",
    "StandardScalerModel",
    "TermFrequency",
]
