from keystone_tpu.ops.stats.nodes import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomFFTFeatures,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
    TermFrequency,
)

__all__ = [
    "ColumnSampler",
    "CosineRandomFeatures",
    "LinearRectifier",
    "NormalizeRows",
    "PaddedFFT",
    "RandomFFTFeatures",
    "RandomSignNode",
    "Sampler",
    "SignedHellingerMapper",
    "StandardScaler",
    "StandardScalerModel",
    "TermFrequency",
]
