"""Built-in sequence taggers: a trainable averaged-perceptron POS tagger
and rule-based POS/NER fallbacks.

Reference: nodes/nlp/POSTagger.scala:24 and NER.scala:20 wrap pre-trained
Epic CRF/SemiCRF models (JVM-only, no in-environment equivalent). The
TPU-native framework ships its own trainable tagger instead: a greedy
averaged perceptron (Collins 2002-style structured perceptron with
averaged weights) fit by ``PerceptronTaggerEstimator`` from labeled
sentences — tagging is host-side string work here, like the rest of the
NLP layer; the heavy featurization downstream (hashing TF, n-grams) is
what rides the device.
"""

from __future__ import annotations

import dataclasses
import re
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, Transformer


def _emit_features(tokens: Sequence[str], i: int) -> List[str]:
    """Tag-history-free feature strings for token ``i`` — local context +
    shape + affixes. This is the emission feature set shared with the CRF
    taggers (crf.py), which model tag history through their transition
    table instead of through features. Fixed length (8)."""
    w = tokens[i]
    lo = w.lower()
    before = tokens[i - 1].lower() if i > 0 else "<s>"
    after = tokens[i + 1].lower() if i + 1 < len(tokens) else "</s>"
    return [
        "b",  # bias
        "w=" + lo,
        "sfx3=" + lo[-3:],
        "sfx2=" + lo[-2:],
        "pfx1=" + lo[:1],
        "shape=" + (
            "d" if w.isdigit()
            else "C" if w[:1].isupper() and i > 0
            else "c" if w[:1].isupper()
            else "x"
        ),
        "pw=" + before,
        "nw=" + after,
    ]


def _features(
    tokens: Sequence[str], i: int, prev: str, prev2: str
) -> List[str]:
    """Feature strings for token ``i`` given the two previous predicted
    tags — the emission set plus tag-history conjunctions."""
    lo = tokens[i].lower()
    return _emit_features(tokens, i) + [
        "pt=" + prev,
        "pt2=" + prev2 + "|" + prev,
        "pt+w=" + prev + "|" + lo,
    ]


def _word_shape(w: str) -> str:
    """Collapsed character-class signature: "McDonald's" -> "CcCc'c"."""
    out = []
    for ch in w[:8]:
        c = (
            "C" if ch.isupper() else "c" if ch.islower()
            else "d" if ch.isdigit() else ch
        )
        if not out or out[-1] != c:
            out.append(c)
    return "".join(out)


def _emit_ner_features(tokens: Sequence[str], i: int) -> List[str]:
    """Tag-history-free window features for NER: identity + affixes +
    shape of a ±2 token window, and the same title/org-suffix/month cues
    the rule tagger keys on — learned weights decide how much to trust
    them. Shared with the CRF NER tagger (crf.py). Fixed length (19)."""
    w = tokens[i]
    lo = w.lower()
    before = tokens[i - 1] if i > 0 else "<s>"
    before2 = tokens[i - 2] if i > 1 else "<s>"
    after = tokens[i + 1] if i + 1 < len(tokens) else "</s>"
    after2 = tokens[i + 2] if i + 2 < len(tokens) else "</s>"
    return [
        "b",  # bias
        "w=" + lo,
        "sfx3=" + lo[-3:],
        "pfx2=" + lo[:2],
        "shape=" + _word_shape(w),
        "first" if i == 0 else "mid",
        "pw=" + before.lower(),
        "pshape=" + _word_shape(before),
        "p2w=" + before2.lower(),
        "nw=" + after.lower(),
        "nshape=" + _word_shape(after),
        "n2w=" + after2.lower(),
        "title" if lo.rstrip(".") in _TITLES else "notitle",
        "ptitle" if before.lower().rstrip(".") in _TITLES else "x",
        "orgsfx" if lo.rstrip(".") in _ORG_SUFFIX else "x",
        "norgsfx" if after.lower().rstrip(".") in _ORG_SUFFIX else "x",
        "month" if lo in _MONTHS else "x",
        "year" if re.fullmatch(r"(1[5-9]|20)\d\d", w) else "x",
        "num" if re.fullmatch(r"\d+([.,]\d+)*", w) else "x",
    ]


def _ner_features(
    tokens: Sequence[str], i: int, prev: str, prev2: str
) -> List[str]:
    """NER features for token ``i`` given the two previous predicted
    labels — the emission set plus label-history conjunctions."""
    lo = tokens[i].lower()
    return _emit_ner_features(tokens, i) + [
        "pt=" + prev,
        "pt2=" + prev2 + "|" + prev,
        "pt+w=" + prev + "|" + lo,
    ]


class AveragedPerceptron:
    """Multiclass perceptron with weight averaging (lazy accumulation:
    totals are updated with the timestamp delta at each weight change,
    so averaging costs O(#updates), not O(#steps * #weights))."""

    def __init__(self) -> None:
        self.weights: Dict[str, Dict[str, float]] = defaultdict(dict)
        self.classes: List[str] = []
        self._totals: Dict[Tuple[str, str], float] = defaultdict(float)
        self._stamps: Dict[Tuple[str, str], int] = defaultdict(int)
        self._step = 0

    def predict(self, feats: Sequence[str]) -> str:
        scores: Dict[str, float] = defaultdict(float)
        for f in feats:
            for tag, w in self.weights.get(f, {}).items():
                scores[tag] += w
        if not scores:
            return self.classes[0] if self.classes else "NN"
        # deterministic argmax: break score ties on tag name
        return max(self.classes, key=lambda t: (scores[t], t))

    def update(self, truth: str, guess: str, feats: Sequence[str]) -> None:
        self._step += 1
        if truth == guess:
            return
        for f in feats:
            for tag, delta in ((truth, 1.0), (guess, -1.0)):
                key = (f, tag)
                cur = self.weights[f].get(tag, 0.0)
                self._totals[key] += (self._step - self._stamps[key]) * cur
                self._stamps[key] = self._step
                self.weights[f][tag] = cur + delta

    def average(self) -> None:
        for f, tags in self.weights.items():
            for tag, w in tags.items():
                key = (f, tag)
                total = self._totals[key] + (self._step - self._stamps[key]) * w
                tags[tag] = total / max(self._step, 1)
        self._totals.clear()
        self._stamps.clear()

    def tag(self, tokens: Sequence[str], feature_fn=None) -> List[str]:
        ffn = feature_fn or _features
        prev, prev2 = "<s>", "<s>"
        out = []
        for i in range(len(tokens)):
            t = self.predict(ffn(tokens, i, prev, prev2))
            out.append(t)
            prev2, prev = prev, t
        return out


def _train_greedy(
    sentences: List[Tuple[List[str], List[str]]],
    n_iter: int,
    seed: int,
    feature_fn,
) -> AveragedPerceptron:
    """Greedy left-to-right averaged-perceptron training on predicted
    (not gold) previous tags, so train matches inference (shared by the
    POS and NER estimators — they differ only in the feature function)."""
    model = AveragedPerceptron()
    model.classes = sorted({t for _, tags in sentences for t in tags})
    rng = np.random.default_rng(seed)
    order = np.arange(len(sentences))
    for _ in range(n_iter):
        rng.shuffle(order)
        for si in order:
            tokens, gold = sentences[si]
            prev, prev2 = "<s>", "<s>"
            for i in range(len(tokens)):
                feats = feature_fn(tokens, i, prev, prev2)
                guess = model.predict(feats)
                model.update(gold[i], guess, feats)
                prev2, prev = prev, guess
    model.average()
    return model


@dataclasses.dataclass(eq=False)
class PerceptronTaggerEstimator(Estimator):
    """fit(Dataset of (tokens, tags) sentences) -> POSTagger with a
    trained averaged-perceptron annotator."""

    n_iter: int = 5
    seed: int = 0

    def fit(self, data: Dataset) -> "_TrainedTagger":
        sentences = [
            (list(toks), list(tags)) for toks, tags in data.items()
        ]
        return _TrainedTagger(
            _train_greedy(sentences, self.n_iter, self.seed, _features)
        )


@dataclasses.dataclass(eq=False)
class NEREstimator(Estimator):
    """fit(Dataset of (tokens, bio_tags) sentences) -> trained NER
    tagger — the trainable replacement for the reference's pre-trained
    Epic SemiCRF (nodes/nlp/NER.scala:20). Same averaged-perceptron
    machinery as the POS estimator with an entity feature set
    (``_ner_features``); ``rule_ner_tag`` stays the zero-data default
    annotator for ``NER()``. Tag scheme is whatever the training data
    uses (BIO recommended so entity boundaries survive round-trips)."""

    n_iter: int = 8
    seed: int = 0

    def fit(self, data: Dataset) -> "_TrainedTagger":
        sentences = [
            (list(toks), list(tags)) for toks, tags in data.items()
        ]
        return _TrainedTagger(
            _train_greedy(sentences, self.n_iter, self.seed, _ner_features),
            feature_fn=_ner_features,
        )


@dataclasses.dataclass(eq=False)
class _TrainedTagger(Transformer):
    """tokens -> (token, tag) pairs from a trained perceptron."""

    model: AveragedPerceptron
    feature_fn: Optional[object] = None  # default: POS `_features`
    vmap_batch = False

    def apply(self, tokens: Sequence[str]):
        return list(zip(tokens, self.model.tag(tokens, self.feature_fn)))

    def __call__(self, tokens: Sequence[str]) -> List[str]:
        """Usable directly as a ``POSTagger``/``NER`` ``annotator=``."""
        return self.model.tag(tokens, self.feature_fn)


_RULE_TAGS = [
    (re.compile(r"^\d+([.,]\d+)*$"), "CD"),
    (re.compile(r"^(the|a|an)$", re.I), "DT"),
    (re.compile(r"^(and|or|but|nor)$", re.I), "CC"),
    (re.compile(r"^(of|in|on|at|by|for|with|from|to|into|over|under)$",
                re.I), "IN"),
    (re.compile(r"^(i|you|he|she|it|we|they|me|him|her|us|them)$", re.I),
     "PRP"),
    (re.compile(r"^(is|are|was|were|be|been|am)$", re.I), "VBZ"),
    (re.compile(r".*ing$", re.I), "VBG"),
    (re.compile(r".*ed$", re.I), "VBD"),
    (re.compile(r".*ly$", re.I), "RB"),
    (re.compile(r".*(ous|ful|ive|able|ible|al|ic)$", re.I), "JJ"),
    (re.compile(r".*s$"), "NNS"),
]


def rule_pos_tag(tokens: Sequence[str]) -> List[str]:
    """Suffix/lexicon heuristic Penn-style tags — the zero-dependency
    default annotator (capitalized mid-sentence tokens -> NNP)."""
    out = []
    for i, w in enumerate(tokens):
        tag = None
        if i > 0 and w[:1].isupper():
            tag = "NNP"
        else:
            for pat, t in _RULE_TAGS:
                if pat.match(w):
                    tag = t
                    break
        out.append(tag or "NN")
    return out


_TITLES = {"mr", "mrs", "ms", "dr", "prof", "president", "sen", "gov"}
_ORG_SUFFIX = {"inc", "corp", "ltd", "llc", "co", "university", "institute"}
_MONTHS = {
    "january", "february", "march", "april", "may", "june", "july",
    "august", "september", "october", "november", "december",
}


def rule_ner_tag(tokens: Sequence[str]) -> List[str]:
    """Heuristic entity labels (PERSON/ORG/DATE/NUMBER/ENTITY/O): runs of
    capitalized tokens form entities; titles mark PERSON, corporate
    suffixes ORG, months/years DATE — the zero-dependency default."""
    n = len(tokens)
    labels = ["O"] * n
    i = 0
    while i < n:
        w = tokens[i]
        lo = w.lower().rstrip(".")
        if re.fullmatch(r"(1[5-9]|20)\d\d", w) or lo in _MONTHS:
            labels[i] = "DATE"
            i += 1
            continue
        if re.fullmatch(r"\d+([.,]\d+)*", w):
            labels[i] = "NUMBER"
            i += 1
            continue
        if w[:1].isupper() and (i > 0 or lo in _TITLES):
            j = i
            while j < n and tokens[j][:1].isupper():
                j += 1
            span_los = [t.lower().rstrip(".") for t in tokens[i:j]]
            kind = "ENTITY"
            if span_los[0] in _TITLES:
                kind = "PERSON"
                # a title binds across an optional "." to the name run:
                # "Dr . Smith" / "Dr. Smith Jones"
                jj = j
                if jj < n and tokens[jj] == ".":
                    jj += 1
                while jj < n and tokens[jj][:1].isupper():
                    labels[jj] = "PERSON"
                    jj += 1
                    j = jj
            elif span_los[-1] in _ORG_SUFFIX:
                kind = "ORG"
            for k in range(i, min(j, n)):
                if labels[k] == "O":
                    labels[k] = kind
            i = j
            continue
        i += 1
    return labels
