"""Linear-chain CRF sequence taggers (POS + NER) with exact inference.

Reference: nodes/nlp/POSTagger.scala:24 and NER.scala:20 wrap Epic's
pre-trained linear-chain CRF / semi-CRF models (JVM-only; no
in-environment equivalent of the trained model files exists). This
module closes the model-class gap by implementing the same family
natively — a first-order linear-chain CRF with exact forward-algorithm
likelihood and exact Viterbi decode — as a TPU-idiomatic JAX program:

- **Emissions**: each token's fixed-K hashed context features (feature
  hashing via the package's stable FNV-1a, hashing_tf.stable_hash) index
  rows of a ``(hash_dim, n_tags)`` weight matrix; the whole emission
  score matrix for a sentence is one gather + sum. No string work
  happens on device.
- **Transitions**: a dense ``(n_tags, n_tags)`` table plus learned
  start scores — tag history lives here, not in features, which is what
  lets inference be exact instead of greedy.
- **Likelihood**: the sentence NLL ``logZ − score(gold)`` runs the
  forward algorithm as a masked ``lax.scan`` over time, ``vmap``-ed over
  a padded sentence batch; gradients are exact via autodiff through the
  scan. The objective is convex (standard CRF MLE + L2), so zero init +
  Adam converges without tuning.
- **Decode**: max-plus Viterbi as a forward ``lax.scan`` carrying
  backpointers and a reverse scan reading off the argmax path.
  Sentences are bucketed to power-of-two lengths so repeat calls hit
  the jit cache.
- **Constraints**: an optional additive transition mask (−1e9 on
  forbidden transitions) participates in *both* training (the partition
  function only sums structurally-valid paths) and decode;
  ``CRFNEREstimator`` uses it to make BIO-invalid outputs
  (O → I-X, B-X → I-Y, I-X at sentence start) impossible by
  construction — the analogue of the segment-level well-formedness the
  reference's semi-CRF gets structurally.

The greedy averaged-perceptron taggers (tagging.py) remain as the
cheap-training option; these CRF estimators are the drop-in stronger
model class (identical ``annotator=`` calling convention).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.special import logsumexp

from keystone_tpu.ops.nlp.hashing_tf import stable_hash
from keystone_tpu.ops.nlp.tagging import _emit_features, _emit_ner_features
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, Transformer

_NEG = -1e9  # additive "forbidden" score; safe headroom in f32


# ---------------------------------------------------------------------------
# Exact inference on an emission matrix e: (L, T). These are the testable
# core; the estimator/transformer layers only add hashing and padding.
# ---------------------------------------------------------------------------


def log_partition(e, trans, start, mask):
    """log Z over all tag paths of the unmasked prefix. ``mask`` is
    (L,) with 1.0 on real steps; mask[0] must be 1 (no empty rows)."""
    alpha0 = start + e[0]

    def step(alpha, inp):
        e_t, m_t = inp
        nxt = logsumexp(alpha[:, None] + trans, axis=0) + e_t
        return jnp.where(m_t > 0, nxt, alpha), None

    alpha, _ = lax.scan(step, alpha0, (e[1:], mask[1:]))
    return logsumexp(alpha)


def path_score(e, trans, start, tags, mask):
    """Unnormalized log-score of one tag path under the same masking."""
    gold_e = (jnp.take_along_axis(e, tags[:, None], axis=1)[:, 0] * mask).sum()
    gold_t = (trans[tags[:-1], tags[1:]] * mask[1:]).sum()
    return gold_e + gold_t + start[tags[0]]


def viterbi(e, trans, start, length):
    """Exact argmax tag path. ``e`` may be padded past ``length``; padded
    steps carry the lattice unchanged (identity backpointers), so the
    returned (L,) path is valid on [:length] regardless of padding."""
    n_tags = e.shape[1]
    steps = jnp.arange(1, e.shape[0])
    delta0 = start + e[0]

    def fwd(delta, inp):
        e_t, t = inp
        scores = delta[:, None] + trans  # (prev, next)
        best_prev = jnp.argmax(scores, axis=0)
        nxt = jnp.max(scores, axis=0) + e_t
        live = t < length
        psi = jnp.where(live, best_prev, jnp.arange(n_tags))
        return jnp.where(live, nxt, delta), psi

    delta, psis = lax.scan(fwd, delta0, (e[1:], steps))
    last = jnp.argmax(delta)

    def back(tag, psi):
        return psi[tag], tag

    first, rest = lax.scan(back, last, psis, reverse=True)
    return jnp.concatenate([first[None], rest])


@jax.jit
def _viterbi_ids(emit, trans, start, idx, length):
    """Hashed-feature wrapper: idx (L, K) feature rows -> tag-id path."""
    e = emit[idx].sum(axis=1)
    return viterbi(e, trans, start, length)


# ---------------------------------------------------------------------------
# Feature hashing / padding
# ---------------------------------------------------------------------------


def _encode(
    tokens: Sequence[str],
    feature_fn: Callable[[Sequence[str], int], List[str]],
    hash_dim: int,
) -> np.ndarray:
    """(L, K) int32 hashed feature indices; K is fixed by feature_fn."""
    return np.asarray(
        [
            [stable_hash(f) % hash_dim for f in feature_fn(tokens, i)]
            for i in range(len(tokens))
        ],
        dtype=np.int32,
    )


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


def bio_transition_mask(
    tag_names: Sequence[str],
) -> Tuple[np.ndarray, np.ndarray]:
    """(trans_mask, start_mask) additive constraints for a BIO scheme:
    I-X may only follow B-X or I-X and may not start a sentence. Tags
    not shaped like B-/I- are unconstrained, so mixed schemes degrade
    gracefully."""
    n = len(tag_names)
    tmask = np.zeros((n, n), np.float32)
    smask = np.zeros((n,), np.float32)
    for j, tj in enumerate(tag_names):
        if tj.startswith("I-"):
            ok_prev = {"B-" + tj[2:], "I-" + tj[2:]}
            for i, ti in enumerate(tag_names):
                if ti not in ok_prev:
                    tmask[i, j] = _NEG
            smask[j] = _NEG
    return tmask, smask


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------


def _fit_crf(
    sentences: List[Tuple[List[str], List[str]]],
    feature_fn,
    hash_dim: int,
    n_epochs: int,
    lr: float,
    l2: float,
    seed: int,
    batch_size: int,
    constrain_bio: bool,
):
    import optax

    sentences = [(t, g) for t, g in sentences if len(t) > 0]
    if not sentences:
        raise ValueError("CRF fit needs at least one non-empty sentence")
    tag_names = sorted({t for _, tags in sentences for t in tags})
    tag_id = {t: i for i, t in enumerate(tag_names)}
    n_tags = len(tag_names)
    k = len(feature_fn(["x"], 0))
    lmax = max(len(t) for t, _ in sentences)
    n = len(sentences)

    idx = np.zeros((n, lmax, k), np.int32)
    tags = np.zeros((n, lmax), np.int32)
    mask = np.zeros((n, lmax), np.float32)
    for s, (toks, gold) in enumerate(sentences):
        enc = _encode(toks, feature_fn, hash_dim)
        idx[s, : len(toks)] = enc
        tags[s, : len(toks)] = [tag_id[g] for g in gold]
        mask[s, : len(toks)] = 1.0

    if constrain_bio:
        tmask, smask = bio_transition_mask(tag_names)
        # a gold path through a forbidden transition would score -1e9 and
        # swamp the f32 batch loss — reject it up front with a fixable error
        for toks, gold in sentences:
            ids = [tag_id[g] for g in gold]
            if smask[ids[0]] < 0 or any(
                tmask[a, b] < 0 for a, b in zip(ids[:-1], ids[1:])
            ):
                raise ValueError(
                    "gold tags violate the BIO constraint (e.g. I-X "
                    f"without a preceding B-X/I-X) in {toks!r} -> {gold!r}; "
                    "convert IOB1-style data to strict BIO or pass "
                    "constrain_bio=False"
                )
    else:
        tmask = np.zeros((n_tags, n_tags), np.float32)
        smask = np.zeros((n_tags,), np.float32)
    tmask_j, smask_j = jnp.asarray(tmask), jnp.asarray(smask)

    params = {
        "emit": jnp.zeros((hash_dim, n_tags), jnp.float32),
        "trans": jnp.zeros((n_tags, n_tags), jnp.float32),
        "start": jnp.zeros((n_tags,), jnp.float32),
    }
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    def batch_nll(p, idx_b, tags_b, mask_b):
        trans = p["trans"] + tmask_j
        start = p["start"] + smask_j

        def one(ix, tg, mk):
            e = p["emit"][ix].sum(axis=1)
            return log_partition(e, trans, start, mk) - path_score(
                e, trans, start, tg, mk
            )

        nll = jax.vmap(one)(idx_b, tags_b, mask_b).sum() / mask_b.sum()
        reg = l2 * (
            (p["emit"] ** 2).sum()
            + (p["trans"] ** 2).sum()
            + (p["start"] ** 2).sum()
        )
        return nll + reg

    @jax.jit
    def step(p, st, idx_b, tags_b, mask_b):
        loss, grads = jax.value_and_grad(batch_nll)(p, idx_b, tags_b, mask_b)
        updates, st = opt.update(grads, st, p)
        return optax.apply_updates(p, updates), st, loss

    rng = np.random.default_rng(seed)
    full_batch = n <= batch_size
    idx_d, tags_d, mask_d = jnp.asarray(idx), jnp.asarray(tags), jnp.asarray(mask)
    prev_loss = np.inf
    for epoch in range(n_epochs):
        if full_batch:
            params, opt_state, loss = step(
                params, opt_state, idx_d, tags_d, mask_d
            )
            epoch_loss = loss
        else:
            order = rng.permutation(n)
            # wrap the tail so every slice keeps the jitted batch shape
            order = np.concatenate(
                [order, order[: (-n) % batch_size]]
            )
            losses = []
            for lo in range(0, len(order), batch_size):
                sl = order[lo : lo + batch_size]
                params, opt_state, loss = step(
                    params, opt_state, idx_d[sl], tags_d[sl], mask_d[sl]
                )
                losses.append(loss)
            # epoch mean, not the last shuffled batch: comparable across
            # epochs, so the convergence check below is meaningful
            epoch_loss = sum(float(l) for l in losses) / len(losses)
        if epoch % 10 == 9:
            cur = float(epoch_loss)
            if abs(prev_loss - cur) < 1e-6:
                break
            prev_loss = cur

    # fold the constraints into the stored tables: decode always uses the
    # same constrained lattice it was trained with
    return _TrainedCRFTagger(
        emit=np.asarray(params["emit"]),
        trans=np.asarray(params["trans"] + tmask_j),
        start=np.asarray(params["start"] + smask_j),
        tag_names=tuple(tag_names),
        hash_dim=hash_dim,
        kind="ner" if feature_fn is _emit_ner_features else "pos",
    )


# ---------------------------------------------------------------------------
# User-facing nodes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class CRFTaggerEstimator(Estimator):
    """fit(Dataset of (tokens, tags) sentences) -> CRF POS tagger.

    The trainable replacement for the reference's pre-trained Epic CRF
    POS wrapper (nodes/nlp/POSTagger.scala:24) — same model class,
    trained in-framework. The result plugs into ``POSTagger`` as an
    ``annotator=``."""

    n_epochs: int = 200
    lr: float = 0.1
    hash_dim: int = 1 << 17
    l2: float = 1e-5
    seed: int = 0
    batch_size: int = 1024

    def fit(self, data: Dataset) -> "_TrainedCRFTagger":
        sentences = [(list(t), list(g)) for t, g in data.items()]
        return _fit_crf(
            sentences, _emit_features, self.hash_dim, self.n_epochs,
            self.lr, self.l2, self.seed, self.batch_size,
            constrain_bio=False,
        )


@dataclasses.dataclass(eq=False)
class CRFNEREstimator(Estimator):
    """fit(Dataset of (tokens, bio_tags) sentences) -> CRF NER tagger.

    The trainable replacement for the reference's Epic SemiCRF wrapper
    (nodes/nlp/NER.scala:20). With ``constrain_bio`` (default), BIO
    structural validity is enforced in the lattice itself — training
    normalizes over valid paths only and decode cannot emit an invalid
    span, mirroring the segment-level guarantee of a semi-CRF."""

    n_epochs: int = 200
    lr: float = 0.1
    hash_dim: int = 1 << 17
    l2: float = 1e-5
    seed: int = 0
    batch_size: int = 1024
    constrain_bio: bool = True

    def fit(self, data: Dataset) -> "_TrainedCRFTagger":
        sentences = [(list(t), list(g)) for t, g in data.items()]
        return _fit_crf(
            sentences, _emit_ner_features, self.hash_dim, self.n_epochs,
            self.lr, self.l2, self.seed, self.batch_size,
            constrain_bio=self.constrain_bio,
        )


@dataclasses.dataclass(eq=False)
class _TrainedCRFTagger(Transformer):
    """tokens -> (token, tag) pairs by exact Viterbi decode. Also usable
    directly as a ``POSTagger``/``NER`` ``annotator=`` via ``__call__``.
    Parameters are plain numpy so the node pickles with FittedPipeline
    save/load; constraint masks are pre-folded into trans/start."""

    emit: np.ndarray
    trans: np.ndarray
    start: np.ndarray
    tag_names: Tuple[str, ...]
    hash_dim: int
    kind: str = "pos"  # picks the feature fn; keeps pickling trivial
    vmap_batch = False

    def _feature_fn(self):
        return _emit_ner_features if self.kind == "ner" else _emit_features

    def _tables(self):
        """Device copies of the weight tables, cached on first use so a
        decode transfers K feature rows, not the full emit matrix, per
        call. Non-field state: dropped from pickles (__getstate__)."""
        cached = self.__dict__.get("_tables_cache")
        if cached is None:
            cached = (
                jnp.asarray(self.emit),
                jnp.asarray(self.trans),
                jnp.asarray(self.start),
            )
            self.__dict__["_tables_cache"] = cached
        return cached

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_tables_cache", None)
        return state

    def __call__(self, tokens: Sequence[str]) -> List[str]:
        if len(tokens) == 0:
            return []
        enc = _encode(tokens, self._feature_fn(), self.hash_dim)
        pad = _bucket(len(tokens))
        idx = np.zeros((pad, enc.shape[1]), np.int32)
        idx[: len(tokens)] = enc
        emit, trans, start = self._tables()
        path = _viterbi_ids(emit, trans, start, idx, np.int32(len(tokens)))
        return [self.tag_names[i] for i in np.asarray(path)[: len(tokens)]]

    def apply(self, tokens: Sequence[str]):
        return list(zip(tokens, self(tokens)))
