from keystone_tpu.ops.nlp.string_utils import LowerCase, Tokenizer, Trim
from keystone_tpu.ops.nlp.ngrams import (
    NGram,
    NGramsCounts,
    NGramsFeaturizer,
)
from keystone_tpu.ops.nlp.hashing_tf import (
    FusedTextHashTF,
    HashingTF,
    NGramsHashingTF,
)
from keystone_tpu.ops.nlp.external import (
    NER,
    CoreNLPFeatureExtractor,
    POSTagger,
)
from keystone_tpu.ops.nlp.tagging import (
    NEREstimator,
    PerceptronTaggerEstimator,
    rule_ner_tag,
    rule_pos_tag,
)
from keystone_tpu.ops.nlp.crf import (
    CRFNEREstimator,
    CRFTaggerEstimator,
)
from keystone_tpu.ops.nlp.word_frequency import (
    WordFrequencyEncoder,
    WordFrequencyTransformer,
)
from keystone_tpu.ops.nlp.stupid_backoff import (
    NaiveBitPackIndexer,
    NGramIndexer,
    StupidBackoffEstimator,
    StupidBackoffModel,
    initial_bigram_partition,
)

__all__ = [
    "CRFNEREstimator",
    "CRFTaggerEstimator",
    "FusedTextHashTF",
    "HashingTF",
    "LowerCase",
    "NGram",
    "NGramIndexer",
    "NGramsCounts",
    "NGramsFeaturizer",
    "NER",
    "NGramsHashingTF",
    "POSTagger",
    "NEREstimator",
    "PerceptronTaggerEstimator",
    "CoreNLPFeatureExtractor",
    "NaiveBitPackIndexer",
    "StupidBackoffEstimator",
    "StupidBackoffModel",
    "Tokenizer",
    "Trim",
    "WordFrequencyEncoder",
    "WordFrequencyTransformer",
    "initial_bigram_partition",
    "rule_ner_tag",
    "rule_pos_tag",
]
