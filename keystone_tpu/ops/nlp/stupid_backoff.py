"""Stupid Backoff n-gram language model (Brants et al. 2007).

Reference: nodes/nlp/StupidBackoff.scala:25,96,147 and indexers.scala:58,
135. Score (unnormalized):
    S(w_i | context) = freq(ngram)/freq(context)  if freq(ngram) > 0
                       alpha * S(w_i | shorter context)  otherwise
with the unigram base case freq(w)/numTokens.

The reference partitions ngrams by their first two words
(InitialBigramPartitioner) so backoff lookups stay partition-local;
``initial_bigram_partition`` reproduces that assignment for sharded
serving layouts, while the in-memory model uses one host hash map.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

from keystone_tpu.ops.nlp.hashing_tf import stable_hash
from keystone_tpu.ops.nlp.ngrams import NGram, NGramsCounts
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, Transformer


class NGramIndexer:
    """Tuple-backed backoff indexer (reference: NGramIndexerImpl,
    indexers.scala:135)."""

    min_ngram_order = 1
    max_ngram_order = 5

    def pack(self, words: Sequence) -> NGram:
        return NGram(words)

    def unpack(self, ngram: NGram, pos: int):
        return ngram[pos]

    def remove_farthest_word(self, ngram: NGram) -> NGram:
        return NGram(ngram[1:])

    def remove_current_word(self, ngram: NGram) -> NGram:
        return NGram(ngram[:-1])

    def ngram_order(self, ngram: NGram) -> int:
        return len(ngram)


class NaiveBitPackIndexer:
    """Packs up to trigrams of word ids < 2^20 into one int (reference:
    indexers.scala:58 — same layout: [4 control bits][farthest]...[curr],
    left-aligned)."""

    min_ngram_order = 1
    max_ngram_order = 3

    def pack(self, ngram: Sequence[int]) -> int:
        for w in ngram:
            if w >= 1 << 20:
                raise ValueError("word id must be < 2^20")
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[1] << 20) | (ngram[0] << 40) | (1 << 60)
        if n == 3:
            return (
                ngram[2] | (ngram[1] << 20) | (ngram[0] << 40) | (1 << 61)
            )
        raise ValueError("ngram order must be in {1, 2, 3}")

    def unpack(self, ngram: int, pos: int) -> int:
        if pos == 0:
            return (ngram >> 40) & ((1 << 20) - 1)
        if pos == 1:
            return (ngram >> 20) & ((1 << 20) - 1)
        if pos == 2:
            return ngram & ((1 << 20) - 1)
        raise ValueError("pos must be in {0, 1, 2}")

    def ngram_order(self, ngram: int) -> int:
        order = (ngram & (0xF << 60)) >> 60
        if not (self.min_ngram_order <= order + 1 <= self.max_ngram_order):
            raise ValueError(f"invalid control bits {order}")
        return order + 1

    def remove_farthest_word(self, ngram: int) -> int:
        order = self.ngram_order(ngram)
        cleared = ngram & (0xF << 60)
        stripped = ngram & ((1 << 40) - 1)
        shifted = ((stripped << 20) | cleared) & ~(0xF << 60)
        if order == 2:
            return shifted
        if order == 3:
            return shifted | (1 << 60)
        raise ValueError(f"unsupported order {order}")

    def remove_current_word(self, ngram: int) -> int:
        order = self.ngram_order(ngram)
        if order == 2:
            return (ngram & ~((1 << 40) - 1)) & ~(0xF << 60)
        if order == 3:
            return ((ngram & ~((1 << 20) - 1)) & ~(0xF << 60)) | (1 << 60)
        raise ValueError(f"unsupported order {order}")


def initial_bigram_partition(
    ngram: NGram, num_partitions: int, indexer: NGramIndexer = None
) -> int:
    """Partition by a hash of the first two (context) words (reference:
    InitialBigramPartitioner, StupidBackoff.scala:25-58)."""
    indexer = indexer or NGramIndexer()
    if indexer.ngram_order(ngram) > 1:
        h = stable_hash(
            (indexer.unpack(ngram, 0), indexer.unpack(ngram, 1))
        )
        return h % num_partitions
    return 0


@dataclasses.dataclass(eq=False)
class StupidBackoffModel(Transformer):
    ngram_counts: Dict[NGram, int]
    unigram_counts: Dict[object, int]
    num_tokens: int
    alpha: float = 0.4
    vmap_batch = False

    def __post_init__(self):
        self._indexer = NGramIndexer()

    def score(self, ngram) -> float:
        ngram = NGram(ngram)
        return self._score(1.0, ngram, self.ngram_counts.get(ngram, 0))

    def _score(self, accum: float, ngram: NGram, freq: int) -> float:
        idx = self._indexer
        order = idx.ngram_order(ngram)
        if order == 1:
            return accum * freq / self.num_tokens
        if freq != 0:
            context = idx.remove_current_word(ngram)
            if order != 2:
                context_freq = self.ngram_counts.get(context, 0)
            else:
                context_freq = self.unigram_counts.get(
                    idx.unpack(context, 0), 0
                )
            return accum * freq / context_freq
        backoffed = idx.remove_farthest_word(ngram)
        if idx.ngram_order(backoffed) != 1:
            freq2 = self.ngram_counts.get(backoffed, 0)
        else:
            freq2 = self.unigram_counts.get(idx.unpack(backoffed, 0), 0)
        return self._score(self.alpha * accum, backoffed, freq2)

    def apply(self, ngram):
        return self.score(ngram)


@dataclasses.dataclass(eq=False)
class StupidBackoffEstimator(Estimator):
    """fit(Dataset of (NGram, count) pairs) -> StupidBackoffModel
    (reference: StupidBackoffEstimator — unigram counts come in
    separately)."""

    unigram_counts: Dict[object, int]
    alpha: float = 0.4

    def fit(self, data: Dataset) -> StupidBackoffModel:
        ngram_counts = {NGram(k): v for k, v in data.items()}
        num_tokens = sum(self.unigram_counts.values())
        return StupidBackoffModel(
            ngram_counts, self.unigram_counts, num_tokens, self.alpha
        )

    def eq_key(self):
        return ("stupid_backoff", id(self.unigram_counts), self.alpha)
