"""String preprocessing transformers.

Reference: nodes/nlp/StringUtils.scala:13,20,28 — regex tokenizer, trim,
lowercase. Host-side ops over items-mode datasets.
"""

from __future__ import annotations

import dataclasses
import re

from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class Tokenizer(Transformer):
    """Split on a delimiting regex (default: punctuation + whitespace,
    matching the reference's ``[\\p{Punct}\\s]+``).

    Scala ``String.split`` semantics are reproduced exactly
    (StringUtilsSuite "tokenizer"): a string that STARTS with a
    separator yields a leading empty token (which the reference's
    downstream TF/vocab nodes then count as a term); ALL trailing empty
    tokens are removed, so a separator-only string yields ``[]``; and
    the no-match case returns the original string whole — so ``""``
    tokenizes to ``[""]``, Java's documented quirk."""

    sep: str = r"[^\w]+"
    vmap_batch = False

    def apply(self, s: str):
        parts = re.split(self.sep, s)
        if len(parts) == 1:
            return parts  # no separator matched: the whole string, as is
        while parts and parts[-1] == "":
            parts.pop()
        return parts

    def eq_key(self):
        return ("tokenizer", self.sep)


class Trim(Transformer):
    vmap_batch = False

    def apply(self, s: str) -> str:
        return s.strip()

    def eq_key(self):
        return ("trim",)


class LowerCase(Transformer):
    vmap_batch = False

    def apply(self, s: str) -> str:
        return s.lower()

    def eq_key(self):
        return ("lower_case",)
