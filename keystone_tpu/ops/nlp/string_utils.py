"""String preprocessing transformers.

Reference: nodes/nlp/StringUtils.scala:13,20,28 — regex tokenizer, trim,
lowercase. Host-side ops over items-mode datasets.
"""

from __future__ import annotations

import dataclasses
import re

from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class Tokenizer(Transformer):
    """Split on a delimiting regex (default: punctuation + whitespace,
    matching the reference's ``[\\p{Punct}\\s]+``)."""

    sep: str = r"[^\w]+"
    vmap_batch = False

    def apply(self, s: str):
        return [t for t in re.split(self.sep, s) if t]

    def eq_key(self):
        return ("tokenizer", self.sep)


class Trim(Transformer):
    vmap_batch = False

    def apply(self, s: str) -> str:
        return s.strip()

    def eq_key(self):
        return ("trim",)


class LowerCase(Transformer):
    vmap_batch = False

    def apply(self, s: str) -> str:
        return s.lower()

    def eq_key(self):
        return ("lower_case",)
