"""Wrappers for external NLP annotators: POS tagging, NER, lemmatizing
feature extraction.

Reference: nodes/nlp/POSTagger.scala:24, NER.scala:20 (Epic CRF/SemiCRF
models broadcast to executors), CoreNLPFeatureExtractor.scala:18 (sista
processors tokenize/lemmatize/NER-replace + n-grams). Those JVM model
libraries have no in-environment equivalent; these nodes accept any
callable annotator (e.g. a spaCy pipeline or a transformers
token-classification pipeline loaded from a local path) and otherwise
raise with instructions — keeping the API surface while making the
external-model dependency explicit.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence

from keystone_tpu.ops.nlp.ngrams import NGramsFeaturizer
from keystone_tpu.workflow.api import Transformer

_MISSING = (
    "{name} needs an external annotator model. Pass `annotator=` — any "
    "callable mapping a token list to per-token labels (e.g. a local "
    "spaCy or transformers token-classification pipeline)."
)


@dataclasses.dataclass(eq=False)
class POSTagger(Transformer):
    """tokens -> (token, tag) pairs via a pluggable annotator."""

    annotator: Optional[Callable[[Sequence[str]], Sequence[str]]] = None
    vmap_batch = False

    def apply(self, tokens: Sequence[str]):
        if self.annotator is None:
            raise RuntimeError(_MISSING.format(name="POSTagger"))
        tags = self.annotator(tokens)
        return list(zip(tokens, tags))


@dataclasses.dataclass(eq=False)
class NER(Transformer):
    """tokens -> per-token entity labels via a pluggable annotator."""

    annotator: Optional[Callable[[Sequence[str]], Sequence[str]]] = None
    vmap_batch = False

    def apply(self, tokens: Sequence[str]):
        if self.annotator is None:
            raise RuntimeError(_MISSING.format(name="NER"))
        return list(self.annotator(tokens))


@dataclasses.dataclass(eq=False)
class CoreNLPFeatureExtractor(Transformer):
    """text -> n-grams over normalized tokens (reference:
    CoreNLPFeatureExtractor.scala — tokenize, lemmatize, replace NER
    entities with their types, then n-grams). Without an external
    lemmatizer/NER this falls back to lowercase tokenization with a
    light rule-based normalizer, keeping the pipeline shape."""

    orders: Sequence[int] = (1, 2, 3)
    lemmatizer: Optional[Callable[[str], str]] = None
    ner: Optional[Callable[[Sequence[str]], Sequence[str]]] = None
    vmap_batch = False

    def _normalize(self, token: str) -> str:
        t = token.lower()
        if self.lemmatizer is not None:
            return self.lemmatizer(t)
        # light rule-based stemming fallback
        for suffix in ("ing", "ed", "es", "s"):
            if t.endswith(suffix) and len(t) > len(suffix) + 2:
                return t[: -len(suffix)]
        return t

    def apply(self, text: str):
        tokens = [t for t in re.split(r"[^\w]+", text) if t]
        if self.ner is not None:
            labels = self.ner(tokens)
            tokens = [
                lab if lab and lab != "O" else tok
                for tok, lab in zip(tokens, labels)
            ]
        tokens = [self._normalize(t) for t in tokens]
        return NGramsFeaturizer(self.orders).apply(tokens)
