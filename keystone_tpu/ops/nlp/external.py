"""POS tagging, NER, and lemmatizing feature extraction.

Reference: nodes/nlp/POSTagger.scala:24, NER.scala:20 (pre-trained Epic
CRF/SemiCRF models broadcast to executors), CoreNLPFeatureExtractor
.scala:18 (sista processors tokenize/lemmatize/NER-replace + n-grams).
The Epic/CoreNLP JVM model libraries have no in-environment equivalent,
so these nodes default to the framework's own annotators (ops/nlp/
tagging.py: a trainable averaged-perceptron tagger via
``PerceptronTaggerEstimator``, plus rule-based POS/NER fallbacks) and
accept any callable annotator (a spaCy pipeline, a transformers
token-classification pipeline, or a trained ``_TrainedTagger``) in the
reference's pass-a-model style.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence

from keystone_tpu.ops.nlp.ngrams import NGramsFeaturizer
from keystone_tpu.ops.nlp.tagging import rule_ner_tag, rule_pos_tag
from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class POSTagger(Transformer):
    """tokens -> (token, tag) pairs. ``annotator`` maps a token list to
    per-token tags; defaults to the rule-based tagger (train a better one
    with ``PerceptronTaggerEstimator``)."""

    annotator: Optional[Callable[[Sequence[str]], Sequence[str]]] = None
    vmap_batch = False

    def apply(self, tokens: Sequence[str]):
        tags = (self.annotator or rule_pos_tag)(tokens)
        return list(zip(tokens, tags))


@dataclasses.dataclass(eq=False)
class NER(Transformer):
    """tokens -> per-token entity labels. Defaults to the heuristic
    capitalization/gazetteer annotator (tagging.rule_ner_tag)."""

    annotator: Optional[Callable[[Sequence[str]], Sequence[str]]] = None
    vmap_batch = False

    def apply(self, tokens: Sequence[str]):
        return list((self.annotator or rule_ner_tag)(tokens))


@dataclasses.dataclass(eq=False)
class CoreNLPFeatureExtractor(Transformer):
    """text -> n-grams over normalized tokens (reference:
    CoreNLPFeatureExtractor.scala — tokenize, lemmatize, replace NER
    entities with their types, then n-grams). Defaults: rule-based NER
    replacement (tagging.rule_ner_tag) + a light rule-based stemmer;
    pass ``lemmatizer``/``ner`` to swap in external annotators, or
    ``ner=False`` to disable entity replacement."""

    orders: Sequence[int] = (1, 2, 3)
    lemmatizer: Optional[Callable[[str], str]] = None
    ner: Any = None  # None=default rule_ner_tag | False=off | callable
    vmap_batch = False

    def _normalize(self, token: str) -> str:
        t = token.lower()
        if self.lemmatizer is not None:
            return self.lemmatizer(t)
        # light rule-based stemming fallback
        for suffix in ("ing", "ed", "es", "s"):
            if t.endswith(suffix) and len(t) > len(suffix) + 2:
                return t[: -len(suffix)]
        return t

    def apply(self, text: str):
        tokens = [t for t in re.split(r"[^\w]+", text) if t]
        ner = rule_ner_tag if self.ner is None else self.ner
        if ner:
            labels = ner(tokens)
            tokens = [
                lab if lab and lab != "O" else tok
                for tok, lab in zip(tokens, labels)
            ]
        tokens = [self._normalize(t) for t in tokens]
        return NGramsFeaturizer(self.orders).apply(tokens)
