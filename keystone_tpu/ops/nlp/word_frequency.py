"""Frequency-rank word encoding.

Reference: nodes/nlp/WordFrequencyEncoder.scala:7,43 — unigram counts
sorted descending give each word its rank index; out-of-vocabulary maps
to -1.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Dict, Sequence

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, Transformer

OOV_INDEX = -1


@dataclasses.dataclass(eq=False)
class WordFrequencyTransformer(Transformer):
    word_index: Dict[str, int]
    unigram_counts: Dict[int, int]  # rank index -> count
    vmap_batch = False

    def apply(self, words: Sequence[str]):
        return [self.word_index.get(w, OOV_INDEX) for w in words]

    def eq_key(self):
        return ("word_frequency_transformer", id(self.word_index))


class WordFrequencyEncoder(Estimator):
    def fit(self, data: Dataset) -> WordFrequencyTransformer:
        counts: Counter = Counter()
        for tokens in data.items():
            counts.update(tokens)
        ordered = sorted(counts.items(), key=lambda kv: -kv[1])
        word_index = {w: i for i, (w, _) in enumerate(ordered)}
        unigrams = {i: c for i, (_, c) in enumerate(ordered)}
        return WordFrequencyTransformer(word_index, unigrams)

    def eq_key(self):
        return ("word_frequency_encoder", id(self))
