"""Hashing-trick term frequencies.

Reference: nodes/nlp/HashingTF.scala:15 (Scala ``.##`` hash mod
numFeatures -> SparseVector of counts) and NGramsHashingTF.scala:25
(rolling MurmurHash3-style n-gram hashing that avoids materializing the
ngram lists). Hashes here use a stable FNV-1a so results are reproducible
across processes (Python's builtin hash is salted).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193
_MASK = 0xFFFFFFFF


def stable_hash(term: Any) -> int:
    """FNV-1a over the utf-8 of str(term) — deterministic across runs."""
    h = _FNV_OFFSET
    for b in str(term).encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def _to_sparse(counts: dict, num_features: int) -> jsparse.BCOO:
    if counts:
        idx = np.fromiter(counts.keys(), np.int32, len(counts))
        order = np.argsort(idx)
        indices = idx[order].reshape(-1, 1)
        values = np.fromiter(
            counts.values(), np.float32, len(counts)
        )[order]
    else:
        indices = np.zeros((0, 1), np.int32)
        values = np.zeros((0,), np.float32)
    return jsparse.BCOO(
        (jnp.asarray(values), jnp.asarray(indices)), shape=(num_features,)
    )


@dataclasses.dataclass(eq=False)
class HashingTF(Transformer):
    """term sequence -> sparse count vector (reference:
    HashingTF.scala:15)."""

    num_features: int
    vmap_batch = False

    def apply(self, document: Sequence) -> jsparse.BCOO:
        counts: dict = {}
        for term in document:
            i = stable_hash(term) % self.num_features
            counts[i] = counts.get(i, 0.0) + 1.0
        return _to_sparse(counts, self.num_features)

    def apply_batch(self, ds: Dataset) -> Dataset:
        rows, cols, vals = [], [], []
        items = ds.items()
        for r, doc in enumerate(items):
            counts: dict = {}
            for term in doc:
                i = stable_hash(term) % self.num_features
                counts[i] = counts.get(i, 0.0) + 1.0
            for i, v in counts.items():
                rows.append(r)
                cols.append(i)
                vals.append(v)
        indices = np.stack(
            [np.asarray(rows, np.int32), np.asarray(cols, np.int32)], axis=1
        ) if rows else np.zeros((0, 2), np.int32)
        mat = jsparse.BCOO(
            (
                jnp.asarray(np.asarray(vals, np.float32)),
                jnp.asarray(indices),
            ),
            shape=(len(items), self.num_features),
        )
        return Dataset.from_array(mat, n=len(items))


@dataclasses.dataclass(eq=False)
class FusedTextHashTF(Transformer):
    """raw document string -> hashed n-gram TF sparse row, with the whole
    Trim -> LowerCase -> Tokenizer -> NGramsHashingTF chain fused into one
    multi-threaded pass of the native C++ runtime (native/text.cc) —
    hash-identical output, no per-token Python objects. Falls back to the
    composed Python nodes when the library is unavailable or a document
    is non-ASCII. ``binarize`` maps counts to 1 (TermFrequency(x => 1))."""

    orders: Sequence[int]
    num_features: int
    binarize: bool = False
    vmap_batch = False

    def __post_init__(self):
        self._delegate = NGramsHashingTF(self.orders, self.num_features)
        if self.num_features <= 0:
            raise ValueError(
                f"num_features must be positive, got {self.num_features}"
            )
        self._lo = self._delegate._lo
        self._hi = self._delegate._hi

    def _python_fallback(self, docs) -> Dataset:
        from keystone_tpu.ops.nlp.string_utils import (
            LowerCase, Tokenizer, Trim,
        )

        tok, lc, tr = Tokenizer(), LowerCase(), Trim()
        token_ds = Dataset.from_items(
            [tok.apply(lc.apply(tr.apply(d))) for d in docs]
        )
        out = self._delegate.apply_batch(token_ds)
        if self.binarize:
            mat = out.padded()
            out = Dataset.from_array(
                jsparse.BCOO(
                    (jnp.minimum(mat.data, 1.0), mat.indices),
                    shape=mat.shape,
                ),
                n=out.n,
            )
        return out

    def apply(self, doc: str) -> jsparse.BCOO:
        mat = self.apply_batch(Dataset.from_items([doc])).padded()
        idx = np.asarray(mat.indices)
        return jsparse.BCOO(
            (jnp.asarray(mat.data), jnp.asarray(idx[:, 1:2])),
            shape=(self.num_features,),
        )

    def apply_batch(self, ds: Dataset) -> Dataset:
        from keystone_tpu import native

        items = ds.items()
        out = native.text_ngram_hash_tf(
            items, self._lo, self._hi, self.num_features, self.binarize
        )
        if out is None:
            return self._python_fallback(items)
        row_ptr, cols, values = out
        rows = np.repeat(
            np.arange(len(items), dtype=np.int32), np.diff(row_ptr)
        )
        indices = np.stack([rows, cols], axis=1)
        mat = jsparse.BCOO(
            (jnp.asarray(values), jnp.asarray(indices)),
            shape=(len(items), self.num_features),
        )
        return Dataset.from_array(mat, n=len(items))


@dataclasses.dataclass(eq=False)
class NGramsHashingTF(Transformer):
    """Rolling-hash n-gram TF: hashes every ngram of the given consecutive
    orders without materializing them (reference:
    NGramsHashingTF.scala:25)."""

    orders: Sequence[int]
    num_features: int
    vmap_batch = False

    def __post_init__(self):
        orders = list(self.orders)
        for a, b in zip(orders, orders[1:]):
            if b != a + 1:
                raise ValueError(f"orders are not consecutive: {orders}")
        self._lo = min(orders)
        self._hi = max(orders)

    def apply(self, tokens: Sequence) -> jsparse.BCOO:
        counts: dict = {}
        n = len(tokens)
        token_hashes = [stable_hash(t) for t in tokens]
        for i in range(n):
            h = _FNV_OFFSET
            for order in range(1, self._hi + 1):
                if i + order > n:
                    break
                # roll the ngram hash forward one token
                h = ((h ^ token_hashes[i + order - 1]) * _FNV_PRIME) & _MASK
                if order >= self._lo:
                    counts[h % self.num_features] = (
                        counts.get(h % self.num_features, 0.0) + 1.0
                    )
        return _to_sparse(counts, self.num_features)

    def apply_batch(self, ds: Dataset) -> Dataset:
        rows, cols, vals = [], [], []
        items = ds.items()
        for r, doc in enumerate(items):
            vec = self.apply(doc)
            idx = np.asarray(vec.indices).reshape(-1)
            v = np.asarray(vec.data)
            rows.extend([r] * len(idx))
            cols.extend(idx.tolist())
            vals.extend(v.tolist())
        indices = np.stack(
            [np.asarray(rows, np.int32), np.asarray(cols, np.int32)], axis=1
        ) if rows else np.zeros((0, 2), np.int32)
        mat = jsparse.BCOO(
            (
                jnp.asarray(np.asarray(vals, np.float32)),
                jnp.asarray(indices),
            ),
            shape=(len(items), self.num_features),
        )
        return Dataset.from_array(mat, n=len(items))
