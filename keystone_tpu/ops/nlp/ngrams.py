"""N-gram extraction and counting.

Reference: nodes/nlp/ngrams.scala — NGramsFeaturizer (consecutive orders,
:20), NGram (hashable token-sequence key, :100), NGramsCounts
(partition-local JHashMap counting + reduceByKey + descending sort, :152).
The host-side Counter here is the shuffle-free equivalent.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, List, Sequence, Tuple

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import FunctionNode, Transformer


class NGram(tuple):
    """Hashable n-gram key (reference: ngrams.scala:100 — a thin wrapper
    with sane equals/hashCode; a tuple already has both)."""

    @property
    def words(self) -> Tuple:
        return tuple(self)

    def __repr__(self) -> str:
        return f"[{','.join(str(w) for w in self)}]"


@dataclasses.dataclass(eq=False)
class NGramsFeaturizer(Transformer):
    """token sequence -> all ngrams of the given consecutive orders
    (reference: ngrams.scala:20-95; same emission order: for each start
    position, min order first then extensions)."""

    orders: Sequence[int]
    vmap_batch = False

    def __post_init__(self):
        orders = list(self.orders)
        if min(orders) < 1:
            raise ValueError(f"minimum order is not >= 1: {min(orders)}")
        for a, b in zip(orders, orders[1:]):
            if b != a + 1:
                raise ValueError(f"orders are not consecutive: {orders}")

    def apply(self, tokens: Sequence) -> List[List]:
        lo = min(self.orders)
        hi = max(self.orders)
        toks = list(tokens)  # one copy; list slices below are fresh lists
        out: List[List] = []
        append = out.append
        n = len(toks)
        for i in range(n - lo + 1):
            top = i + min(hi, n - i)
            for j in range(i + lo, top + 1):
                append(toks[i:j])
        return out

    def eq_key(self):
        return ("ngrams_featurizer", tuple(self.orders))


class NGramsCounts(FunctionNode):
    """Dataset of per-line ngram lists -> (NGram, count) pairs sorted by
    descending frequency (reference: ngrams.scala:152 — mode `default`
    aggregates + sorts; `noAdd` keeps per-line partial counts)."""

    def __init__(self, mode: str = "default"):
        if mode not in ("default", "noAdd"):
            raise ValueError("`mode` must be `default` or `noAdd`")
        self.mode = mode

    def apply(self, data) -> Dataset:
        ds = Dataset.of(data)
        counts: Counter = Counter()
        for line in ds.items():
            for gram in line:
                counts[NGram(gram)] += 1
        if self.mode == "default":
            items = sorted(counts.items(), key=lambda kv: -kv[1])
        else:
            items = list(counts.items())
        return Dataset.from_items(items)
