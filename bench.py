"""Benchmarks for the five BASELINE.md tracked configs, on the live TPU.

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": x | null}
vs_baseline > 1 means faster than the reference 16-node r3.4xlarge Spark
cluster; null where the reference published no number for the config
(BASELINE.md: only the TIMIT/Amazon solver rows have published times).
Solver rows additionally carry "tflops" (achieved TFLOP/s from the
analytic FLOP count of the measured program) so MFU is tracked per
round (v5e peak is ~197 bf16 TFLOP/s).

Tracked configs (BASELINE.md "Tracked configs"):
  - TimitPipeline      -> timit_block_ls_1024_solve(+_amortized)
  - MnistRandomFFT     -> mnist_random_fft_featurize_solve
  - RandomPatchCifar   -> random_patch_cifar_featurize imgs/sec (the
    app's real whitened-filter path) + solve
  - NewsgroupsPipeline -> newsgroups_train
  - ImageNetSiftLcsFV  -> imagenet_sift_lcs_fv examples/sec/chip
    (featurize-only north star) + imagenet_sift_lcs_fv_end_to_end
    (featurize -> weighted BCD fit -> top-5: the BASELINE.json metric)
  - flagship solvers   -> weighted_block_ls_4096_solve, krr_block_solve

Timing discipline: np.asarray(...) forces real execution —
block_until_ready alone does not drain the remote dispatch stream on
tunneled devices, and any host sync costs ~100 ms of round-trip latency,
so each metric queues its whole computation and syncs once (the
*_amortized metric additionally amortizes that fixed sync cost away).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

TIMIT_BASELINE_MS = 33_521.0  # scripts/solver-comparisons-final.csv:14
AMAZON_EXACT_BASELINE_MS = 186_149.0  # …csv:2 (Exact, 1024 features)
AMAZON_BEST_BASELINE_MS = 33_704.0  # …csv:4 (LS-LBFGS, their fastest)


_EMITTED = set()
_ROWS = []  # every emitted row, for the --markdown table


def emit(metric: str, value: float, unit: str, vs=None, tflops=None,
         extra=None) -> None:
    if metric in _EMITTED:  # a retried bench re-measures what an earlier
        return  # attempt already emitted; duplicate rows would corrupt
        # the driver's one-row-per-metric BENCH_r{N}.json
    _EMITTED.add(metric)
    row = {
        "metric": metric,
        "value": round(value, 2) if value is not None else None,
        "unit": unit,
        "vs_baseline": round(vs, 2) if vs else None,
    }
    if tflops is not None:
        row["tflops"] = round(tflops, 2)
    if extra:
        row.update(extra)
    _ROWS.append(row)
    print(json.dumps(row), flush=True)


def measure(run_once, reps: int = 3):
    """Best-of-``reps`` + spread for a single-sync measured callable
    (VERDICT r3 weak #8: single-shot rows are dominated by ~100 ms of
    tunnel round-trip jitter; best-of-k with the spread reported makes
    round-over-round deltas attributable). Returns (best_ms, extra)."""
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_once()
        times.append((time.perf_counter() - t0) * 1e3)
    return min(times), {
        "spread_ms": round(max(times) - min(times), 2),
        "reps": reps,
    }


_RT_MS = None


def tunnel_rt_ms() -> float:
    """Measured host↔device round-trip latency (best of 7 syncs of an
    already-materialized scalar). Every single-sync row's wall time is
    ``device + RT``; rows carry ``device_ms = wall − RT`` so the
    program's own cost is TRACKED, not argued in PROFILE notes
    (VERDICT r4 weak #2/#3). Measured once per bench process and
    emitted as its own row."""
    global _RT_MS
    if _RT_MS is None:
        np.asarray(jnp.zeros(()))  # warm the trivial program
        times = []
        for i in range(7):
            # a FRESH tiny computation per rep: re-reading an
            # already-materialized array is served from the host-side
            # buffer cache and measures ~0
            x = jnp.full((), float(i))
            t0 = time.perf_counter()
            np.asarray(x)
            times.append((time.perf_counter() - t0) * 1e3)
        _RT_MS = min(times)
        emit("tunnel_roundtrip", _RT_MS, "ms",
             extra={"spread_ms": round(max(times) - min(times), 2)})
    return _RT_MS


def solver_extras(best_ms: float, flop: float, extra: dict) -> dict:
    """Attach the RT-corrected device-side time and TFLOP/s to a solver
    row (the environment tax and the program were previously conflated
    in the tracked number)."""
    rt = tunnel_rt_ms()
    device_ms = max(best_ms - rt, 1e-3)
    extra = dict(extra)
    extra.update(
        device_ms=round(device_ms, 2),
        tflops_device=round(flop / device_ms / 1e9, 2),
        rt_ms=round(rt, 1),
    )
    return extra


def bench_timit() -> None:
    """BlockLS solve on the TIMIT shape: 2.25M frames x 1024 features,
    147 classes, one BCD pass (reference row: 33,521 ms on the cluster)."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K, BLOCK = 2_251_569, 1024, 147, 1024
    mesh = mesh_lib.make_mesh()
    with mesh_lib.use_mesh(mesh):
        nshards = mesh_lib.n_data_shards(mesh)
        n = -(-N // nshards) * nshards

        @jax.jit
        def gen(key):
            kx, kw = jax.random.split(key)
            mask = (jnp.arange(n) < N).astype(jnp.bfloat16)[:, None]
            X = jax.random.normal(kx, (n, D), jnp.bfloat16) * mask
            W = jax.random.normal(kw, (D, K), jnp.bfloat16) * 0.1
            Y = jax.lax.dot_general(
                X, W, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return X, Y

        X, Y = gen(jax.random.PRNGKey(0))
        X = jax.device_put(X, mesh_lib.data_sharding(mesh))
        Y = jax.device_put(Y, mesh_lib.data_sharding(mesh))
        np.asarray(X[:1, :1])
        Xd = Dataset.from_array(X, n=N)
        Yd = Dataset.from_array(Y, n=N)

        # FLOPs of the measured program (num_iter=1, one 1024 block):
        # first_pass skips the zero-model contrib matmul and last_pass
        # skips the dead residual update, leaving gram (2·N·D²) +
        # rhs (2·N·D·K).
        flop = 2 * N * D * D + 2 * N * D * K

        est = BlockLeastSquaresEstimator(block_size=BLOCK, num_iter=1, lam=0.1)
        np.asarray(est.fit(Xd, Yd).W)  # warm compile + force exec
        single_ms, extra = measure(
            lambda: np.asarray(est.fit(Xd, Yd).W), reps=3
        )

        reps = 8
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            last = est.fit(Xd, Yd)
        np.asarray(last.W)
        amortized_ms = (time.perf_counter() - t0) * 1e3 / reps

    emit("timit_block_ls_1024_solve", single_ms, "ms",
         TIMIT_BASELINE_MS / single_ms, tflops=flop / single_ms / 1e9,
         extra=solver_extras(single_ms, flop, extra))
    emit("timit_block_ls_1024_solve_amortized", amortized_ms, "ms",
         TIMIT_BASELINE_MS / amortized_ms,
         tflops=flop / amortized_ms / 1e9, extra={"reps": reps})


TIMIT_LBFGS_BASELINE_MS = 70_396.0  # …csv:15 (LS-LBFGS, 1024 features)


def bench_timit_lbfgs() -> None:
    """Fused device L-BFGS at the TIMIT shape (2.25M x 1024, 147
    classes, 20 iterations — reference row: 70,396 ms on the cluster,
    scripts/solver-comparisons-final.csv:15). The whole optimization
    (two-loop recursion + Armijo line search) runs as ONE device
    program (ops/learning/lbfgs.py run_lbfgs_device)."""
    from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K = 2_251_569, 1024, 147

    @jax.jit
    def gen(key):
        kx, kw = jax.random.split(key)
        X = jax.random.normal(kx, (N, D), jnp.bfloat16)
        W = jax.random.normal(kw, (D, K), jnp.bfloat16) * 0.1
        Y = jax.lax.dot_general(
            X, W, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return X, Y

    X, Y = gen(jax.random.PRNGKey(0))
    Xd = Dataset.from_array(X, n=N)
    Yd = Dataset.from_array(Y, n=N)
    est = DenseLBFGSwithL2(
        num_iterations=20, reg_param=1e-4, fit_intercept=False
    )

    # LOWER bound: one value+grad per iteration (forward 2NDK + backward
    # 2NDK); Armijo re-evaluations on top are data-dependent
    flop = est.num_iterations * 4 * N * D * K

    np.asarray(est.fit(Xd, Yd).W[:1, :1])  # warm
    ms, extra = measure(
        lambda: np.asarray(est.fit(Xd, Yd).W[:1, :1]), reps=3
    )
    emit("timit_lbfgs_1024_solve", ms, "ms",
         TIMIT_LBFGS_BASELINE_MS / ms, tflops=flop / ms / 1e9,
         extra=solver_extras(ms, flop, extra))


def bench_amazon() -> None:
    """Amazon reviews solver row at the reference experiment's shape:
    65M examples x 1024 hashed-TF features, ~0.5% dense (nnz=5/row),
    binary labels (scripts/constantEstimator.R:34-36). The ELL one-pass
    normal-equations solver (ops/learning/sparse_ell.py) replaces BOTH
    reference solvers for this least-squares workload, so one measured
    fit compares against the Exact row (186,149 ms) and against their
    fastest solver, LS-LBFGS (33,704 ms)."""
    from keystone_tpu.ops.learning import (
        EllLeastSquaresEstimator, ell_dataset,
    )
    from keystone_tpu.parallel.dataset import Dataset

    N, D, NNZ, K = 65_000_000, 1024, 5, 2

    @jax.jit
    def gen(key):
        ki, kv, kb = jax.random.split(key, 3)
        return (
            jax.random.randint(ki, (N, NNZ), 0, D, jnp.int32),
            jax.random.normal(kv, (N, NNZ), jnp.bfloat16),
            jax.random.normal(kb, (N, K), jnp.bfloat16),
        )

    idx, vals, Y = gen(jax.random.PRNGKey(0))
    ds = ell_dataset(idx, vals)
    labels = Dataset.from_array(Y)
    est = EllLeastSquaresEstimator(d=D, lam=1e-2)

    # tile-densified Gram + AᵀY over the dense (chunk, d) tiles: the
    # solver really performs the dense-equivalent matmuls on the MXU
    flop = 2 * N * D * (D + K)

    np.asarray(est.fit(ds, labels).W[0, 0])  # warm
    ms, extra = measure(
        lambda: np.asarray(est.fit(ds, labels).W[0, 0]), reps=3
    )
    extra = solver_extras(ms, flop, extra)
    emit("amazon_ls_1024_solve", ms, "ms", AMAZON_BEST_BASELINE_MS / ms,
         tflops=flop / ms / 1e9, extra=extra)
    emit("amazon_exact_1024_solve", ms, "ms",
         AMAZON_EXACT_BASELINE_MS / ms, tflops=flop / ms / 1e9,
         extra=extra)


AMAZON_BLOCK_16384_BASELINE_MS = 13_631_976.0  # …csv:11 (Block, 16384)
AMAZON_LBFGS_16384_BASELINE_MS = 52_290.0  # …csv:12 (LS-LBFGS, 16384)


def bench_amazon_16384(n: int = 65_000_000) -> None:
    """Amazon reviews at the reference's HEADLINE config — 16384 hashed
    features (scripts/solver-comparisons-final.csv:11-12: Block
    13,631,976 ms, LS-LBFGS 52,290 ms, both reaching 11.4% train
    error). One ELL normal-equations pass + (16384,16384) solve: the
    exact solution (Block-quality) in one data pass. The Gram is
    2·N·D² ≈ 3.5e16 dense-equivalent FLOPs — a many-minute
    single-chip program, so the row is OPT-IN (``--amazon-16384``),
    timed as ONE fit (reps=1; the scan program is length-dependent, so
    there is no cheap warm pass), run once per round and recorded in
    PERF. Two emits mirror the 1024-feature rows:
    vs the solver with matching solution quality (Block) and vs the
    reference's fastest solver at this width (LS-LBFGS)."""
    from keystone_tpu.ops.learning import (
        EllLeastSquaresEstimator, ell_dataset,
    )
    from keystone_tpu.parallel.dataset import Dataset

    D, NNZ, K = 16_384, 5, 2
    # dense (chunk, 16384) bf16 tile = 512 MB; the 1M default would be
    # a 32 GB tile
    CHUNK = 16_384

    @jax.jit
    def gen(key):
        ki, kv, kb = jax.random.split(key, 3)
        return (
            jax.random.randint(ki, (n, NNZ), 0, D, jnp.int32),
            jax.random.normal(kv, (n, NNZ), jnp.bfloat16),
            jax.random.normal(kb, (n, K), jnp.bfloat16),
        )

    idx, vals, Y = gen(jax.random.PRNGKey(0))
    ds = ell_dataset(idx, vals)
    labels = Dataset.from_array(Y)
    est = EllLeastSquaresEstimator(d=D, lam=1e-2, chunk=CHUNK)

    flop = 2 * n * D * (D + K)
    t0 = time.perf_counter()
    W = est.fit(ds, labels).W
    np.asarray(W[0, 0])
    ms = (time.perf_counter() - t0) * 1e3
    assert bool(np.isfinite(np.asarray(W).sum())), "non-finite W"
    extra = solver_extras(ms, flop, {"reps": 1, "n": n})
    emit("amazon_exact_16384_solve", ms, "ms",
         AMAZON_BLOCK_16384_BASELINE_MS / ms, tflops=flop / ms / 1e9,
         extra=extra)
    emit("amazon_ls_16384_solve", ms, "ms",
         AMAZON_LBFGS_16384_BASELINE_MS / ms, tflops=flop / ms / 1e9,
         extra=extra)


def bench_mnist() -> None:
    """MnistRandomFFT at MNIST scale (60k x 784, 24 FFT branches -> 24,576
    features) — featurize + one-pass BlockLS, end to end."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.stats import RandomFFTFeatures
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset

    N, D, NUM_FFTS, K = 60_000, 784, 24, 10
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((N, D)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    labels = ClassLabelIndicators(K).apply_batch(Dataset.from_array(y))
    fft_bank = RandomFFTFeatures.create(D, NUM_FFTS, seed=0)

    def featurize(ds):
        out = fft_bank.apply_batch(ds)
        return Dataset.from_array(
            out.padded().astype(jnp.bfloat16), n=ds.n
        )

    est = BlockLeastSquaresEstimator(block_size=4096, num_iter=1, lam=0.1)

    def run_once():
        feats = featurize(Dataset.from_array(X))
        model = est.fit(feats, labels)
        np.asarray(model.W)

    run_once()  # warm
    ms, extra = measure(run_once, reps=3)
    emit("mnist_random_fft_featurize_solve", ms, "ms", extra=extra)


def bench_cifar() -> None:
    """RandomPatchCifar at the app's REAL featurization path — whitened
    random-patch filter bank (Windower patches -> normalize -> ZCA ->
    filters, pipelines/images/random_patch_cifar.py build_filters, ref
    RandomPatchCifar.scala:45-57), then conv + rectify + pool over the
    CIFAR train set with the whole chunk loop inside ONE jitted
    lax.map program (no per-chunk Python dispatch or host concat), and
    the 4096-feature BlockLS solve."""
    from keystone_tpu.ops.images import (
        Convolver, Pooler, SymmetricRectifier,
    )
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset
    from keystone_tpu.pipelines.images.random_patch_cifar import (
        RandomCifarConfig, build_filters, synthetic_cifar,
    )

    N, SIZE, F = 10_000, 32, 512
    conf = RandomCifarConfig(num_filters=F)
    train, _ = synthetic_cifar(n_train=2_000)
    filters, whitener = build_filters(train.images, conf)

    conv = Convolver(
        filters, SIZE, SIZE, 3, whitener=whitener, normalize_patches=True
    )
    rect = SymmetricRectifier(alpha=conf.alpha)
    pool = Pooler(conf.pool_stride, conf.pool_size)

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        rng.standard_normal((N, SIZE, SIZE, 3)).astype(np.float32) * 20
        + 128
    )
    CHUNK = 500  # conv intermediate is (CHUNK, 27, 27, 2F) — HBM-bounded

    @jax.jit
    def featurize(imgs_chunked):
        def one(chunk):
            z = conv._convolve.__wrapped__(conv, chunk)
            z = rect.apply(z)
            z = pool._pool.__wrapped__(pool, z)
            return jnp.transpose(z, (0, 2, 1, 3)).reshape(z.shape[0], -1)
        return jax.lax.map(one, imgs_chunked)

    chunked = imgs.reshape(N // CHUNK, CHUNK, SIZE, SIZE, 3)
    out = featurize(chunked)  # warm
    np.asarray(out[:1, :1, :1])
    state = {}

    def run_once():
        state["out"] = featurize(chunked)
        np.asarray(state["out"][:1, :1, :1])

    ms, extra = measure(run_once, reps=3)
    out = state["out"]
    emit("random_patch_cifar_featurize", N / (ms / 1e3), "imgs/sec",
         extra=extra)

    feats = Dataset.from_array(
        out.reshape(N, -1).astype(jnp.bfloat16), n=N
    )
    y = jnp.asarray(rng.integers(0, 10, N).astype(np.int32))
    labels = ClassLabelIndicators(10).apply_batch(Dataset.from_array(y))
    est = BlockLeastSquaresEstimator(block_size=4096, num_iter=1, lam=10.0)
    np.asarray(est.fit(feats, labels).W)  # warm
    ms, extra = measure(
        lambda: np.asarray(est.fit(feats, labels).W), reps=3
    )
    emit("random_patch_cifar_solve", ms, "ms", extra=extra)


def bench_newsgroups() -> None:
    """NewsgroupsPipeline train path on synthetic 20-class docs:
    tokenize -> 1..2-grams -> TF -> CommonSparseFeatures(10k) ->
    NaiveBayes (host featurization + device solve)."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.pipelines.text.newsgroups import (
        NewsgroupsConfig, build_pipeline,
    )
    from keystone_tpu.parallel.dataset import Dataset

    rng = np.random.default_rng(0)
    vocab = [f"w{i:04d}" for i in range(2000)]
    docs, ys = [], []
    for i in range(2000):
        c = i % 20
        words = rng.choice(vocab[c * 80: c * 80 + 200], size=60)
        docs.append(" ".join(words))
        ys.append(c)
    train = LabeledData(
        data=Dataset.from_items(docs),
        labels=Dataset.from_array(jnp.asarray(np.asarray(ys, np.int32))),
    )
    conf = NewsgroupsConfig(n_grams=2, common_features=10_000)

    def run_once():
        pipe = build_pipeline(train, conf)
        preds = pipe.apply(train.data).get()
        np.asarray(preds.padded()[:1])

    run_once()  # warm
    ms, extra = measure(run_once, reps=3)
    emit("newsgroups_train", ms, "ms", extra=extra)


def bench_weighted_ls() -> None:
    """The flagship's ACTUAL solver: BlockWeightedLeastSquaresEstimator
    (mixture-weighted BCD) at the ImageNetSiftLcsFV training shape per
    chip — FV-dim features (2 branches x 2·descDim·vocabSize = 8192),
    block size 4096 (ImageNetSiftLcsFV.scala:139-142), 128 classes,
    262k examples (the reference published no time for this solver ->
    vs_baseline null; this row exists so the flagship's own solver has
    a measured number, VERDICT r2 missing #3)."""
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset

    N, D, C, BLOCK = 262_144, 8192, 128, 4096

    @jax.jit
    def gen(key):
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (N, D), jnp.bfloat16)
        y = jax.random.randint(ky, (N,), 0, C, jnp.int32)
        return X, y

    X, y = gen(jax.random.PRNGKey(0))
    Xd = Dataset.from_array(X, n=N)
    labels = ClassLabelIndicators(C).apply_batch(Dataset.from_array(y))

    est = BlockWeightedLeastSquaresEstimator(
        block_size=BLOCK, num_iter=1, lam=1e-3, mixture_weight=0.5,
        convergence_check="off",  # the check syncs inside fit; the bench
        # reads + asserts the same diagnostics AFTER the timed region
    )
    np.asarray(est.fit(Xd, labels).W[:1, :1])  # warm
    state = {}

    def run_once():
        state["model"] = est.fit(Xd, labels)
        np.asarray(state["model"].W[:1, :1])

    ms, extra = measure(run_once)
    model = state["model"]
    pcg_rel = float(model.solver_info["pcg_max_rel_residual"])
    pcg_iters = int(model.solver_info["pcg_iterations"])
    assert pcg_rel < 1e-5, f"under-converged PCG in bench: {pcg_rel}"
    extra.update(pcg_max_rel_residual=pcg_rel, pcg_iterations=pcg_iters)

    # FLOPs of the measured (auto->PCG) path — a LOWER bound counting
    # only its guaranteed dense passes: pop cov 2·N·b² + residual delta
    # 2·N·b·C per block. The CG matvecs/preconditioner solves on top are
    # iteration-count-dependent and excluded, so true utilization is
    # somewhat higher than the emitted tflops.
    nb = D // BLOCK
    flop = nb * (2 * N * BLOCK**2 + 2 * N * BLOCK * C)
    emit("weighted_block_ls_4096_solve", ms, "ms", tflops=flop / ms / 1e9,
         extra=solver_extras(ms, flop, extra))


def bench_krr() -> None:
    """KernelRidgeRegression block Gauss-Seidel solve at the
    RandomPatchCifarKernel shape: 48k train rows, 1024-dim features,
    RBF kernel, 4096-row blocks, 10 classes, one epoch
    (KernelRidgeRegression.scala:86-235; no published reference time ->
    vs_baseline null)."""
    from keystone_tpu.ops.learning.kernel import (
        GaussianKernelGenerator, KernelRidgeRegression,
    )
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K, BLOCK = 49_152, 1024, 10, 4096

    @jax.jit
    def gen(key):
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (N, D), jnp.float32)
        y = jax.random.randint(ky, (N,), 0, K, jnp.int32)
        return X, y

    X, y = gen(jax.random.PRNGKey(0))
    Xd = Dataset.from_array(X, n=N)
    labels = ClassLabelIndicators(K).apply_batch(Dataset.from_array(y))

    est = KernelRidgeRegression(
        kernel_generator=GaussianKernelGenerator(gamma=1e-3),
        lam=1e-2, block_size=BLOCK, num_epochs=1,
    )
    np.asarray(est.fit(Xd, labels).model[:1, :1])  # warm

    def run_once():
        np.asarray(est.fit(Xd, labels).model[:1, :1])

    ms, extra = measure(run_once)

    # per block: RBF block gen 2·N·b·D + residual K_colᵀW 2·N·b·K +
    # (b,b) Cholesky b³/3
    nb = N // BLOCK
    flop = nb * (2 * N * BLOCK * D + 2 * N * BLOCK * K + BLOCK**3 // 3)
    emit("krr_block_solve", ms, "ms", tflops=flop / ms / 1e9,
         extra=solver_extras(ms, flop, extra))

    # cached-kernel mode at 3 epochs (the reference's cacheKernel,
    # KernelMatrix.scala:50): K(:, B) built once + one batched diagonal
    # Cholesky bank, so epochs 2+ cost only residual + triangular
    # solves (~40 ms/epoch device vs ~142 regenerating). Flops credited
    # honestly for the cached schedule: one kernel gen, one chol bank,
    # E× (residual + 2 tri-solve pairs).
    EPOCHS = 3
    est_c = KernelRidgeRegression(
        kernel_generator=GaussianKernelGenerator(gamma=1e-3),
        lam=1e-2, block_size=BLOCK, num_epochs=EPOCHS, cache_kernel=True,
    )
    np.asarray(est_c.fit(Xd, labels).model[:1, :1])  # warm

    def run_cached():
        np.asarray(est_c.fit(Xd, labels).model[:1, :1])

    ms_c, extra_c = measure(run_cached)
    flop_c = nb * (2 * N * BLOCK * D + BLOCK**3 // 3) + EPOCHS * nb * (
        2 * N * BLOCK * K + 4 * BLOCK * BLOCK * K
    )
    extra_c = solver_extras(ms_c, flop_c, extra_c)
    extra_c["epochs"] = EPOCHS
    emit("krr_cached_3epoch_solve", ms_c, "ms", tflops=flop_c / ms_c / 1e9,
         extra=extra_c)


def _fixture_images(n: int, size: int, return_n_base: bool = False):
    """Real ImageNet fixture images (the reference's test tar), resized
    to ``size``² and tiled to ``n`` — SIFT work is data-dependent
    (contrast-threshold zeroing, gradient statistics), so benching on
    uniform noise mismeasures it (VERDICT r2 weak #7). Falls back to
    textured synthetic images if the fixture tar is unavailable."""
    tar = "/root/reference/src/test/resources/images/imagenet/n15075141.tar"
    labels = "/root/reference/src/test/resources/images/imagenet-test-labels"
    base = []
    try:
        from keystone_tpu.loaders.image_loaders import ImageNetLoader

        for item in ImageNetLoader(tar, labels).items():
            img = jnp.asarray(np.asarray(item.image, np.float32))
            base.append(np.asarray(jax.image.resize(
                img, (size, size, 3), method="bilinear"
            )))
    except Exception as e:
        import sys
        print(f"fixture images unavailable ({e}); falling back to "
              "synthetic textures — imagenet rows are NOT comparable "
              "to fixture-image rounds", file=sys.stderr, flush=True)
    if not base:
        rng = np.random.default_rng(0)
        x, y = np.meshgrid(np.arange(size), np.arange(size))
        for freq in (3.0, 5.0, 9.0, 17.0):
            img = 128 + 90 * np.sin(x / freq) * np.cos(y / freq)
            base.append(
                np.repeat(img[:, :, None], 3, 2).astype(np.float32)
                + rng.normal(0, 8, (size, size, 3))
            )
    reps = -(-n // len(base))
    out = np.stack((base * reps)[:n]).astype(np.float32)
    return (out, len(base)) if return_n_base else out


def _build_fv_pipeline(rng, desc_dim, vocab):
    """The ImageNetSiftLcsFV featurization pipeline (shared by the
    featurize-only and end-to-end benches) — the same warm-start chain
    the serving gateway's flagship mode builds, so fit and serve
    measure ONE featurize implementation."""
    from keystone_tpu.serving.featurize import flagship_pipeline

    return flagship_pipeline(rng, desc_dim, vocab)


def bench_imagenet_fv() -> None:
    """North star (featurize): ImageNetSiftLcsFV featurization
    examples/sec/chip — dense multi-scale SIFT + LCS, PCA to 64 dims,
    16-component GMM Fisher Vectors, Hellinger + L2 normalization, at
    256x256 ImageNet-like resolution (reference pipeline:
    ImageNetSiftLcsFV.scala:106-138)."""
    from keystone_tpu.parallel.dataset import Dataset

    SIZE, N = 256, 512
    CHUNK = 128  # bounds the (chunk, 128, ~13k) descriptor intermediates;
    # the chunk loop keeps the dispatch stream pipelined so the ~100 ms
    # tunnel sync amortizes over all N examples (throughput, not
    # latency). Measured against CHUNK=256 on v5e: 872 vs 749 ex/s —
    # the doubled intermediates cost more in HBM pressure than the
    # halved dispatch count saves
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(_fixture_images(N, SIZE))
    # the deployment path: freeze the (estimator-free) pipeline and
    # lower the whole featurize graph into ONE compiled program per
    # chunk shape (FittedPipeline.jit_batch) instead of ~15 per-node
    # dispatches through the graph executor per chunk
    featurize = _build_fv_pipeline(rng, 64, 16).fit().jit_batch()

    def run_once():
        last = None
        for s in range(0, N, CHUNK):
            last = featurize(imgs[s : s + CHUNK])
        np.asarray(last[:1, :1])

    run_once()  # warm
    ms, extra = measure(run_once, reps=3)
    emit("imagenet_sift_lcs_fv_featurize", N / (ms / 1e3),
         "examples/sec/chip", extra=extra)


def bench_imagenet_e2e() -> None:
    """North star (END TO END, the BASELINE.json metric): featurize ->
    BlockWeightedLeastSquaresEstimator(4096) fit -> top-5 prediction,
    examples/sec/chip over the full train pass (reference:
    ImageNetSiftLcsFV.scala:82-148 — featurize + weighted BCD solve +
    TopKClassifier(5))."""
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators, TopKClassifier
    from keystone_tpu.parallel.dataset import Dataset

    SIZE, N, C = 256, 512, 100
    CHUNK = 128
    rng = np.random.default_rng(0)
    # the tiling in _fixture_images is cyclic, so base_id is the
    # example index mod the ACTUAL tiling period (np.unique would both
    # miscount under byte-identical fixture images and sort ~400 MB of
    # rows); per-example noise makes every image — and its features —
    # unique within its cluster
    base_imgs, n_bases = _fixture_images(N, SIZE, return_n_base=True)
    assert n_bases <= C, (
        f"fixture tar holds {n_bases} base images > indicator width {C}"
        " — raise C or subsample the bases"
    )
    base_id = np.arange(N) % n_bases
    imgs = jnp.asarray(
        base_imgs + rng.normal(0, 3.0, (N, SIZE, SIZE, 3)).astype(np.float32)
    )
    # labels = base-image identity (VERDICT r3 weak #3): a genuinely
    # learnable signal for one BCD pass — clusters are margin-separable
    # in FV space — while the indicator width stays C=100 so the solver
    # does the full flagship-shape work. (Random labels are unlearnable
    # from ~5 examples/class by one pass, and a feature-derived linear
    # teacher collapses to the ~4 feature clusters; both were measured.)
    y = jnp.asarray(base_id.astype(np.int32))
    featurize = _build_fv_pipeline(rng, 64, 16).fit().jit_batch()
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4096, num_iter=1, lam=1e-3, mixture_weight=0.5,
        convergence_check="off",
    )
    top5 = TopKClassifier(5)

    def feature_pass():
        return jnp.concatenate(
            [featurize(imgs[s : s + CHUNK]) for s in range(0, N, CHUNK)],
            axis=0,
        )

    # featurize-health check on the warm pass, outside the timed
    # region: distinct base images must map to well-separated feature
    # clusters (collapsed/constant features fail this long before they
    # fail the accuracy floor)
    F_warm = np.asarray(feature_pass(), np.float32)
    if n_bases > 1:
        cents = np.stack([
            F_warm[base_id == b].mean(0) for b in range(n_bases)
        ])
        within = float(np.mean([
            np.linalg.norm(F_warm[base_id == b] - cents[b], axis=1).mean()
            for b in range(n_bases)
        ]))
        inter = np.linalg.norm(
            cents[:, None, :] - cents[None, :, :], axis=2
        )
        min_inter = float(inter[~np.eye(n_bases, dtype=bool)].min())
        assert min_inter > 2.0 * within, (
            f"feature clusters collapsed: min inter-centroid "
            f"{min_inter:.3f} vs within-cluster spread {within:.3f}"
        )
    # rank-richness: centroid separation alone is blind to rank
    # collapse (separated collinear centroids would pass). Globally the
    # spectrum is DOMINATED by the ~4-cluster structure (global stable
    # rank ≈ 2 on healthy features — measured), so measure richness on
    # the WITHIN-CLUSTER deviations: per-example noise must excite many
    # feature directions (healthy FV: stable rank ≫ 5; a rank-collapsed
    # featurize gives ~1)
    if n_bases > 1:
        Fw = F_warm - cents[base_id]
    else:
        Fw = F_warm - F_warm.mean(0)
    sv = np.linalg.svd(Fw, compute_uv=False)
    stable_rank = float((sv ** 2).sum() / max(sv[0] ** 2, 1e-30))
    assert stable_rank > 5.0, (
        f"within-cluster feature stable rank {stable_rank:.2f} — "
        "featurize output has collapsed to a low-rank subspace"
    )
    state = {}

    def run_once():
        feats = Dataset.from_array(feature_pass(), n=N)
        labels = ClassLabelIndicators(C).apply_batch(Dataset.from_array(y))
        model = est.fit(feats, labels)
        preds = top5.apply_batch(model.apply_batch(feats))
        state["top5"] = np.asarray(preds.padded()[:N])

    run_once()  # warm the fit/apply programs
    ms, extra = measure(run_once, reps=2)
    yh = np.asarray(y)
    top5_err = float(np.mean([
        yh[i] not in state["top5"][i] for i in range(N)
    ]))
    top1_err = float(np.mean(state["top5"][:, 0] != yh))
    # margin-separable clusters: a real error means the pipeline or
    # solver broke, not that the workload is hard
    assert top1_err < 0.05, f"e2e top-1 train error {top1_err}"
    extra.update(top1_err=round(top1_err, 4), top5_err=round(top5_err, 4))
    emit("imagenet_sift_lcs_fv_end_to_end", N / (ms / 1e3),
         "examples/sec/chip", extra=extra)




def bench_imagenet_e2e_hard(mix_lo: float = 0.30,
                            mix_hi: float = 0.50) -> None:
    """HARD variant of the end-to-end row (VERDICT r4 next #7). Two
    deliberate changes vs the easy row, each fixing a way 0.0 error
    could be vacuous:

    * **Held-out evaluation.** With D=8192 ≫ n, ridge interpolates ANY
      training labels — train error is structurally 0 however hard the
      workload (measured: σ=140 pixel noise still gave 0.000 train
      top-1). Error here is measured on a disjoint validation split
      drawn from the same generator.
    * **Cross-class blending, not iid noise.** Fisher Vectors pool
      thousands of descriptors, so iid pixel noise averages out
      (σ∈{30,80,140} all measured 0.000). Each example instead blends
      its base image with a DIFFERENT base at α ~ U(mix_lo, mix_hi):
      approaching α=0.5 the example is genuinely ambiguous, so even a
      perfect featurize carries an irreducible, α-tunable error.

    The row carries its own negative control — the same solver on a
    collapsed featurize (all-zero features, the real bring-up failure
    mode the e2e centroid guard once caught), whose intercept-only
    ranking sits at ~0.8 val top-1. 'The featurize carries signal' is
    the measured gap between the healthy band and that control. With
    ~5 effective classes inside a 100-wide indicator, top-5 is
    trivially near 0 — top-1 is the banded metric; top-5 is reported.
    """
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators, TopKClassifier
    from keystone_tpu.parallel.dataset import Dataset

    SIZE, C = 256, 100
    N_TRAIN, N_VAL = 512, 256
    N = N_TRAIN + N_VAL
    CHUNK = 128
    rng = np.random.default_rng(1)
    base_imgs, n_bases = _fixture_images(N, SIZE, return_n_base=True)
    base_id = np.arange(N) % n_bases
    partner = (
        base_id + 1 + rng.integers(0, n_bases - 1, N)
    ) % n_bases
    alpha = rng.uniform(mix_lo, mix_hi, N).astype(np.float32)[
        :, None, None, None
    ]
    bases = base_imgs[:n_bases]
    imgs = jnp.asarray(
        (1.0 - alpha) * bases[base_id]
        + alpha * bases[partner]
        + rng.normal(0, 4.0, (N, SIZE, SIZE, 3)).astype(np.float32)
    )
    y = base_id.astype(np.int32)
    featurize = _build_fv_pipeline(rng, 64, 16).fit().jit_batch()
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4096, num_iter=1, lam=1e-3, mixture_weight=0.5,
        convergence_check="off",
    )
    top5 = TopKClassifier(5)
    labels = ClassLabelIndicators(C).apply_batch(
        Dataset.from_array(jnp.asarray(y[:N_TRAIN]))
    )

    def errors(model, F, ys):
        ds = Dataset.from_array(F, n=F.shape[0])
        preds = np.asarray(
            top5.apply_batch(model.apply_batch(ds)).padded()[: F.shape[0]]
        )
        t5 = float(np.mean([ys[i] not in preds[i] for i in range(len(ys))]))
        t1 = float(np.mean(preds[:, 0] != ys))
        return t1, t5

    def fit_and_val_errors(F_all):
        model = est.fit(
            Dataset.from_array(F_all[:N_TRAIN], n=N_TRAIN), labels
        )
        return errors(model, F_all[N_TRAIN:], y[N_TRAIN:])

    def feature_pass():
        return jnp.concatenate(
            [featurize(imgs[s : s + CHUNK]) for s in range(0, N, CHUNK)],
            axis=0,
        )

    state = {}

    def run_once():
        state["errs"] = fit_and_val_errors(feature_pass())

    run_once()  # warm
    ms, m_extra = measure(run_once, reps=2)
    dt = ms / 1e3
    v1, v5 = state["errs"]

    # negative control: collapsed features -> intercept-only ranking
    F_zero = jnp.zeros((N, 2 * 2 * 64 * 16), jnp.float32)
    c1, c5 = fit_and_val_errors(F_zero)

    # calibrated on the fixture images at U(0.30, 0.50) blending (v5e,
    # r5): healthy val top-1 lands meaningfully off 0.0 but far under
    # the collapsed control's ~0.8; a featurize losing its signal
    # drifts toward the control and trips the ceiling
    assert 0.01 <= v1 <= 0.55, (
        f"hard-workload val top-1 {v1:.3f} outside the healthy band "
        f"[0.01, 0.55] — below floor means the blend degenerated to "
        f"separable (raise mix range); above ceiling means the "
        f"featurize lost its signal (control top-1 is {c1:.3f})"
    )
    assert c1 >= 0.7, (
        f"negative control (collapsed features) val top-1 {c1:.3f} "
        "< 0.7 — the control no longer separates broken from healthy"
    )
    assert c1 - v1 >= 0.2, (
        f"healthy ({v1:.3f}) and collapsed ({c1:.3f}) val top-1 are "
        "too close — the row lost its discriminating power"
    )
    m_extra.update(
        val_top1_err=round(v1, 4), val_top5_err=round(v5, 4),
        mix_lo=mix_lo, mix_hi=mix_hi, n_train=N_TRAIN, n_val=N_VAL,
        control_top1_err=round(c1, 4), control_top5_err=round(c5, 4),
    )
    emit("imagenet_sift_lcs_fv_end_to_end_hard", N / dt,
         "examples/sec/chip", extra=m_extra)


IMAGENET_FIXTURE_TAR = (
    "/root/reference/src/test/resources/images/imagenet/n15075141.tar"
)
IMAGENET_FIXTURE_LABELS = (
    "/root/reference/src/test/resources/images/imagenet-test-labels"
)


def _vm_rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def bench_imagenet_stream_input(n_images: int = 100_000) -> None:
    """Out-of-core input pipeline at ImageNet scale (VERDICT r3 missing
    #1): cycle the reference fixture tar to ``n_images`` images through
    the streaming loader (JPEG draft decode at 256², bounded decode
    window) into device batches with a light featurize step, asserting
    FLAT host RSS — an eager load of this stream would be
    n·256²·3·4B ≈ 75 GB at the default 100k."""
    import os

    from keystone_tpu.loaders.streaming import StreamingImageNetLoader
    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.parallel.dataset import Dataset

    if not (
        os.path.exists(IMAGENET_FIXTURE_TAR)
        and os.path.exists(IMAGENET_FIXTURE_LABELS)
    ):
        import sys

        print("fixture tar/labels unavailable; skipping stream-input "
              "bench", file=sys.stderr, flush=True)
        return
    SIZE, BATCH = 256, 256
    # count the fixture tar once, then cycle enough times
    probe = StreamingImageNetLoader(
        IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS
    )
    per_cycle = sum(1 for _ in probe._iter_raw())
    if per_cycle == 0:
        import sys

        print("fixture tar has no labeled members; skipping stream-input "
              "bench", file=sys.stderr, flush=True)
        return
    cycles = -(-n_images // per_cycle)
    loader = StreamingImageNetLoader(
        IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS,
        decode_size=SIZE, cycle=cycles, limit=n_images,
        decode_threads=8,
    )
    scaler, gray = PixelScaler(), GrayScaler()

    @jax.jit
    def light_featurize(imgs_u8):
        # scale -> NTSC grayscale -> per-image stats: enough device work
        # to prove the host pipeline feeds the chip without the row
        # re-measuring SIFT (imagenet_sift_lcs_fv_featurize does that)
        g = gray.apply(scaler.apply(imgs_u8.astype(jnp.float32)))
        return jnp.mean(g.reshape(g.shape[0], -1), axis=1)

    seen = 0
    rss0, peak = None, 0.0
    acc = None
    t0 = time.perf_counter()
    for imgs, labs, n_valid in loader.batches(BATCH):
        # device feed = 64² uint8 thumbnails: this row measures the HOST
        # input pipeline (decode throughput + flat RSS); through the
        # remote-dispatch tunnel (~14 MB/s measured) a full-res f32 feed
        # would add ~96 min of pure upload at 100k images. On local
        # hardware feed the full-resolution batch instead.
        thumb = np.ascontiguousarray(
            imgs[:, ::4, ::4, :]
        ).astype(np.uint8)
        stats = light_featurize(jnp.asarray(thumb))
        acc = stats if acc is None else acc + stats
        seen += n_valid
        if rss0 is None:
            rss0 = _vm_rss_mb()
        elif (seen // BATCH) % 50 == 0:
            peak = max(peak, _vm_rss_mb())
    np.asarray(acc[:1])
    dt = time.perf_counter() - t0
    peak = max(peak, _vm_rss_mb())
    growth = peak - rss0
    assert seen >= n_images, (seen, n_images)
    # The guard: the pipeline must not MATERIALIZE the dataset. Eager
    # load here would be seen·256²·3·4B (~75 GB at 100k). Host-side the
    # pipeline is strictly flat — tests/parallel/test_streaming.py
    # asserts <120 MB growth, and a host-only 100k run oscillates
    # around ~500 MB total RSS. Through the remote-dispatch tunnel,
    # however, the axon client retains upload-related buffers with
    # LARGE run-to-run variance (measured 0.6, 2.3, and 4.3 GB across
    # identical 100k runs) — an environment artifact this row cannot
    # control, so the assertion here is the order-of-magnitude
    # materialization bound (10% of the eager footprint) and the strict
    # host-side bound in the test suite guards the fine-grained leak
    # classes. The measured growth is reported in the row either way.
    eager_mb = seen * SIZE * SIZE * 3 * 4 / 1e6
    # min(… eager/2) keeps the guard meaningful for small --stream-images
    # runs, where a flat 1 GB floor would exceed the eager footprint
    allowance = max(0.10 * eager_mb, min(1000.0, 0.5 * eager_mb))
    assert growth < allowance, (
        f"streaming input pipeline RSS grew {growth:.0f} MB over "
        f"{seen} images (allowance {allowance:.0f} MB; eager would be "
        f"{eager_mb:.0f} MB) — it is materializing"
    )
    emit("imagenet_stream_input", seen / dt, "imgs/sec",
         extra={"images": seen, "rss_growth_mb": round(growth, 1)})


def bench_imagenet_stream_featurize(n_images: int = 1536) -> None:
    """INTEGRATED host→chip path (VERDICT r4 next #1): the streaming
    loader (native libjpeg draft decode) feeding the FULL SIFT+LCS
    Fisher Vector chain through the SAME fused serving engine the
    gateway runs (``StreamingImageLoader.featurized_batches`` over a
    ``compiled()`` flagship featurize — raw uint8 on the H2D wire, cast
    + featurize in one per-bucket XLA program), with decode, upload,
    and compute overlapped through the async dispatch stream.

    Reports the sustained ex/s plus each stage's standalone rate —
    decode (host, imgs/s and imgs/s/core), upload (H2D of uint8
    chunks), compute (device-resident featurize) — and
    ``overlap_efficiency`` = sustained / min(stage rates): ~1.0 means
    the pipeline loses nothing to serialization. Two environments, one
    row:
      * through the remote tunnel (this CI), upload is the narrow stage
        (~70-100 imgs/s at 256² uint8) — the row then proves overlap
        against that bound;
      * on a TPU-VM host (PCIe H2D, many cores), decode or compute is
        the narrow stage, and the assertion tightens to the VERDICT
        criterion: sustained within ~10% of compute-only whenever
        decode+upload capacity exceeds it.
    Host RSS stays bounded — the loader never materializes the stream.
    The stage probes are standalone sync-bounded measurements; their
    composition through an async remote-dispatch stream is approximate
    (deeply pipelined transfers can BEAT the standalone upload probe,
    so overlap_efficiency may exceed 1.0 — measured 1.0-1.6 here). The
    assertion is one-sided: sustained must not fall below 0.8x the
    model; exceeding it only means the model is conservative.
    Reference capability: loaders/ImageLoaderUtils.scala:22-47 decodes
    on executors in parallel while the driver schedules compute."""
    import os

    if not (
        os.path.exists(IMAGENET_FIXTURE_TAR)
        and os.path.exists(IMAGENET_FIXTURE_LABELS)
    ):
        import sys

        print("fixture tar/labels unavailable; skipping stream-featurize "
              "bench", file=sys.stderr, flush=True)
        return
    from keystone_tpu.loaders.streaming import StreamingImageNetLoader

    SIZE, CHUNK = 256, 128
    rng = np.random.default_rng(0)
    # the FIT-path featurize rides the serving engine: the frozen
    # flagship chain compiled() into bucketed programs — identical
    # staging, fusion, and h2d accounting to the gateway's
    # device-featurize lane (one featurize implementation, fit & serve)
    engine = _build_fv_pipeline(rng, 64, 16).fit().compiled(
        buckets=(CHUNK,), aot_store=False
    )

    def feed(u8_chunk):
        # uint8 on the wire (4x less H2D), cast + featurize fused in
        # the engine's bucket program
        return engine.apply(u8_chunk)

    def make_loader(limit, **kw):
        probe = StreamingImageNetLoader(
            IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS
        )
        per_cycle = sum(1 for _ in probe._iter_raw())
        return StreamingImageNetLoader(
            IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS,
            decode_size=SIZE, cycle=-(-limit // per_cycle), limit=limit,
            **kw,
        )

    # -- stage rates (each standalone) ----------------------------------
    n_probe = 4 * CHUNK
    t0 = time.perf_counter()
    chunks = [
        u8 for u8, _, _ in make_loader(n_probe).batches(CHUNK, np.uint8)
    ]
    decode_rate = n_probe / (time.perf_counter() - t0)
    cores = os.cpu_count() or 1

    dev = jax.devices()[0]
    up = jax.device_put(chunks[0], dev)
    np.asarray(up[:1, :1, :1, 0])  # warm
    best_up = float("inf")  # tunnel transfer jitter is large; best-of-2
    for _ in range(2):
        t0 = time.perf_counter()
        for c in chunks:
            up = jax.device_put(c, dev)
        np.asarray(up[:1, :1, :1, 0])
        best_up = min(best_up, time.perf_counter() - t0)
    upload_rate = n_probe / best_up

    resident = jax.device_put(chunks[0], dev)
    np.asarray(feed(resident)[:1, :1])  # warm compile
    t0 = time.perf_counter()
    out = None
    for _ in range(len(chunks)):
        out = feed(resident)
    np.asarray(out[:1, :1])
    compute_rate = n_probe / (time.perf_counter() - t0)

    # -- integrated sustained run (best-of-2: tunnel jitter) ------------
    sustained, growth = 0.0, 0.0
    for _ in range(2):
        seen = 0
        rss0, peak = None, 0.0
        out = None
        t0 = time.perf_counter()
        for out, labs, n_valid in make_loader(n_images).featurized_batches(
            engine, CHUNK
        ):
            # async H2D + async dispatch inside the engine; the next
            # loop iteration decodes while the chip works this chunk
            seen += n_valid
            if rss0 is None:
                rss0 = _vm_rss_mb()
            else:
                peak = max(peak, _vm_rss_mb())
        np.asarray(out[:1, :1])
        dt = time.perf_counter() - t0
        peak = max(peak, _vm_rss_mb())
        assert seen >= n_images, (seen, n_images)
        if seen / dt > sustained:
            sustained = seen / dt
            growth = peak - (rss0 or 0.0)

    bottleneck = min(
        ("decode", decode_rate), ("upload", upload_rate),
        ("compute", compute_rate), key=lambda kv: kv[1],
    )
    # What a perfectly-overlapped pipeline can sustain HERE: compute
    # runs on the chip, but decode and the Python-side upload
    # marshalling run on host cores — with one core they serialize
    # against each other, so the host-side bound is harmonic, not min.
    if cores >= 2:
        host_bound = min(decode_rate, upload_rate)
        floor = 0.8
    else:
        host_bound = 1.0 / (1.0 / decode_rate + 1.0 / upload_rate)
        # single-core remote-tunnel hosts: the upload stage drifts
        # 70-170 imgs/s between the standalone probe and the 3-minute
        # integrated window (measured), so a tight floor flags tunnel
        # weather, not broken overlap; 0.55 still trips on actual
        # serialization regressions (e.g. a per-batch sync)
        floor = 0.55
    expected = min(compute_rate, host_bound)
    efficiency = sustained / expected
    assert efficiency > floor, (
        f"integrated pipeline runs at {sustained:.0f} ex/s but perfect "
        f"overlap would sustain {expected:.0f} (stages: decode "
        f"{decode_rate:.0f}, upload {upload_rate:.0f}, compute "
        f"{compute_rate:.0f}; {cores} host core(s)) — overlap is "
        f"broken (efficiency {efficiency:.2f} <= {floor})"
    )
    if expected == compute_rate:
        # the VERDICT criterion proper: host feeds the chip
        assert sustained > 0.9 * compute_rate, (
            f"decode+upload capacity exceeds compute yet sustained "
            f"{sustained:.0f} < 90% of compute-only {compute_rate:.0f}"
        )
    m = engine.metrics
    emit("imagenet_stream_featurize", sustained, "examples/sec/chip",
         extra={
             "images": seen,
             "decode_rate": round(decode_rate, 1),
             "decode_rate_per_core": round(decode_rate / cores, 1),
             "host_cores": cores,
             "upload_rate": round(upload_rate, 1),
             "compute_rate": round(compute_rate, 1),
             "bottleneck": bottleneck[0],
             "expected_rate": round(expected, 1),
             "overlap_efficiency": round(efficiency, 3),
             "rss_growth_mb": round(growth, 1),
             # the fused engine's own wire accounting: raw uint8
             # pixels per image staged, vs the 4x f32 alternative
             "h2d_bytes_per_image": round(
                 m.h2d_bytes.total / m.examples.total, 1
             ),
             "h2d_reduction_vs_f32": 4.0,
             "engine_compiles": m.compiles.total,
         })


def bench_stream_decode_scaling(n_images: int = 1024) -> None:
    """Decode-pool scaling curve (VERDICT r4 next #6): host-only decode
    imgs/s at decode_processes ∈ {0 (thread pool), 2, 4, ...} up to the
    core count. On a 1-core host the process rows are SKIPPED (emitted
    with skipped=true) — spawn+IPC overhead measures scheduling noise,
    not scaling — so the 'scales with cores' claim becomes a measured
    curve the moment multi-core hardware runs this bench. Thread/process
    output parity is pinned by tests/parallel/test_streaming.py."""
    import os

    if not (
        os.path.exists(IMAGENET_FIXTURE_TAR)
        and os.path.exists(IMAGENET_FIXTURE_LABELS)
    ):
        import sys

        print("fixture tar/labels unavailable; skipping decode-scaling "
              "bench", file=sys.stderr, flush=True)
        return
    from keystone_tpu.loaders.streaming import StreamingImageNetLoader

    SIZE = 256
    probe = StreamingImageNetLoader(
        IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS
    )
    per_cycle = sum(1 for _ in probe._iter_raw())
    cores = os.cpu_count() or 1
    # {0, 2, 4} always appear (skipped rows included, so the curve's
    # shape is visible in every BENCH artifact); larger pools only
    # where the host could actually exercise them
    pools = [0, 2, 4] + [p for p in (8, 16) if p <= cores]
    for procs in pools:
        name = f"stream_decode_procs_{procs}"
        if procs > 0 and (cores < 2 or procs > cores):
            emit(name, None, "imgs/sec", extra={
                "skipped": True,
                "reason": f"host has {cores} core(s); a {procs}-process "
                "decode pool is unmeasurable here",
            })
            continue
        loader = StreamingImageNetLoader(
            IMAGENET_FIXTURE_TAR, IMAGENET_FIXTURE_LABELS,
            decode_size=SIZE, cycle=-(-n_images // per_cycle),
            limit=n_images, decode_processes=procs,
        )
        t0 = time.perf_counter()
        seen = sum(nv for _, _, nv in loader.batches(128, np.uint8))
        dt = time.perf_counter() - t0
        assert seen >= n_images
        emit(name, seen / dt, "imgs/sec",
             extra={"host_cores": cores,
                    "per_core": round(seen / dt / max(procs, 1), 1)})


def _gen_host_blocks(n, d, block, k, seed=0):
    """Host-RAM bf16 feature blocks + labels planted on block 0 (the
    teacher lives entirely in the first block, so a fit's W must
    concentrate there — a correctness signal that needs no full-matrix
    cross-check at scales where none is computable)."""
    import ml_dtypes

    rng = np.random.default_rng(seed)
    blocks = []
    for s in range(0, d, block):
        w = min(block, d - s)
        blocks.append(
            rng.standard_normal((n, w), dtype=np.float32)
            .astype(ml_dtypes.bfloat16)
        )
    W1 = rng.standard_normal((blocks[0].shape[1], k)).astype(np.float32)
    W1 *= 0.1
    # chunked host matmul: Y depends only on block 0
    Y = np.empty((n, k), np.float32)
    step = 65536
    b0 = blocks[0]
    for r in range(0, n, step):
        Y[r : r + step] = b0[r : r + step].astype(np.float32) @ W1
    Y += 0.05 * rng.standard_normal((n, k), dtype=np.float32)
    return blocks, Y, W1


def bench_hostblocks_overlap() -> None:
    """Out-of-aggregate-HBM training (VERDICT r4 next #2): BlockLS on a
    host-RAM-resident feature matrix (Dataset.from_host_blocks), each
    slab double-buffered onto the chip per pass. Reports the fit wall
    time against its two standalone components — transfer-only (all
    slabs device_put + sync) and compute-only (the same fit with X
    device-resident) — and overlap_efficiency =
    max(transfer, compute) / wall: 1.0 means the smaller component is
    fully hidden under the larger. Through the remote tunnel transfer
    dominates by orders of magnitude, so the row chiefly proves compute
    hides under transfer; on PCIe-attached hardware the same row
    becomes compute-bound and proves the reverse. Reference capability:
    BlockLinearMapper.scala:50-73 (cluster-RAM feature cache),
    AutoCacheRule.scala:559-602 (memory-budgeted caching)."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K, BLOCK = 131_072, 2048, 128, 1024
    blocks, Y, _ = _gen_host_blocks(N, D, BLOCK, K)
    gb = sum(b.nbytes for b in blocks) / 2**30
    Yd = Dataset.from_array(jnp.asarray(Y))
    est = BlockLeastSquaresEstimator(block_size=BLOCK, num_iter=1, lam=0.1)

    host_ds = Dataset.from_host_blocks(blocks)
    np.asarray(est.fit(host_ds, Yd).W[:1, :1])  # warm compiles

    # transfer-only: every slab H2D, one sync
    t0 = time.perf_counter()
    last = None
    for b in blocks:
        last = jax.device_put(b)
    np.asarray(last[:1, :1])
    t_transfer = time.perf_counter() - t0

    # compute-only: same fit, X already device-resident
    dev_ds = Dataset.from_array(
        jnp.concatenate([jnp.asarray(b) for b in blocks], axis=1)
    )
    np.asarray(est.fit(dev_ds, Yd).W[:1, :1])  # warm
    t0 = time.perf_counter()
    np.asarray(est.fit(dev_ds, Yd).W[:1, :1])
    t_compute = time.perf_counter() - t0

    t0 = time.perf_counter()
    model = est.fit(host_ds, Yd)
    np.asarray(model.W[:1, :1])
    wall = time.perf_counter() - t0

    efficiency = max(t_transfer, t_compute) / wall
    assert efficiency > 0.7, (
        f"host-blocks fit took {wall:.1f}s but its larger standalone "
        f"component is only {max(t_transfer, t_compute):.1f}s (transfer "
        f"{t_transfer:.1f}, compute {t_compute:.1f}) — H2D/compute "
        f"overlap is broken"
    )
    emit("hostblocks_block_ls_solve", wall * 1e3, "ms", extra={
        "features_gb": round(gb, 2),
        "transfer_only_s": round(t_transfer, 2),
        "compute_only_s": round(t_compute, 2),
        "overlap_efficiency": round(efficiency, 3),
    })


def bench_hostblocks_xl(hbm_gb: float = 16.0) -> None:
    """The ≥2x-HBM proof (opt-in: ``--hostblocks-xl``): fit a feature
    matrix TWICE the chip's HBM from host RAM on the single chip —
    1M x 16384 bf16 = 32 GiB vs v5e-lite 16 GiB — streaming each 2 GiB
    slab through the double-buffered BCD pass. The planted teacher
    lives in block 0, so the learned W must concentrate there: a
    correctness check that costs O(D*K) host math instead of another
    full pass. Not part of the default bench (through this remote
    tunnel the 32 GiB upload alone is ~35 min); run once per round and
    recorded in PERF. Small-scale equivalence with the in-HBM fit is
    pinned by tests/parallel/test_host_blocks.py."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K, BLOCK = 1_048_576, 16_384, 147, 1024
    t0 = time.perf_counter()
    blocks, Y, W1 = _gen_host_blocks(N, D, BLOCK, K)
    gen_s = time.perf_counter() - t0
    gb = sum(b.nbytes for b in blocks) / 2**30
    hbm_multiple = gb / hbm_gb
    assert hbm_multiple >= 2.0, (gb, hbm_gb)
    print(json.dumps({
        "note": "hostblocks_xl generated",
        "features_gib": round(gb, 1),
        "hbm_multiple": round(hbm_multiple, 2),
        "gen_s": round(gen_s, 1),
    }), flush=True)

    est = BlockLeastSquaresEstimator(block_size=BLOCK, num_iter=1, lam=1.0)
    t0 = time.perf_counter()
    model = est.fit(
        Dataset.from_host_blocks(blocks),
        Dataset.from_array(jnp.asarray(Y)),
    )
    W = np.asarray(model.W)
    wall = time.perf_counter() - t0

    assert np.all(np.isfinite(W)), "non-finite model from XL fit"
    w0 = W[: blocks[0].shape[1]]
    cos = float(
        np.sum(w0 * W1)
        / (np.linalg.norm(w0) * np.linalg.norm(W1) + 1e-30)
    )
    off_ratio = float(
        np.linalg.norm(W[blocks[0].shape[1]:])
        / (np.linalg.norm(w0) + 1e-30)
    )
    assert cos > 0.9, f"teacher block not recovered: cos={cos:.3f}"
    assert off_ratio < 0.5, (
        f"weight mass leaked off the teacher block: {off_ratio:.3f}"
    )
    emit("hostblocks_xl_2x_hbm_solve", wall * 1e3, "ms", extra={
        "features_gib": round(gb, 1),
        "hbm_multiple": round(hbm_multiple, 2),
        "effective_h2d_mb_s": round(gb * 1024 / wall, 1),
        "teacher_cos": round(cos, 4),
        "off_block_ratio": round(off_ratio, 4),
    })


def bench_imagenet_real(data_dir: str, labels_path: str,
                        val_dir: str = None, desc_dim: int = 64,
                        vocab: int = 16, num_classes: int = 1000,
                        size: int = 256, batch: int = 128) -> None:
    """REAL-DATA parity mode (VERDICT r3 weak #3): when an ImageNet tar
    directory is mounted, stream it through the full SIFT+LCS Fisher
    Vector pipeline, fit the 4096-block weighted BCD solver, and report
    reference-comparable top-1/top-5 error (train set, plus val when
    ``val_dir`` is given). See README "Real-data parity runbook".

    Run: python bench.py --imagenet-data DIR --imagenet-labels FILE
         [--imagenet-val DIR]

    ``size``/``batch`` exist so the suite can drive this exact code
    path on the 5-image reference fixture tar at CPU-friendly shapes
    (tests/pipelines/test_real_parity_mode.py) — the plumbing is
    exercised every run, so it works the day real ImageNet is mounted.
    """
    from keystone_tpu.loaders.streaming import StreamingImageNetLoader
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators, TopKClassifier
    from keystone_tpu.parallel.dataset import Dataset

    SIZE, BATCH = size, batch
    rng = np.random.default_rng(0)
    # fixed-shape batches -> the whole featurize graph as ONE compiled
    # program (same fast path as the synthetic FV benches)
    featurize = _build_fv_pipeline(rng, desc_dim, vocab).fit().jit_batch()

    def featurize_stream(directory):
        loader = StreamingImageNetLoader(
            directory, labels_path, decode_size=SIZE, decode_threads=8,
        )
        feats, ys = [], []
        for imgs, labs, n_valid in loader.batches(BATCH):
            out = featurize(jnp.asarray(imgs))
            feats.append(out[:n_valid].astype(jnp.bfloat16))
            ys.extend(labs[:n_valid])
        return (
            jnp.concatenate(feats, axis=0),
            jnp.asarray(np.asarray(ys, np.int32)),
        )

    t0 = time.perf_counter()
    X, y = featurize_stream(data_dir)
    n = X.shape[0]
    labels = ClassLabelIndicators(num_classes).apply_batch(
        Dataset.from_array(y)
    )
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4096, num_iter=1, lam=1e-3, mixture_weight=0.5,
        convergence_check="off",
    )
    model = est.fit(Dataset.from_array(X, n=n), labels)
    top5 = TopKClassifier(5)

    def errors(Xs, ys):
        preds = np.asarray(
            top5.apply_batch(
                model.apply_batch(Dataset.from_array(Xs, n=Xs.shape[0]))
            ).padded()[: Xs.shape[0]]
        )
        yh = np.asarray(ys)
        t5 = float(np.mean([yh[i] not in preds[i] for i in range(len(yh))]))
        t1 = float(np.mean(preds[:, 0] != yh))
        return t1, t5

    t1, t5 = errors(X, y)
    dt = time.perf_counter() - t0
    extra = {"train_top1_err": round(t1, 4), "train_top5_err": round(t5, 4),
             "n_train": int(n)}
    if val_dir:
        Xv, yv = featurize_stream(val_dir)
        v1, v5 = errors(Xv, yv)
        extra.update(val_top1_err=round(v1, 4), val_top5_err=round(v5, 4),
                     n_val=int(Xv.shape[0]))
    emit("imagenet_real_end_to_end", n / dt, "examples/sec/chip",
         extra=extra)


def bench_serving() -> None:
    """Serving fast path (serving/engine.py + batching.py) and request
    plane (gateway/): cold-vs-warm dispatch latency on one shape,
    bucketed throughput across every batch size with a compile-count
    ceiling, micro-batched p99, gateway-plane p99 under the same load
    (`serving_gateway_p99`), and the forced live-engine-swap blip with
    zero failures asserted (`serving_swap_blip`) — vs_baseline null
    (the reference published no serving numbers; the wiring exists so
    future rounds ratio against these rows)."""
    from keystone_tpu.serving.bench import run_serving_benches

    run_serving_benches(emit)


def write_markdown(path: str) -> None:
    """Render every emitted row as the README performance table — the
    table is GENERATED from bench output, never hand-edited (VERDICT r3
    weak #4)."""
    lines = [
        "| metric | value | unit | TFLOP/s | device ms | device TFLOP/s"
        " | vs baseline | spread (ms) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in _ROWS:
        if r.get("unit") == "error":
            lines.append(
                f"| {r['metric']} | FAILED | — | — | — | — | — | — |"
            )
            continue
        if r.get("skipped"):
            lines.append(
                f"| {r['metric']} | skipped | — | — | — | — | — | — |"
            )
            continue
        lines.append(
            "| {m} | {v:,.2f} | {u} | {tf} | {dms} | {dtf} | {vs} | {sp} |"
            .format(
                m=r["metric"], v=r["value"], u=r["unit"],
                tf=r.get("tflops", "—") or "—",
                dms=r.get("device_ms", "—"),
                dtf=r.get("tflops_device", "—"),
                vs=r.get("vs_baseline") or "—",
                sp=r.get("spread_ms", "—"),
            )
        )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {path}", flush=True)


def main() -> None:
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--markdown", metavar="PATH",
                    help="also write the rows as a markdown table")
    ap.add_argument("--only", metavar="SUBSTR",
                    help="run only benches whose name contains SUBSTR")
    ap.add_argument("--stream-images", type=int, default=100_000,
                    help="image count for the streaming input row")
    ap.add_argument("--hostblocks-xl", action="store_true",
                    help="run ONLY the 2x-HBM host-blocks fit (slow: "
                    "32 GiB H2D; see bench_hostblocks_xl)")
    ap.add_argument("--amazon-16384", action="store_true",
                    help="run ONLY the Amazon 16384-feature exact "
                    "solve (slow: ~3.5e16-FLOP Gram; recorded in PERF)")
    ap.add_argument("--imagenet-data", metavar="DIR",
                    help="real ImageNet train tar dir -> parity mode")
    ap.add_argument("--imagenet-labels", metavar="FILE",
                    help="WNID->class map for --imagenet-data")
    ap.add_argument("--imagenet-val", metavar="DIR",
                    help="validation tar dir for parity mode")
    ap.add_argument("--desc-dim", type=int, default=64,
                    help="PCA descriptor dim for parity mode")
    ap.add_argument("--vocab", type=int, default=16,
                    help="GMM vocab size for parity mode")
    ap.add_argument("--num-classes", type=int, default=1000,
                    help="class count for parity mode")
    args = ap.parse_args()

    # persistent XLA executable cache: reruns (and the driver's
    # end-of-round run) skip the ~20-40s-per-program remote compiles
    from keystone_tpu.parallel.runtime import setup_compilation_cache

    setup_compilation_cache(
        cache_dir="/tmp/kstpu_jax_cache", min_compile_time_secs=1.0
    )

    if args.hostblocks_xl:
        bench_hostblocks_xl()
        if args.markdown:
            write_markdown(args.markdown)
        return

    if args.amazon_16384:
        bench_amazon_16384()
        if args.markdown:
            write_markdown(args.markdown)
        return

    if args.imagenet_data:
        if not args.imagenet_labels:
            ap.error("--imagenet-data requires --imagenet-labels")
        bench_imagenet_real(
            args.imagenet_data, args.imagenet_labels, args.imagenet_val,
            desc_dim=args.desc_dim, vocab=args.vocab,
            num_classes=args.num_classes,
        )
        if args.markdown:
            write_markdown(args.markdown)
        return

    def bench_stream_input():
        bench_imagenet_stream_input(args.stream_images)

    bench_stream_input.__name__ = "bench_imagenet_stream_input"

    benches = [
        bench_timit,
        bench_timit_lbfgs,
        bench_amazon,
        bench_mnist,
        bench_cifar,
        bench_newsgroups,
        bench_weighted_ls,
        bench_krr,
        bench_imagenet_fv,
        bench_imagenet_e2e,
        bench_imagenet_e2e_hard,
        bench_stream_input,
        bench_imagenet_stream_featurize,
        bench_stream_decode_scaling,
        bench_hostblocks_overlap,
        bench_serving,
    ]
    benches = [
        b for b in benches if not args.only or args.only in b.__name__
    ]
    for b in benches:
        # one attempt + one retry: the remote-compile tunnel occasionally
        # drops a response mid-read; a transient flake must not cost the
        # round every remaining metric
        for attempt in (0, 1):
            try:
                b()
                break
            except Exception as e:
                print(f"{b.__name__} attempt {attempt} failed: {e}",
                      file=sys.stderr, flush=True)
                if attempt == 1:
                    # explicit failure row: a broken bench must be
                    # distinguishable from a not-run bench in the round's
                    # BENCH JSON (ADVICE r3)
                    emit(b.__name__, None, "error",
                         extra={"error": str(e)[:300]})
    if args.markdown:
        write_markdown(args.markdown)


if __name__ == "__main__":
    main()
