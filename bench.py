"""Benchmark: BlockLS solver wall-clock on a TIMIT-shaped problem.

BASELINE.md's closest published number is "TIMIT, Block solver, 1024
features: 33,521 ms" on a 16-node r3.4xlarge cluster
(scripts/solver-comparisons-final.csv:14). The KeystoneML paper's TIMIT
set is ~2.25M train frames with 147 classes; we time one
BlockLeastSquaresEstimator pass over the same (n, d, k) shape on the live
TPU chip(s). Features are generated on device (the baseline row times the
solver, not featurization); stored bf16, Gram math accumulates f32 —
the TPU-native precision discipline.

Prints one JSON line per metric:
  {"metric": ..., "value": ms, "unit": "ms", "vs_baseline": baseline/ours}
vs_baseline > 1 means faster than the reference cluster. The *_amortized
metric isolates solver device-compute from the fixed ~100 ms round-trip
of the tunneled single-chip setup (8 fits queued async, one sync).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_MS = 33_521.0  # scripts/solver-comparisons-final.csv:14
N = 2_251_569  # TIMIT train frames (KeystoneML paper scale)
D = 1024
K = 147
BLOCK = 1024


def main() -> None:
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.parallel.dataset import Dataset

    mesh = mesh_lib.make_mesh()
    with mesh_lib.use_mesh(mesh):
        nshards = mesh_lib.n_data_shards(mesh)
        n = -(-N // nshards) * nshards

        @jax.jit
        def gen(key):
            kx, kw = jax.random.split(key)
            mask = (jnp.arange(n) < N).astype(jnp.float32)[:, None]
            X = jax.random.normal(kx, (n, D), jnp.bfloat16) * mask.astype(
                jnp.bfloat16
            )
            W = jax.random.normal(kw, (D, K), jnp.bfloat16) * 0.1
            Y = jax.lax.dot_general(
                X, W, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) + 0.01 * mask * jax.random.normal(
                jax.random.fold_in(kw, 1), (n, K), jnp.float32
            )
            return X, Y

        X, Y = gen(jax.random.PRNGKey(0))
        X = jax.device_put(X, mesh_lib.data_sharding(mesh))
        Y = jax.device_put(Y, mesh_lib.data_sharding(mesh))
        jax.block_until_ready((X, Y))
        Xd = Dataset.from_array(X, n=N)
        Yd = Dataset.from_array(Y, n=N)

        est = BlockLeastSquaresEstimator(block_size=BLOCK, num_iter=1, lam=0.1)
        # warm-up compile on the same shapes; np.asarray forces real
        # execution (block_until_ready alone doesn't drain the remote
        # dispatch stream on tunneled devices)
        np.asarray(est.fit(Xd, Yd).W)
        t0 = time.perf_counter()
        model = est.fit(Xd, Yd)
        np.asarray(model.W)
        elapsed_ms = (time.perf_counter() - t0) * 1000.0

        # Amortized per-fit device time: the whole fit runs in the async
        # dispatch stream with zero host syncs, so queueing R fits and
        # syncing once isolates solver compute from the fixed ~100 ms
        # host<->device round-trip of the tunneled single-chip setup.
        reps = 8
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            last = est.fit(Xd, Yd)
        np.asarray(last.W)
        amortized_ms = (time.perf_counter() - t0) * 1000.0 / reps

    print(
        json.dumps(
            {
                "metric": "timit_block_ls_1024_solve",
                "value": round(elapsed_ms, 1),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / elapsed_ms, 2),
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "timit_block_ls_1024_solve_amortized",
                "value": round(amortized_ms, 1),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / amortized_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
