"""Benchmarks for the five BASELINE.md tracked configs, on the live TPU.

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": x | null}
vs_baseline > 1 means faster than the reference 16-node r3.4xlarge Spark
cluster; null where the reference published no number for the config
(BASELINE.md: only the TIMIT/Amazon solver rows have published times).

Tracked configs (BASELINE.md "Tracked configs"):
  - TimitPipeline      -> timit_block_ls_1024_solve(+_amortized)
  - MnistRandomFFT     -> mnist_random_fft_featurize_solve
  - RandomPatchCifar   -> random_patch_cifar_featurize imgs/sec + solve
  - NewsgroupsPipeline -> newsgroups_train
  - ImageNetSiftLcsFV  -> imagenet_sift_lcs_fv examples/sec/chip (north
    star: full SIFT+LCS -> PCA -> GMM Fisher Vector featurization)

Timing discipline: np.asarray(...) forces real execution —
block_until_ready alone does not drain the remote dispatch stream on
tunneled devices, and any host sync costs ~100 ms of round-trip latency,
so each metric queues its whole computation and syncs once (the
*_amortized metric additionally amortizes that fixed sync cost away).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

TIMIT_BASELINE_MS = 33_521.0  # scripts/solver-comparisons-final.csv:14
AMAZON_EXACT_BASELINE_MS = 186_149.0  # …csv:2 (Exact, 1024 features)
AMAZON_BEST_BASELINE_MS = 33_704.0  # …csv:4 (LS-LBFGS, their fastest)


def emit(metric: str, value: float, unit: str, vs=None) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 2),
                "unit": unit,
                "vs_baseline": round(vs, 2) if vs else None,
            }
        ),
        flush=True,
    )


def bench_timit() -> None:
    """BlockLS solve on the TIMIT shape: 2.25M frames x 1024 features,
    147 classes, one BCD pass (reference row: 33,521 ms on the cluster)."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.parallel.dataset import Dataset

    N, D, K, BLOCK = 2_251_569, 1024, 147, 1024
    mesh = mesh_lib.make_mesh()
    with mesh_lib.use_mesh(mesh):
        nshards = mesh_lib.n_data_shards(mesh)
        n = -(-N // nshards) * nshards

        @jax.jit
        def gen(key):
            kx, kw = jax.random.split(key)
            mask = (jnp.arange(n) < N).astype(jnp.bfloat16)[:, None]
            X = jax.random.normal(kx, (n, D), jnp.bfloat16) * mask
            W = jax.random.normal(kw, (D, K), jnp.bfloat16) * 0.1
            Y = jax.lax.dot_general(
                X, W, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return X, Y

        X, Y = gen(jax.random.PRNGKey(0))
        X = jax.device_put(X, mesh_lib.data_sharding(mesh))
        Y = jax.device_put(Y, mesh_lib.data_sharding(mesh))
        np.asarray(X[:1, :1])
        Xd = Dataset.from_array(X, n=N)
        Yd = Dataset.from_array(Y, n=N)

        est = BlockLeastSquaresEstimator(block_size=BLOCK, num_iter=1, lam=0.1)
        np.asarray(est.fit(Xd, Yd).W)  # warm compile + force exec
        t0 = time.perf_counter()
        np.asarray(est.fit(Xd, Yd).W)
        single_ms = (time.perf_counter() - t0) * 1e3

        reps = 8
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            last = est.fit(Xd, Yd)
        np.asarray(last.W)
        amortized_ms = (time.perf_counter() - t0) * 1e3 / reps

    emit("timit_block_ls_1024_solve", single_ms, "ms",
         TIMIT_BASELINE_MS / single_ms)
    emit("timit_block_ls_1024_solve_amortized", amortized_ms, "ms",
         TIMIT_BASELINE_MS / amortized_ms)


def bench_amazon() -> None:
    """Amazon reviews solver row at the reference experiment's shape:
    65M examples x 1024 hashed-TF features, ~0.5% dense (nnz=5/row),
    binary labels (scripts/constantEstimator.R:34-36). The ELL one-pass
    normal-equations solver (ops/learning/sparse_ell.py) replaces BOTH
    reference solvers for this least-squares workload, so one measured
    fit compares against the Exact row (186,149 ms) and against their
    fastest solver, LS-LBFGS (33,704 ms)."""
    from keystone_tpu.ops.learning import (
        EllLeastSquaresEstimator, ell_dataset,
    )
    from keystone_tpu.parallel.dataset import Dataset

    N, D, NNZ, K = 65_000_000, 1024, 5, 2

    @jax.jit
    def gen(key):
        ki, kv, kb = jax.random.split(key, 3)
        return (
            jax.random.randint(ki, (N, NNZ), 0, D, jnp.int32),
            jax.random.normal(kv, (N, NNZ), jnp.bfloat16),
            jax.random.normal(kb, (N, K), jnp.bfloat16),
        )

    idx, vals, Y = gen(jax.random.PRNGKey(0))
    ds = ell_dataset(idx, vals)
    labels = Dataset.from_array(Y)
    est = EllLeastSquaresEstimator(d=D, lam=1e-2)

    np.asarray(est.fit(ds, labels).W[0, 0])  # warm
    t0 = time.perf_counter()
    np.asarray(est.fit(ds, labels).W[0, 0])
    ms = (time.perf_counter() - t0) * 1e3
    emit("amazon_ls_1024_solve", ms, "ms", AMAZON_BEST_BASELINE_MS / ms)
    emit("amazon_exact_1024_solve", ms, "ms",
         AMAZON_EXACT_BASELINE_MS / ms)


def bench_mnist() -> None:
    """MnistRandomFFT at MNIST scale (60k x 784, 24 FFT branches -> 24,576
    features) — featurize + one-pass BlockLS, end to end."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.stats import RandomFFTFeatures
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset

    N, D, NUM_FFTS, K = 60_000, 784, 24, 10
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((N, D)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    labels = ClassLabelIndicators(K).apply_batch(Dataset.from_array(y))
    fft_bank = RandomFFTFeatures.create(D, NUM_FFTS, seed=0)

    def featurize(ds):
        out = fft_bank.apply_batch(ds)
        return Dataset.from_array(
            out.padded().astype(jnp.bfloat16), n=ds.n
        )

    est = BlockLeastSquaresEstimator(block_size=4096, num_iter=1, lam=0.1)

    def run_once():
        feats = featurize(Dataset.from_array(X))
        model = est.fit(feats, labels)
        np.asarray(model.W)

    run_once()  # warm
    t0 = time.perf_counter()
    run_once()
    emit("mnist_random_fft_featurize_solve",
         (time.perf_counter() - t0) * 1e3, "ms")


def bench_cifar() -> None:
    """RandomPatchCifar featurization (conv 512 whitened 6x6 patches +
    rectify + pool) throughput over CIFAR train-set-shaped data, and the
    4096-feature BlockLS solve."""
    from keystone_tpu.ops.images import (
        Convolver, ImageVectorizer, Pooler, SymmetricRectifier,
    )
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.util.nodes import ClassLabelIndicators
    from keystone_tpu.parallel.dataset import Dataset

    N, SIZE, F = 10_000, 32, 512
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        rng.standard_normal((N, SIZE, SIZE, 3)).astype(np.float32)
    )
    filters = jnp.asarray(
        rng.standard_normal((F, 6 * 6 * 3)).astype(np.float32)
    )
    feat = (
        Convolver(filters, SIZE, SIZE, 3, normalize_patches=True)
        .and_then(SymmetricRectifier(alpha=0.25))
        .and_then(Pooler(13, 14))
        .and_then(ImageVectorizer())
    )

    CHUNK = 1000  # conv intermediate is (CHUNK, 27, 27, 2F) — HBM-bounded

    def featurize():
        outs = []
        for s in range(0, N, CHUNK):
            ds = Dataset.from_array(imgs[s : s + CHUNK])
            outs.append(feat.apply(ds).get().padded())
        return jnp.concatenate(outs, axis=0)

    out = featurize()  # warm (lazy -> force)
    np.asarray(out[:1, :1])
    t0 = time.perf_counter()
    out = featurize()
    np.asarray(out[:1, :1])
    dt = time.perf_counter() - t0
    emit("random_patch_cifar_featurize", N / dt, "imgs/sec")

    feats = Dataset.from_array(out.astype(jnp.bfloat16), n=N)
    y = jnp.asarray(rng.integers(0, 10, N).astype(np.int32))
    labels = ClassLabelIndicators(10).apply_batch(Dataset.from_array(y))
    est = BlockLeastSquaresEstimator(block_size=4096, num_iter=1, lam=10.0)
    np.asarray(est.fit(feats, labels).W)  # warm
    t0 = time.perf_counter()
    np.asarray(est.fit(feats, labels).W)
    emit("random_patch_cifar_solve", (time.perf_counter() - t0) * 1e3, "ms")


def bench_newsgroups() -> None:
    """NewsgroupsPipeline train path on synthetic 20-class docs:
    tokenize -> 1..2-grams -> TF -> CommonSparseFeatures(10k) ->
    NaiveBayes (host featurization + device solve)."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.pipelines.text.newsgroups import (
        NewsgroupsConfig, build_pipeline,
    )
    from keystone_tpu.parallel.dataset import Dataset

    rng = np.random.default_rng(0)
    vocab = [f"w{i:04d}" for i in range(2000)]
    docs, ys = [], []
    for i in range(2000):
        c = i % 20
        words = rng.choice(vocab[c * 80: c * 80 + 200], size=60)
        docs.append(" ".join(words))
        ys.append(c)
    train = LabeledData(
        data=Dataset.from_items(docs),
        labels=Dataset.from_array(jnp.asarray(np.asarray(ys, np.int32))),
    )
    conf = NewsgroupsConfig(n_grams=2, common_features=10_000)

    def run_once():
        pipe = build_pipeline(train, conf)
        preds = pipe.apply(train.data).get()
        np.asarray(preds.padded()[:1])

    run_once()  # warm
    t0 = time.perf_counter()
    run_once()
    emit("newsgroups_train", (time.perf_counter() - t0) * 1e3, "ms")


def bench_imagenet_fv() -> None:
    """North star: ImageNetSiftLcsFV featurization examples/sec/chip —
    dense multi-scale SIFT + LCS, PCA to 64 dims, 16-component GMM Fisher
    Vectors, Hellinger + L2 normalization, at 256x256 ImageNet-like
    resolution (reference pipeline: ImageNetSiftLcsFV.scala:106-138)."""
    from keystone_tpu.ops.images.fisher_vector import FisherVector
    from keystone_tpu.ops.images.lcs import LCSExtractor
    from keystone_tpu.ops.images.sift import SIFTExtractor
    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.learning import BatchPCATransformer
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.ops.util.nodes import (
        FloatToDouble, MatrixVectorizer, VectorCombiner,
    )
    from keystone_tpu.parallel.dataset import Dataset
    from keystone_tpu.workflow.api import Pipeline

    DESC_DIM, VOCAB, SIZE, N = 64, 16, 256, 512
    CHUNK = 128  # bounds the (chunk, 128, ~13k) descriptor intermediates;
    # the chunk loop keeps the dispatch stream pipelined so the ~100 ms
    # tunnel sync amortizes over all N examples (throughput, not latency)
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        (rng.random((N, SIZE, SIZE, 3)) * 255).astype(np.float32)
    )

    def branch(prefix, in_dim):
        pca = jnp.asarray(
            rng.standard_normal((DESC_DIM, in_dim)).astype(np.float32) * 0.1
        )
        gmm = GaussianMixtureModel(
            jnp.asarray(rng.standard_normal((DESC_DIM, VOCAB)), jnp.float32),
            jnp.ones((DESC_DIM, VOCAB), jnp.float32),
            jnp.ones((VOCAB,), jnp.float32) / VOCAB,
        )
        return (
            prefix
            .and_then(BatchPCATransformer(pca.T))
            .and_then(FisherVector(gmm))
            .and_then(FloatToDouble())
            .and_then(MatrixVectorizer())
            .and_then(NormalizeRows())
            .and_then(SignedHellingerMapper())
            .and_then(NormalizeRows())
        )

    sift = branch(
        PixelScaler().and_then(GrayScaler())
        .and_then(SIFTExtractor(scale_step=1))
        .and_then(SignedHellingerMapper()),
        128,
    )
    lcs = branch(LCSExtractor(4, 16, 6).to_pipeline(), 96)
    pipe = Pipeline.gather([sift, lcs]).and_then(VectorCombiner())

    def run_once():
        last = None
        for s in range(0, N, CHUNK):
            out = pipe.apply(Dataset.from_array(imgs[s : s + CHUNK])).get()
            last = out.padded()
        np.asarray(last[:1, :1])

    run_once()  # warm
    t0 = time.perf_counter()
    run_once()
    dt = time.perf_counter() - t0
    emit("imagenet_sift_lcs_fv_featurize", N / dt, "examples/sec/chip")


def main() -> None:
    bench_timit()
    bench_amazon()
    bench_mnist()
    bench_cifar()
    bench_newsgroups()
    bench_imagenet_fv()


if __name__ == "__main__":
    main()
